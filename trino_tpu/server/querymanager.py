"""Query management: dispatch, lifecycle, results buffering.

Reference: ``dispatcher/DispatchManager.java:61,148`` (createQuery →
queue → execute), ``execution/SqlQueryManager`` (registry/limits),
``execution/QueryStateMachine.java`` (lifecycle + stats), and
``server/protocol/Query.java:117`` (paged result serving).

Observability: each ManagedQuery owns the query's root span (trace id =
query id) and fires QueryCreated/QueryCompleted events exactly once per
query across EVERY terminal path — normal completion, failure,
client cancel, coordinator kill (CLUSTER_OUT_OF_MEMORY), and
resource-group rejection. Interval math uses ``time.monotonic()``;
epoch timestamps survive only in display fields (createTime/endTime).
"""

from __future__ import annotations

import dataclasses
import inspect
import itertools
import secrets
import threading
import time
import traceback
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.engine import Engine, StatementResult
from trino_tpu.obs.trace import get_tracer
from trino_tpu.server.statemachine import (
    QueryState,
    StateMachine,
    new_query_state_machine,
)

_query_counter = itertools.count(1)


def _new_query_id() -> str:
    # reference format: yyyyMMdd_HHmmss_index_coord (QueryIdGenerator)
    ts = time.strftime("%Y%m%d_%H%M%S")
    return f"{ts}_{next(_query_counter):05d}_trino_tpu"


@dataclasses.dataclass
class ErrorInfo:
    """Reference: ``client/.../QueryError.java`` shape."""

    message: str
    error_code: int = 1
    error_name: str = "GENERIC_INTERNAL_ERROR"
    error_type: str = "INTERNAL_ERROR"
    stack: str = ""
    # ft classification: would a retry (different worker / fresh attempt)
    # plausibly succeed? Drives QUERY retry and is surfaced to clients.
    retryable: bool = False

    def to_json(self) -> dict:
        return {
            "message": self.message,
            "errorCode": self.error_code,
            "errorName": self.error_name,
            "errorType": self.error_type,
            "retryable": self.retryable,
            "failureInfo": {"type": self.error_name, "message": self.message,
                            "stack": self.stack.splitlines()},
        }


class ManagedQuery:
    """One query's full lifecycle + buffered results."""

    def __init__(self, sql: str, session: Session, engine: Optional[Engine] = None):
        self.query_id = _new_query_id()
        self.slug = "x" + secrets.token_hex(8)
        self.sql = sql
        self.session = session
        self.state = new_query_state_machine(self.query_id)
        self.result: Optional[StatementResult] = None
        self.error: Optional[ErrorInfo] = None
        self.create_time = time.time()  # epoch: createTime display only
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self._create_mono = time.monotonic()
        self._start_mono_ts: Optional[float] = None
        self._end_mono: Optional[float] = None
        self.last_access = time.monotonic()  # protocol touch; guards history GC
        self._cancelled = threading.Event()
        # set by QueryManager while this query waits un-admitted in a
        # resource-group queue; cancel() invokes it to free the queue slot
        self._admission_abandon: Optional[Any] = None
        # lazy byte-budgeted pager over result.rows (streaming protocol)
        self._pager: Optional["ResultPager"] = None
        self._pager_lock = threading.Lock()
        self.query_attempts = 1  # >1 under retry_policy=QUERY
        self._engine = engine
        self._completed_fired = False
        self._completed_lock = threading.Lock()
        # root span for the whole query (covers queued time); the dispatch
        # thread re-activates it so engine/scheduler spans nest under it
        self.span = get_tracer().start_span(
            "query",
            trace_id=self.query_id,
            attrs={"queryId": self.query_id, "user": session.user},
        )
        # flight recorder (obs/flight.py): crash-safe lifecycle journal.
        # None when flight_dir is unset; every _flight() call is
        # non-blocking (queue put) so cancel()/admission callbacks may
        # journal from loop threads
        from trino_tpu.obs import flight as _flight_mod

        self._flight = _flight_mod.for_session(session)
        self._flight_event(
            "created", query=sql, user=session.user,
            source=getattr(session, "source", None),
        )

    def _flight_event(self, event: str, **payload: Any) -> None:
        if self._flight is not None:
            self._flight.record(self.query_id, event, payload)

    def touch(self) -> None:
        self.last_access = time.monotonic()

    # --- lifecycle --------------------------------------------------------

    def run(self, engine: Engine, release=None) -> None:
        """Execute. ``release`` (the admission-slot release hook) is
        invoked once engine work is done but BEFORE the terminal state
        transition fires client-visible listeners — otherwise a client
        can observe its query complete while the slot still reads as
        running (the caller's finally still covers every early exit)."""
        from trino_tpu.ft.retry import Backoff, RetryPolicy, is_retryable

        if self._cancelled.is_set():
            return
        self.start_time = time.time()
        self._start_mono_ts = time.monotonic()  # queuedMs interval math
        self.state.set(QueryState.PLANNING)
        # retry_policy=QUERY: the whole statement re-runs on a fresh
        # attempt salt (fault_attempt_salt keys the injector's draws, so a
        # deterministic chaos run does not replay the exact same faults on
        # attempt 2). Reference: Trino's QUERY retry policy.
        policy = RetryPolicy.from_session(self.session)
        if policy == RetryPolicy.QUERY:
            try:
                max_attempts = max(1, int(self.session.get("query_retry_attempts")))
            except KeyError:
                max_attempts = 3
        else:
            max_attempts = 1
        backoff = Backoff.from_session(self.session)
        tracer = get_tracer()
        try:
            if self._cancelled.is_set():
                return
            self.state.set(QueryState.RUNNING)
            self._flight_event(
                "running",
                queuedMs=int((self._start_mono_ts - self._create_mono) * 1000),
                maxAttempts=max_attempts,
            )
            attempt = 1
            with tracer.activate(self.span):
                while True:
                    try:
                        if attempt > 1:
                            self.session.properties["fault_attempt_salt"] = attempt
                        self.result = self._call_engine(engine)
                        break
                    except Exception as e:  # noqa: BLE001
                        if (
                            attempt >= max_attempts
                            or self._cancelled.is_set()
                            or not is_retryable(e)
                        ):
                            raise
                        self._flight_event(
                            "retry", attempt=attempt + 1,
                            error=str(e), errorClass=type(e).__name__,
                        )
                        time.sleep(backoff.delay(attempt))
                        attempt += 1
                        self.query_attempts = attempt
            if release is not None:
                release()
            self.state.set(QueryState.FINISHING)
            self.state.set(QueryState.FINISHED)
        except Exception as e:  # noqa: BLE001 — any failure fails the query
            from trino_tpu.errors import classify_error
            from trino_tpu.ft.retry import is_retryable

            code, name, typ = classify_error(e)
            self.error = ErrorInfo(
                str(e), code, name, typ, traceback.format_exc(),
                retryable=is_retryable(e),
            )
            if release is not None:
                release()
            self.state.set(QueryState.FAILED)
        finally:
            self.end_time = time.time()
            self._end_mono = time.monotonic()
            self._fire_completed(engine)

    def _call_engine(self, engine: Engine) -> StatementResult:
        """Invoke the engine, pinning this query's id and taking event
        ownership when the engine supports it (test doubles may not)."""
        try:
            params = inspect.signature(engine.execute_statement).parameters
            extended = "fire_events" in params
        except (TypeError, ValueError):  # builtins / exotic callables
            extended = False
        if extended:
            return engine.execute_statement(
                self.sql, self.session,
                query_id=self.query_id, fire_events=False,
            )
        return engine.execute_statement(self.sql, self.session)

    def _fire_completed(self, engine: Optional[Engine] = None) -> None:
        """Fire QueryCompletedEvent exactly once, on whichever terminal
        path got here first, and close the root span."""
        with self._completed_lock:
            if self._completed_fired:
                return
            self._completed_fired = True
        st = self.state.get()
        end = self.end_time or time.time()
        wall = (self._end_mono or time.monotonic()) - self._create_mono
        self.span.finish(
            status="OK" if st == QueryState.FINISHED else "ERROR",
            state=st.value,
        )
        self._flight_completed(st, wall)
        eng = engine or self._engine
        listeners = getattr(eng, "event_listeners", None)
        if listeners is None:
            return
        from trino_tpu.events import QueryCompletedEvent

        listeners.fire_completed(
            QueryCompletedEvent(
                self.query_id, self.sql, self.session.user,
                self.create_time, end, st.value,
                output_rows=len(self.result.rows) if self.result else 0,
                peak_memory_bytes=(
                    self.result.peak_memory_bytes if self.result else 0
                ),
                error_message=self.error.message if self.error else None,
                wall_seconds=wall,
                error_code=self.error.error_code if self.error else None,
                error_type=self.error.error_type if self.error else None,
            )
        )

    def _flight_completed(self, st: "QueryState", wall_s: float) -> None:
        """Journal the terminal post-mortem record: enough that the flight
        journal ALONE explains how the query ended — state, error
        classification, retry/recovery accounting, queryStats,
        operatorStats, and the span tree (when a sink retained it)."""
        if self._flight is None:
            return
        cluster_stats = self.result.cluster_stats if self.result else {}
        elapsed = (self._end_mono or time.monotonic()) - self._create_mono
        err = self.error.to_json() if self.error else None
        if err is not None:
            # classification only — the full stack would bloat the
            # bounded journal without aiding post-mortem triage
            err.pop("failureInfo", None)
        spans = None
        try:
            for sink in getattr(get_tracer(), "_sinks", []):
                spans_for = getattr(sink, "spans_for", None)
                if spans_for is not None:
                    spans = spans_for(self.query_id)
                    break
        except Exception:  # noqa: BLE001
            spans = None
        self._flight_event(
            "completed",
            state=st.value,
            wallMs=int(wall_s * 1000),
            queryAttempts=self.query_attempts,
            taskRetries=cluster_stats.get("task_retries", 0),
            recoveredTasks=cluster_stats.get("recovered_tasks", 0),
            recoveredTaskLevels=cluster_stats.get("recovered_levels", {}),
            spooledBytes=cluster_stats.get("spooled_bytes", 0),
            queryStats=self._query_stats(elapsed, cluster_stats),
            operatorStats=(
                getattr(self.result, "operator_stats", None)
                if self.result else None
            ),
            error=err,
            spans=spans,
        )

    def cancel(self, message: str = "Query was canceled") -> None:
        self._cancelled.set()
        abandon = self._admission_abandon
        if abandon is not None:
            self._admission_abandon = None
            try:
                abandon()  # free the un-admitted resource-group queue slot
            except Exception:  # noqa: BLE001
                pass
        if self.state.set(QueryState.CANCELED):
            self._flight_event("canceled", message=message)
            self.error = ErrorInfo(message, 1, "USER_CANCELED", "USER_ERROR")
            self.end_time = time.time()
            self._end_mono = time.monotonic()
            self._fire_completed()

    def result_pager(
        self, page_max_bytes: int, max_rows_per_page: int = 4096
    ) -> Optional["ResultPager"]:
        """The query's streaming pager (created lazily, one per query).
        Returns None until the result materializes."""
        if self.result is None:
            return None
        with self._pager_lock:
            if self._pager is None:
                self._pager = ResultPager(
                    self.result.rows, page_max_bytes, max_rows_per_page
                )
            return self._pager

    def kill(self, message: str) -> bool:
        """Administrative kill (cluster memory manager): FAILED with
        CLUSTER_OUT_OF_MEMORY, not user-canceled (reference:
        ``ClusterMemoryManager.java:104`` killQuery)."""
        self._cancelled.set()
        if self.state.set(QueryState.FAILED):
            self._flight_event("killed", message=message)
            self.error = ErrorInfo(
                message, 131081, "CLUSTER_OUT_OF_MEMORY",
                "INSUFFICIENT_RESOURCES",
            )
            self.end_time = time.time()
            self._end_mono = time.monotonic()
            self._fire_completed()
            return True
        return False

    # --- info -------------------------------------------------------------

    def info(self) -> dict:
        st = self.state.get()
        elapsed = (self._end_mono or time.monotonic()) - self._create_mono
        cluster_stats = self.result.cluster_stats if self.result else {}
        return {
            "queryId": self.query_id,
            "state": st.value,
            "query": self.sql,
            "user": self.session.user,
            "elapsedTimeMillis": int(elapsed * 1000),
            "createTime": self.create_time,
            "endTime": self.end_time,
            "peakMemoryBytes": self.result.peak_memory_bytes if self.result else 0,
            "updateType": self.result.update_type if self.result else None,
            # ft counters (trino_tpu/ft): retry policy + attempt accounting
            "retryPolicy": cluster_stats.get(
                "retry_policy",
                self.session.properties.get("retry_policy", "NONE"),
            ),
            "queryAttempts": self.query_attempts,
            "taskRetries": cluster_stats.get("task_retries", 0),
            "taskAttempts": cluster_stats.get("task_attempts", {}),
            # hedged execution: duplicates dispatched for detected
            # stragglers, and how many of them finished first
            "speculativeAttempts": cluster_stats.get("speculative_attempts", 0),
            "speculativeWins": cluster_stats.get("speculative_wins", 0),
            # spooled-exchange recovery (trino_tpu/exchange/spool.py):
            # tasks healed after producer death, by tier (task = spool
            # re-point, lineage = producer re-execution, fused = a whole
            # fused unit re-executed atomically). With worker_execution=
            # fused these ride alongside exchangeStats.fusedFragments:
            # spooledBytes counts unit-boundary pages, recoveredTasks
            # counts healed units — fusion and recovery coexist
            "recoveredTasks": cluster_stats.get("recovered_tasks", 0),
            "recoveredTaskLevels": cluster_stats.get("recovered_levels", {}),
            "spooledBytes": cluster_stats.get("spooled_bytes", 0),
            # per-stage rollup (obs): elapsed + sibling task elapsed
            # p50/p99 — the speculative-execution straggler signal
            "queryStats": self._query_stats(elapsed, cluster_stats),
            # skew-aware exchange counters (shuffle rows/bytes, padding
            # ratio, overflow retries, hot/salted keys, capacity provenance)
            "exchangeStats": self.result.exchange_stats if self.result else None,
            # in-program operator telemetry (exec/fragments.py op!
            # channel): per-site row flow, cluster-merged across workers
            "operatorStats": (
                getattr(self.result, "operator_stats", None)
                if self.result else None
            ),
            # columnar ingest tier (trino_tpu/ingest.py): split decode
            # wall, coalesced H2D bytes, device-table-cache hits/misses —
            # a warm repeat scan shows h2d_bytes == 0
            "ingestStats": self.result.ingest_stats if self.result else None,
            "resultCacheStats": (
                self.result.result_cache_stats if self.result else None
            ),
            # cross-query batching (exec/batching.py): which dispatch this
            # query shared and how long it waited; None when it ran alone
            "batchStats": (
                getattr(self.result, "batch_stats", None)
                if self.result else None
            ),
            # device profiler rollup (obs/profiler.py): per-program XLA
            # flops / peak HBM merged across workers, plus query totals
            "deviceStats": self.result.device_stats if self.result else None,
            # compile-time telemetry (cross-query program cache): a warm
            # run shows traceCount == 0 and programCacheHits > 0
            "compileMs": self.result.compile_ms if self.result else 0.0,
            "traceCount": self.result.trace_count if self.result else 0,
            "programCacheHits": (
                self.result.program_cache_hits if self.result else 0
            ),
            "programCacheMisses": (
                self.result.program_cache_misses if self.result else 0
            ),
            "error": self.error.to_json() if self.error else None,
        }

    def _query_stats(self, elapsed_s: float, cluster_stats: dict) -> dict:
        bs = (getattr(self.result, "batch_stats", None)
              if self.result else None) or {}
        ex = (getattr(self.result, "exchange_stats", None)
              if self.result else None) or {}
        rc = (getattr(self.result, "result_cache_stats", None)
              if self.result else None) or {}
        return {
            "elapsedMs": int(elapsed_s * 1000),
            "queuedMs": int(
                ((self._start_mono() or time.monotonic()) - self._create_mono)
                * 1000
            ),
            # cross-query batching: 0/1/absent-wait when the query ran alone
            "batchedQueries": bs.get("batchedQueries", 0),
            "batchSize": bs.get("batchSize", 1),
            "batchWaitMs": bs.get("batchWaitMs", 0.0),
            # query history (obs/history.py): capacity sites seeded from
            # observed truth, and whether a prior run of this fingerprint
            # informed this one
            "historySeeds": ex.get("history_seeds", 0),
            "historyHits": ex.get("history_hits", 0),
            # semantic result cache (trino_tpu/cache): 1 when this query
            # was served from (or incrementally maintained in) the
            # coordinator result cache
            "resultCacheHit": rc.get("resultCacheHit", 0),
            "resultCacheMaintained": rc.get("incrementalMaintenance", 0),
            # SLO sentinel (obs/slo.py): the regression verdict the
            # engine attached at completion (None = within baseline or
            # sentinel off/cold)
            "regression": (
                getattr(self.result, "regression", None)
                if self.result else None
            ),
            "speculativeAttempts": cluster_stats.get("speculative_attempts", 0),
            "speculativeWins": cluster_stats.get("speculative_wins", 0),
            "recoveredTasks": cluster_stats.get("recovered_tasks", 0),
            "spooledBytes": cluster_stats.get("spooled_bytes", 0),
            "stages": cluster_stats.get("stages", []),
        }

    def _start_mono(self) -> Optional[float]:
        if self._start_mono_ts is not None:
            return self._start_mono_ts
        # legacy fallback (test doubles that set start_time directly):
        # approximate from the epoch delta, clamped non-negative — a
        # wall-clock step during the queue wait can skew this path only
        if self.start_time is None:
            return None
        return self._create_mono + max(0.0, self.start_time - self.create_time)


class ResultPager:
    """Byte-budgeted page server over a query's result rows.

    Reference: ``server/protocol/Query.java`` (targetResultSize paging).
    Pages are cut on demand as the client polls ``nextUri`` — a page ends
    when its JSON-encoded size reaches ``page_max_bytes`` or
    ``max_rows_per_page`` rows, whichever first.  Serving token N acks
    (frees) every buffered page below N, so at most the in-flight page
    plus the just-produced one stay resident: producer backpressure is
    the client's own poll cadence.  Re-requesting the last un-acked token
    is idempotent (HTTP retry safety).
    """

    def __init__(
        self, rows, page_max_bytes: int, max_rows_per_page: int = 4096
    ):
        self._src = iter(rows)
        self.total_rows = len(rows)
        self._budget = max(1, int(page_max_bytes))
        self._max_rows = max(1, int(max_rows_per_page))
        self._pages: dict[int, list] = {}
        self._page_bytes: dict[int, int] = {}
        self._next = 0  # next token to produce
        self._exhausted = False
        self.pages_produced = 0
        self.buffered_bytes = 0
        self.peak_buffered_bytes = 0
        self._lock = threading.Lock()

    def page(self, token: int) -> tuple[Optional[list], bool]:
        """Rows for ``token`` (None when past the end) plus whether more
        pages may follow."""
        with self._lock:
            self._ack_below_locked(token)
            while token >= self._next and not self._exhausted:
                self._produce_locked()
            self._ack_below_locked(token)
            rows = self._pages.get(token)
            if rows is None:
                return None, False
            more = (token + 1 < self._next) or not self._exhausted
            return rows, more

    def _ack_below_locked(self, token: int) -> None:
        for t in [t for t in self._pages if t < token]:
            self.buffered_bytes -= self._page_bytes.pop(t)
            del self._pages[t]

    def _produce_locked(self) -> None:
        import json

        rows: list = []
        nbytes = 2  # brackets
        for row in self._src:
            try:
                enc = len(json.dumps(row, default=str))
            except (TypeError, ValueError):
                enc = 64
            rows.append(row)
            nbytes += enc + 2
            if nbytes >= self._budget or len(rows) >= self._max_rows:
                break
        else:
            self._exhausted = True
        if not rows:
            self._exhausted = True
            return
        self._pages[self._next] = rows
        self._page_bytes[self._next] = nbytes
        self._next += 1
        self.pages_produced += 1
        self.buffered_bytes += nbytes
        self.peak_buffered_bytes = max(
            self.peak_buffered_bytes, self.buffered_bytes
        )


class _DispatchPool:
    """Bounded daemon-thread pool for ADMITTED queries.

    concurrent.futures.ThreadPoolExecutor keeps non-daemon workers that
    pin interpreter exit, so: lazily-spawned daemon threads parked on a
    queue, sentinel shutdown. Only admitted work lands here — admission
    waits live in the resource-group waiter queue, so queued queries
    cost a waiter object each, never a stack.
    """

    def __init__(self, max_workers: int, name: str = "dispatch"):
        import queue

        self._q: "queue.Queue" = queue.Queue()
        self._max = max(1, max_workers)
        self._name = name
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._lock = threading.Lock()
        self._shutdown = False

    def submit(self, fn, *args) -> None:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("dispatch pool is shut down")
            # put_nowait: the queue is unbounded, so this never blocks —
            # and the loop thread submits here, so it must never be able to
            self._q.put_nowait((fn, args))
            if self._idle == 0 and len(self._threads) < self._max:
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"{self._name}-{len(self._threads)}",
                )
                self._threads.append(t)
                t.start()

    def _worker(self) -> None:
        # pool workers block on the queue; running one on an event-loop
        # thread would wedge the reactor
        from trino_tpu.server.eventloop import assert_not_loop_thread

        assert_not_loop_thread("_DispatchPool worker")
        while True:
            with self._lock:
                self._idle += 1
            item = self._q.get()
            with self._lock:
                self._idle -= 1
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — work items own their errors
                pass

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            threads = list(self._threads)
        for _ in threads:
            self._q.put(None)


class QueryManager:
    """Registry + dispatch (DispatchManager + SqlQueryManager).

    Two admission styles:

    - ``resource_groups=`` (the server's path): event-driven. create_query
      submits to the resource-group waiter queue and returns; once a slot
      frees, the query runs on a bounded daemon pool. No thread is parked
      while a query is QUEUED, so queued depth is bounded by the groups'
      ``max_queued`` — not by dispatch threads.
    - ``admit=``/``complete=`` hooks (legacy; test doubles): dedicated
      thread per query, because the hook may BLOCK in admit and must not
      occupy pool workers.
    """

    def __init__(
        self,
        engine: Engine,
        max_concurrent: int = 4,
        admit=None,
        complete=None,
        resource_groups=None,
    ):
        self.engine = engine
        self._queries: dict[str, ManagedQuery] = {}
        self._lock = threading.Lock()
        self._admit = admit  # (query) -> token; may block (queue) or raise
        self._complete = complete  # (query, token) -> None
        self.resource_groups = resource_groups
        # pool at least as wide as a full batch: K batchmates each hold a
        # worker while parked on the batch collector's per-member events
        self._pool = _DispatchPool(max(max_concurrent, 16))
        self.max_history = 100
        self._shutdown = False

    def create_query(self, sql: str, session: Session) -> ManagedQuery:
        q = ManagedQuery(sql, session, engine=self.engine)
        try:
            # session-settable retained-history bound (coordinator memory
            # under sustained traffic); the hardcoded-100 default lives
            # in config.Session.DEFAULTS now
            self.max_history = int(session.get("query_manager_max_history"))
        except (KeyError, TypeError, ValueError):
            pass
        with self._lock:
            if self._shutdown:
                raise RuntimeError("query manager is shut down")
            self._queries[q.query_id] = q
            self._gc_locked()
        listeners = getattr(self.engine, "event_listeners", None)
        if listeners is not None:
            from trino_tpu.events import QueryCreatedEvent

            listeners.fire_created(
                QueryCreatedEvent(
                    q.query_id, sql, session.user, q.create_time
                )
            )
        # semantic result-cache fast path: a pure hit consumes no
        # execution slot, so it bypasses admission queueing entirely (ACL
        # generation + per-user checks still run inside the probe).
        # Maintenance is deliberately disallowed here — delta merges
        # execute scans and belong on the dispatch pool via the admitted
        # path, which then refreshes or overwrites the entry.
        if self._try_result_cache(q):
            return q
        if self.resource_groups is not None and self._admit is None:
            self._submit_admission(q)
        else:
            threading.Thread(
                target=self._dispatch, args=(q,), daemon=True
            ).start()
        return q

    def _try_result_cache(self, q: ManagedQuery) -> bool:
        """Complete ``q`` from the result cache; False -> normal dispatch."""
        probe = getattr(self.engine, "try_cached_result", None)
        if probe is None:
            return False
        try:
            res = probe(q.sql, q.session, allow_maintenance=False)
        except Exception:  # noqa: BLE001 — the probe must never fail a query
            return False
        if res is None:
            return False
        q.start_time = time.time()
        q._start_mono_ts = time.monotonic()
        q.state.set(QueryState.PLANNING)
        q.state.set(QueryState.RUNNING)
        q.result = res
        q.state.set(QueryState.FINISHING)
        q.state.set(QueryState.FINISHED)
        q.end_time = time.time()
        q._end_mono = time.monotonic()
        q._fire_completed(self.engine)
        return True

    # --- event-driven admission (resource_groups path) --------------------

    def _submit_admission(self, q: ManagedQuery) -> None:
        def ready(group, err) -> None:
            # fires on whichever thread freed the slot (or reaped the
            # timeout) — hand off immediately, never execute inline
            q._admission_abandon = None
            if err is not None:
                self._reject(q, err)
                return
            q._flight_event(
                "admitted", group=getattr(group, "name", None), queued=True
            )
            try:
                self._pool.submit(self._run_admitted, q, group)
            except RuntimeError:  # pool shut down: give the slot back
                self.resource_groups.finish(group)

        try:
            # history HBM gate: a fingerprint whose OBSERVED peak HBM
            # cannot fit the device at all hard-rejects here (classified
            # EXCEEDED_MEMORY_LIMIT) instead of failing at compile; one
            # that fits the device but not the CURRENT headroom rides the
            # hint into the waiter queue and waits for memory to free
            peak = self._history_hbm_gate(q)
            group, admitted = self.resource_groups.submit(
                q.session.user, q.session.source, ready,
                peak_hbm_hint=peak,
            )
        except TypeError:
            # resource-group doubles without the hint kwarg
            try:
                group, admitted = self.resource_groups.submit(
                    q.session.user, q.session.source, ready
                )
            except Exception as e:  # noqa: BLE001
                self._reject(q, e)
                return
        except Exception as e:  # noqa: BLE001 — queue full / no selector /
            # over-HBM fingerprint
            self._reject(q, e)
            return
        if admitted:
            q._flight_event(
                "admitted", group=getattr(group, "name", None), queued=False
            )
            self._pool.submit(self._run_admitted, q, group)
        else:
            q._flight_event(
                "queued", group=getattr(group, "name", None)
            )
            # let cancel() free the queue slot if the client abandons the
            # query before a slot opens (resource-group doubles may lack
            # abandon(); getattr keeps them working)
            abandon_fn = getattr(self.resource_groups, "abandon", None)
            if abandon_fn is not None:
                q._admission_abandon = lambda: abandon_fn(group, ready)

    def _history_hbm_gate(self, q: ManagedQuery) -> int:
        """Observed peak-HBM for this query's fingerprint, as an admission
        hint (bytes; 0 = unknown). Raises HistoryHbmRejected when the
        observed footprint exceeds the device limit outright — waiting
        cannot help a program that never fits. Best-effort: any gate
        failure admits (history must never wedge admission)."""
        try:
            hist = self.engine.history_store(q.session)
            if hist is None:
                return 0
            fp, _ = self.engine.fingerprint(q.sql, q.session)
            if fp is None:
                return 0
            ent = hist.get(fp, touch=False)
            if ent is None:
                return 0
            peak = int(ent.get("peak_hbm_bytes", 0) or 0)
            if peak <= 0:
                return 0
            from trino_tpu.ingest import device_hbm_limit
            from trino_tpu.obs.history import HistoryHbmRejected

            limit = device_hbm_limit()
            if limit and peak > 0.9 * limit:
                raise HistoryHbmRejected(fp, peak, limit)
            return peak
        except Exception as e:  # noqa: BLE001
            from trino_tpu.obs.history import HistoryHbmRejected

            if isinstance(e, HistoryHbmRejected):
                raise
            return 0

    def _run_admitted(self, q: ManagedQuery, group) -> None:
        released = threading.Event()

        def release() -> None:
            if not released.is_set():
                released.set()
                self.resource_groups.finish(group)

        try:
            if q.state.get() == QueryState.QUEUED:
                q.run(self.engine, release=release)
        finally:
            release()

    def _reject(self, q: ManagedQuery, e: Exception) -> None:
        from trino_tpu.errors import classify_error

        code, name, typ = classify_error(e)
        if name == "GENERIC_INTERNAL_ERROR":
            # legacy admission failures (queue full, no selector) keep
            # their QUERY_REJECTED surface; classified errors — the
            # history HBM gate's EXCEEDED_MEMORY_LIMIT — pass through
            code, name, typ = 3, "QUERY_REJECTED", "USER_ERROR"
        q._flight_event(
            "rejected", error=str(e), errorName=name, errorType=typ
        )
        q.error = ErrorInfo(str(e), code, name, typ)
        q.state.set(QueryState.FAILED)
        q.end_time = time.time()
        q._end_mono = time.monotonic()
        q._fire_completed(self.engine)

    # --- legacy blocking admission (admit=/complete= hooks) ----------------

    def _dispatch(self, q: ManagedQuery) -> None:
        token = None
        admitted = False
        try:
            if self._admit is not None:
                token = self._admit(q)  # blocks while queued; raises on reject
                admitted = True
            if q.state.get() == QueryState.QUEUED:
                q.run(self.engine)
        except Exception as e:  # noqa: BLE001
            self._reject(q, e)
        finally:
            if admitted and self._complete is not None:
                self._complete(q, token)

    def get(self, query_id: str) -> Optional[ManagedQuery]:
        with self._lock:
            return self._queries.get(query_id)

    def queries(self) -> list[ManagedQuery]:
        with self._lock:
            return list(self._queries.values())

    def state_counts(self) -> dict[str, int]:
        """``system.runtime.queries``-style breakdown: live query count
        per state (QUEUED/RUNNING/FINISHED/…) for /v1/status."""
        out: dict[str, int] = {}
        with self._lock:
            for q in self._queries.values():
                st = q.state.get().value
                out[st] = out.get(st, 0) + 1
        return out

    def cancel(self, query_id: str) -> bool:
        q = self.get(query_id)
        if q is None:
            return False
        q.cancel()
        return True

    def expire_abandoned(self, client_timeout_s: float) -> list[str]:
        """Cancel non-terminal queries whose ``nextUri`` went unpolled for
        ``client_timeout_s`` (abandoned dashboards must not pin resource
        groups). Returns the canceled query ids.

        Reference: Trino ``query.client.timeout`` in SqlQueryManager's
        ``enforceTimeouts``.
        """
        if client_timeout_s <= 0:
            return []
        now = time.monotonic()
        victims = [
            q for q in self.queries()
            if not q.state.is_terminal()
            and now - q.last_access > client_timeout_s
        ]
        out: list[str] = []
        for q in victims:
            q.cancel(
                "Query abandoned: no client poll within "
                f"{client_timeout_s:g}s"
            )
            out.append(q.query_id)
        if out:
            try:
                from trino_tpu.obs.metrics import get_registry

                get_registry().counter(
                    "trino_tpu_queries_abandoned_total"
                ).inc(len(out))
            except Exception:  # noqa: BLE001
                pass
        return out

    def kill(self, query_id: str, message: str) -> bool:
        q = self.get(query_id)
        if q is None:
            return False
        return q.kill(message)

    def _gc_locked(self) -> None:
        try:
            from trino_tpu.obs.metrics import get_registry

            get_registry().gauge("trino_tpu_query_history_retained").set(
                len(self._queries)
            )
        except Exception:  # noqa: BLE001
            pass
        if len(self._queries) <= self.max_history:
            return
        # evict least-recently-ACCESSED terminal queries only: a client may
        # still be paging a finished query's buffered results
        now = time.monotonic()
        done = [
            q
            for q in self._queries.values()
            if q.state.is_terminal() and now - q.last_access > 5.0
        ]
        done.sort(key=lambda q: q.last_access)
        for q in done[: len(self._queries) - self.max_history]:
            self._queries.pop(q.query_id, None)

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._shutdown = True
        self._pool.shutdown()
