"""Query management: dispatch, lifecycle, results buffering.

Reference: ``dispatcher/DispatchManager.java:61,148`` (createQuery →
queue → execute), ``execution/SqlQueryManager`` (registry/limits),
``execution/QueryStateMachine.java`` (lifecycle + stats), and
``server/protocol/Query.java:117`` (paged result serving).
"""

from __future__ import annotations

import dataclasses
import itertools
import secrets
import threading
import time
import traceback
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.engine import Engine, StatementResult
from trino_tpu.server.statemachine import (
    QueryState,
    StateMachine,
    new_query_state_machine,
)

_query_counter = itertools.count(1)


def _new_query_id() -> str:
    # reference format: yyyyMMdd_HHmmss_index_coord (QueryIdGenerator)
    ts = time.strftime("%Y%m%d_%H%M%S")
    return f"{ts}_{next(_query_counter):05d}_trino_tpu"


@dataclasses.dataclass
class ErrorInfo:
    """Reference: ``client/.../QueryError.java`` shape."""

    message: str
    error_code: int = 1
    error_name: str = "GENERIC_INTERNAL_ERROR"
    error_type: str = "INTERNAL_ERROR"
    stack: str = ""
    # ft classification: would a retry (different worker / fresh attempt)
    # plausibly succeed? Drives QUERY retry and is surfaced to clients.
    retryable: bool = False

    def to_json(self) -> dict:
        return {
            "message": self.message,
            "errorCode": self.error_code,
            "errorName": self.error_name,
            "errorType": self.error_type,
            "retryable": self.retryable,
            "failureInfo": {"type": self.error_name, "message": self.message,
                            "stack": self.stack.splitlines()},
        }


class ManagedQuery:
    """One query's full lifecycle + buffered results."""

    def __init__(self, sql: str, session: Session):
        self.query_id = _new_query_id()
        self.slug = "x" + secrets.token_hex(8)
        self.sql = sql
        self.session = session
        self.state = new_query_state_machine(self.query_id)
        self.result: Optional[StatementResult] = None
        self.error: Optional[ErrorInfo] = None
        self.create_time = time.time()
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self.last_access = time.time()  # protocol touch; guards history GC
        self._cancelled = threading.Event()
        self.query_attempts = 1  # >1 under retry_policy=QUERY

    def touch(self) -> None:
        self.last_access = time.time()

    # --- lifecycle --------------------------------------------------------

    def run(self, engine: Engine) -> None:
        from trino_tpu.ft.retry import Backoff, RetryPolicy, is_retryable

        if self._cancelled.is_set():
            return
        self.start_time = time.time()
        self.state.set(QueryState.PLANNING)
        # retry_policy=QUERY: the whole statement re-runs on a fresh
        # attempt salt (fault_attempt_salt keys the injector's draws, so a
        # deterministic chaos run does not replay the exact same faults on
        # attempt 2). Reference: Trino's QUERY retry policy.
        policy = RetryPolicy.from_session(self.session)
        if policy == RetryPolicy.QUERY:
            try:
                max_attempts = max(1, int(self.session.get("query_retry_attempts")))
            except KeyError:
                max_attempts = 3
        else:
            max_attempts = 1
        backoff = Backoff.from_session(self.session)
        try:
            if self._cancelled.is_set():
                return
            self.state.set(QueryState.RUNNING)
            attempt = 1
            while True:
                try:
                    if attempt > 1:
                        self.session.properties["fault_attempt_salt"] = attempt
                    self.result = engine.execute_statement(self.sql, self.session)
                    break
                except Exception as e:  # noqa: BLE001
                    if (
                        attempt >= max_attempts
                        or self._cancelled.is_set()
                        or not is_retryable(e)
                    ):
                        raise
                    time.sleep(backoff.delay(attempt))
                    attempt += 1
                    self.query_attempts = attempt
            self.state.set(QueryState.FINISHING)
            self.state.set(QueryState.FINISHED)
        except Exception as e:  # noqa: BLE001 — any failure fails the query
            from trino_tpu.analyzer import SemanticError
            from trino_tpu.memory import ExceededMemoryLimitError
            from trino_tpu.planner.sanity import PlanValidationError
            from trino_tpu.sql.lexer import SqlSyntaxError

            if isinstance(e, SqlSyntaxError):
                code, name, typ = 1, "SYNTAX_ERROR", "USER_ERROR"
            elif isinstance(e, SemanticError):
                code, name, typ = 2, "SEMANTIC_ERROR", "USER_ERROR"
            elif isinstance(e, PlanValidationError):
                # a sanity checker rejected the plan: an engine bug, not a
                # user error — name the checker in the /v1/query error
                code, name, typ = 65537, "PLAN_VALIDATION_ERROR", "INTERNAL_ERROR"
            elif isinstance(e, ExceededMemoryLimitError):
                code, name, typ = 131075, "EXCEEDED_MEMORY_LIMIT", "INSUFFICIENT_RESOURCES"
            elif isinstance(e, KeyError):
                code, name, typ = 2, "SEMANTIC_ERROR", "USER_ERROR"
            else:
                code, name, typ = 65536, "GENERIC_INTERNAL_ERROR", "INTERNAL_ERROR"
            self.error = ErrorInfo(
                str(e), code, name, typ, traceback.format_exc(),
                retryable=is_retryable(e),
            )
            self.state.set(QueryState.FAILED)
        finally:
            self.end_time = time.time()

    def cancel(self) -> None:
        self._cancelled.set()
        if self.state.set(QueryState.CANCELED):
            self.error = ErrorInfo("Query was canceled", 1, "USER_CANCELED", "USER_ERROR")
            self.end_time = time.time()

    def kill(self, message: str) -> bool:
        """Administrative kill (cluster memory manager): FAILED with
        CLUSTER_OUT_OF_MEMORY, not user-canceled (reference:
        ``ClusterMemoryManager.java:104`` killQuery)."""
        self._cancelled.set()
        if self.state.set(QueryState.FAILED):
            self.error = ErrorInfo(
                message, 131081, "CLUSTER_OUT_OF_MEMORY",
                "INSUFFICIENT_RESOURCES",
            )
            self.end_time = time.time()
            return True
        return False

    # --- info -------------------------------------------------------------

    def info(self) -> dict:
        st = self.state.get()
        elapsed = (self.end_time or time.time()) - self.create_time
        cluster_stats = self.result.cluster_stats if self.result else {}
        return {
            "queryId": self.query_id,
            "state": st.value,
            "query": self.sql,
            "user": self.session.user,
            "elapsedTimeMillis": int(elapsed * 1000),
            "createTime": self.create_time,
            "endTime": self.end_time,
            "peakMemoryBytes": self.result.peak_memory_bytes if self.result else 0,
            "updateType": self.result.update_type if self.result else None,
            # ft counters (trino_tpu/ft): retry policy + attempt accounting
            "retryPolicy": cluster_stats.get(
                "retry_policy",
                self.session.properties.get("retry_policy", "NONE"),
            ),
            "queryAttempts": self.query_attempts,
            "taskRetries": cluster_stats.get("task_retries", 0),
            "taskAttempts": cluster_stats.get("task_attempts", {}),
            # skew-aware exchange counters (shuffle rows/bytes, padding
            # ratio, overflow retries, hot/salted keys, capacity provenance)
            "exchangeStats": self.result.exchange_stats if self.result else None,
            # compile-time telemetry (cross-query program cache): a warm
            # run shows traceCount == 0 and programCacheHits > 0
            "compileMs": self.result.compile_ms if self.result else 0.0,
            "traceCount": self.result.trace_count if self.result else 0,
            "programCacheHits": (
                self.result.program_cache_hits if self.result else 0
            ),
            "programCacheMisses": (
                self.result.program_cache_misses if self.result else 0
            ),
            "error": self.error.to_json() if self.error else None,
        }


class QueryManager:
    """Registry + dispatch pool (DispatchManager + SqlQueryManager).

    ``admit`` is the resource-group hook: called before execution starts;
    it may delay (queue) the query.
    """

    def __init__(
        self,
        engine: Engine,
        max_concurrent: int = 4,
        admit=None,
        complete=None,
    ):
        self.engine = engine
        self._queries: dict[str, ManagedQuery] = {}
        self._lock = threading.Lock()
        # dedicated thread per query: admission may BLOCK (queued state), so
        # a bounded pool would let waiters exhaust dispatch slots and bypass
        # the resource groups' own max_queued caps. Execution concurrency is
        # bounded by resource-group admission (max_concurrent is advisory
        # for the default permissive group installed by the server).
        self._admit = admit  # (query) -> token; may block (queue) or raise
        self._complete = complete  # (query, token) -> None
        self.max_history = 100
        self._shutdown = False

    def create_query(self, sql: str, session: Session) -> ManagedQuery:
        q = ManagedQuery(sql, session)
        with self._lock:
            if self._shutdown:
                raise RuntimeError("query manager is shut down")
            self._queries[q.query_id] = q
            self._gc_locked()
        threading.Thread(target=self._dispatch, args=(q,), daemon=True).start()
        return q

    def _dispatch(self, q: ManagedQuery) -> None:
        token = None
        admitted = False
        try:
            if self._admit is not None:
                token = self._admit(q)  # blocks while queued; raises on reject
                admitted = True
            if q.state.get() == QueryState.QUEUED:
                q.run(self.engine)
        except Exception as e:  # noqa: BLE001
            q.error = ErrorInfo(str(e), 3, "QUERY_REJECTED", "USER_ERROR")
            q.state.set(QueryState.FAILED)
            q.end_time = time.time()
        finally:
            if admitted and self._complete is not None:
                self._complete(q, token)

    def get(self, query_id: str) -> Optional[ManagedQuery]:
        with self._lock:
            return self._queries.get(query_id)

    def queries(self) -> list[ManagedQuery]:
        with self._lock:
            return list(self._queries.values())

    def cancel(self, query_id: str) -> bool:
        q = self.get(query_id)
        if q is None:
            return False
        q.cancel()
        return True

    def kill(self, query_id: str, message: str) -> bool:
        q = self.get(query_id)
        if q is None:
            return False
        return q.kill(message)

    def _gc_locked(self) -> None:
        if len(self._queries) <= self.max_history:
            return
        # evict least-recently-ACCESSED terminal queries only: a client may
        # still be paging a finished query's buffered results
        now = time.time()
        done = [
            q
            for q in self._queries.values()
            if q.state.is_terminal() and now - q.last_access > 5.0
        ]
        done.sort(key=lambda q: q.last_access)
        for q in done[: len(self._queries) - self.max_history]:
            self._queries.pop(q.query_id, None)

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._shutdown = True
