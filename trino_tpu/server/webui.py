"""Web UI: cluster overview page.

Reference: the coordinator web UI (``core/trino-main/src/main/resources/webapp/``
React app + ``server/ui/ClusterStatsResource.java``). A single self-refreshing
page served at ``/ui`` over the existing JSON endpoints — no build step,
no external assets.
"""

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>trino-tpu</title>
<style>
  body { font-family: ui-monospace, Menlo, monospace; margin: 2rem;
         background: #16161d; color: #e6e6ef; }
  h1 { font-size: 1.2rem; } h1 span { color: #7aa2f7; }
  .tiles { display: flex; gap: 1rem; flex-wrap: wrap; margin: 1rem 0; }
  .tile { background: #1f1f2b; padding: .8rem 1.2rem; border-radius: 8px; }
  .tile .v { font-size: 1.6rem; color: #9ece6a; }
  .tile .l { font-size: .75rem; color: #9aa0b0; }
  table { border-collapse: collapse; width: 100%; margin-top: 1rem; }
  th, td { text-align: left; padding: .35rem .6rem; font-size: .8rem;
           border-bottom: 1px solid #2a2a38; }
  th { color: #9aa0b0; font-weight: normal; }
  .FINISHED { color: #9ece6a; } .FAILED { color: #f7768e; }
  .RUNNING, .QUEUED, .PLANNING { color: #e0af68; }
  td.q { max-width: 40rem; overflow: hidden; text-overflow: ellipsis;
         white-space: nowrap; }
</style>
</head>
<body>
<h1><span>trino-tpu</span> cluster overview</h1>
<div class="tiles">
  <div class="tile"><div class="v" id="queries">-</div><div class="l">queries tracked</div></div>
  <div class="tile"><div class="v" id="running">-</div><div class="l">running</div></div>
  <div class="tile"><div class="v" id="mem">-</div><div class="l">HBM pool reserved</div></div>
  <div class="tile"><div class="v" id="state">-</div><div class="l">node state</div></div>
</div>
<table id="qtable">
  <tr><th>query id</th><th>state</th><th>user</th><th>elapsed</th><th>query</th></tr>
</table>
<script>
async function refresh() {
  const st = await (await fetch('/v1/status')).json();
  const qs = await (await fetch('/v1/query')).json();
  document.getElementById('queries').textContent = qs.length;
  document.getElementById('running').textContent =
      qs.filter(q => !['FINISHED','FAILED','CANCELED'].includes(q.state)).length;
  const mb = st.memoryInfo.reservedBytes / (1024 * 1024);
  document.getElementById('mem').textContent = mb.toFixed(1) + ' MB';
  document.getElementById('state').textContent = st.state;
  const esc = s => String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;')
      .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
  const stateClass = s => ['FINISHED','FAILED','RUNNING','QUEUED','PLANNING']
      .includes(s) ? s : '';
  const rows = qs.sort((a, b) => b.createTime - a.createTime).slice(0, 50).map(q =>
    `<tr><td>${esc(q.queryId)}</td><td class="${stateClass(q.state)}">${esc(q.state)}</td>` +
    `<td>${esc(q.user)}</td><td>${esc(q.elapsedTimeMillis)} ms</td>` +
    `<td class="q">${esc(q.query)}</td></tr>`).join('');
  document.getElementById('qtable').innerHTML =
    '<tr><th>query id</th><th>state</th><th>user</th><th>elapsed</th><th>query</th></tr>' + rows;
}
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
