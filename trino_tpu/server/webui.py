"""Web UI: cluster overview page.

Reference: the coordinator web UI (``core/trino-main/src/main/resources/webapp/``
React app + ``server/ui/ClusterStatsResource.java``). A single self-refreshing
page served at ``/ui`` over the existing JSON endpoints — no build step,
no external assets. Clicking a query row expands a per-stage timeline
rendered from ``/v1/query/{id}/timeline`` span data (stage + task_attempt
bars, offset from the query root span).
"""

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>trino-tpu</title>
<style>
  body { font-family: ui-monospace, Menlo, monospace; margin: 2rem;
         background: #16161d; color: #e6e6ef; }
  h1 { font-size: 1.2rem; } h1 span { color: #7aa2f7; }
  .tiles { display: flex; gap: 1rem; flex-wrap: wrap; margin: 1rem 0; }
  .tile { background: #1f1f2b; padding: .8rem 1.2rem; border-radius: 8px; }
  .tile .v { font-size: 1.6rem; color: #9ece6a; }
  .tile .l { font-size: .75rem; color: #9aa0b0; }
  table { border-collapse: collapse; width: 100%; margin-top: 1rem; }
  th, td { text-align: left; padding: .35rem .6rem; font-size: .8rem;
           border-bottom: 1px solid #2a2a38; }
  th { color: #9aa0b0; font-weight: normal; }
  .FINISHED { color: #9ece6a; } .FAILED { color: #f7768e; }
  .RUNNING, .QUEUED, .PLANNING { color: #e0af68; }
  td.q { max-width: 40rem; overflow: hidden; text-overflow: ellipsis;
         white-space: nowrap; }
  tr.qrow { cursor: pointer; }
  tr.qrow:hover td { background: #1f1f2b; }
  .tl { padding: .6rem; }
  .tlrow { display: flex; align-items: center; gap: .6rem;
           margin: .15rem 0; }
  .tlname { width: 16rem; font-size: .72rem; color: #9aa0b0;
            overflow: hidden; text-overflow: ellipsis; white-space: nowrap; }
  .tltrack { flex: 1; position: relative; height: .9rem;
             background: #1f1f2b; border-radius: 3px; }
  .tlbar { position: absolute; height: 100%; border-radius: 3px;
           background: #7aa2f7; min-width: 2px; }
  .tlbar.stage { background: #bb9af7; }
  .tlbar.err { background: #f7768e; }
  .tlbar.spec { background: #e0af68; }
  .tlbar.spec.cancelled { background: #565f89; }
  .tlbar.rec { background: #73daca; }
  .tlms { width: 6rem; font-size: .72rem; color: #9aa0b0;
          text-align: right; }
  table.stages { width: auto; margin: .4rem 0 .6rem .6rem; }
  table.stages th, table.stages td { font-size: .72rem;
          padding: .2rem .5rem; border-bottom: 1px solid #2a2a38; }
  table.stages td.num { text-align: right; }
</style>
</head>
<body>
<h1><span>trino-tpu</span> cluster overview</h1>
<div class="tiles">
  <div class="tile"><div class="v" id="queries">-</div><div class="l">queries tracked</div></div>
  <div class="tile"><div class="v" id="running">-</div><div class="l">running</div></div>
  <div class="tile"><div class="v" id="mem">-</div><div class="l">HBM pool reserved</div></div>
  <div class="tile"><div class="v" id="state">-</div><div class="l">node state</div></div>
</div>
<table id="qtable">
  <tr><th>query id</th><th>state</th><th>user</th><th>elapsed</th><th>query</th></tr>
</table>
<script>
const open = new Set();  // query ids with an expanded timeline

function bar(span, t0, total, cls) {
  const left = total > 0 ? ((span.startMs - t0) / total) * 100 : 0;
  const width = total > 0 ? ((span.durationMs || 0) / total) * 100 : 0;
  const a = span.attrs || {};
  let c = cls;
  // speculative attempts render distinctly: amber for the hedge,
  // muted for whichever attempt lost the race and was cancelled.
  // Recovery (teal) covers every tier: spool re-points, lineage
  // re-execution, and whole fused-unit re-runs (attrs.fused) alike
  if (a.speculative) c += ' spec';
  if (a.recovered) c += ' rec';
  if (a.state === 'CANCELED_SPECULATIVE') c += ' spec cancelled';
  if (span.status === 'ERROR') c += ' err';
  return `<div class="tlbar ${c}" style="left:${Math.max(0, left).toFixed(2)}%;` +
         `width:${Math.max(0.2, width).toFixed(2)}%"></div>`;
}

function renderTimeline(tl) {
  const esc = s => String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;');
  const spans = tl.spans || [];
  if (!spans.length) return '<div class="tl">no spans recorded</div>';
  const t0 = Math.min(...spans.map(s => s.startMs));
  const total = Math.max(...spans.map(
      s => (s.startMs - t0) + (s.durationMs || 0)));
  const interesting = spans.filter(
      s => ['query', 'stage', 'task_attempt', 'task_execute',
            'plan', 'optimize', 'fragment'].includes(s.name))
    .sort((a, b) => a.startMs - b.startMs);
  const label = s => {
    const a = s.attrs || {};
    if (s.name === 'stage') return `stage ${a.stage}` +
        (a.coordinator ? ' (coordinator)' : ` · ${a.tasks} tasks`);
    if (s.name === 'task_attempt') return `  ${a.taskId}` +
        (a.retry ? ' (retry)' : '') +
        (a.speculative ? ' (speculative)' : '') +
        (a.recovered ? ' (recovered)' : '') +
        (a.state === 'CANCELED_SPECULATIVE' ? ' (lost race)' : '');
    if (s.name === 'task_execute') return `  exec ${a.taskId}`;
    return s.name;
  };
  return '<div class="tl">' + interesting.map(s =>
    `<div class="tlrow"><div class="tlname">${esc(label(s))}</div>` +
    `<div class="tltrack">` +
    bar(s, t0, total, s.name === 'stage' || s.name === 'query' ? 'stage' : '') +
    `</div><div class="tlms">${(s.durationMs || 0).toFixed(1)} ms</div></div>`
  ).join('') + '</div>';
}

// per-stage device-profiler columns (queryStats.stages merged by the
// coordinator from worker task stats: rows / wall / exchange bytes /
// XLA cost-analysis FLOPs / peak HBM). Blank cells mean the backend
// reported no cost model (e.g. CPU) — the row layout stays stable.
function renderStages(q) {
  const stages = ((q.queryStats || {}).stages) || [];
  if (!stages.length) return '';
  const esc = s => String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;');
  const num = v => (v === null || v === undefined) ? '' :
      Number(v).toLocaleString();
  const flops = v => (v === null || v === undefined) ? '' :
      Number(v).toExponential(2);
  // capacity provenance: which of default/seeded/history (+grown/+halved
  // corrections) the stage's capacity sites ran on — 'history' means the
  // query ran on observed truth, a '+' suffix means the estimate missed
  const prov = ex => {
    const caps = ex.capacities || {};
    const seen = new Set();
    for (const k of Object.keys(caps)) {
      const p = (caps[k] || {}).provenance;
      if (p) seen.add(p);
    }
    return Array.from(seen).sort().join(' ');
  };
  const rows = stages.map(s => {
    const ex = s.exchange || {};
    return `<tr><td>${esc(s.stage)}</td>` +
      `<td class="num">${num(s.tasks)}</td>` +
      `<td class="num">${num(s.rows)}</td>` +
      `<td class="num">${(s.elapsedMs || 0).toFixed(1)}</td>` +
      `<td class="num">${num(ex.shuffle_bytes)}</td>` +
      `<td class="num">${flops(s.flops)}</td>` +
      `<td class="num">${num(s.peakHbmBytes)}</td>` +
      `<td>${esc(prov(ex))}</td></tr>`;
  });
  return '<table class="stages"><tr><th>stage</th><th>tasks</th>' +
    '<th>rows</th><th>wall ms</th><th>shuffle B</th>' +
    '<th>flops</th><th>peak HBM B</th><th>capacity prov</th></tr>' +
    rows.join('') + '</table>';
}

// per-operator row flow (operatorStats: the in-program op! counter
// channel, cluster-merged). Sites are restart-stable `kind@stage#ord`
// names; rows group under their stage so the table reads top-down in
// the same order as the span waterfall above it.
function renderOperators(q) {
  const ops = q.operatorStats || {};
  const sites = Object.keys(ops);
  if (!sites.length) return '';
  const esc = s => String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;');
  const num = v => (v === null || v === undefined) ? '' :
      Number(v).toLocaleString();
  const stageOf = site => {
    const m = site.match(/@(\\d+)/);
    return m ? Number(m[1]) : 1e9;
  };
  sites.sort((a, b) => stageOf(a) - stageOf(b) || a.localeCompare(b));
  let lastStage = null;
  const rows = [];
  for (const site of sites) {
    const ent = ops[site] || {};
    const stage = stageOf(site);
    const rin = Number(ent.rows_in || 0);
    const rout = Number(ent.rows_out || 0);
    const sel = rin > 0 ? (rout / rin).toFixed(3) : '';
    const stageCell = stage === lastStage ? '' : `stage ${stage}`;
    lastStage = stage;
    rows.push(`<tr><td>${esc(stageCell)}</td><td>${esc(site)}</td>` +
      `<td>${esc(ent.kind || '')}</td>` +
      `<td class="num">${num(rin)}</td>` +
      `<td class="num">${num(rout)}</td>` +
      `<td class="num">${sel}</td></tr>`);
  }
  return '<table class="stages"><tr><th>stage</th><th>operator site</th>' +
    '<th>kind</th><th>rows in</th><th>rows out</th><th>selectivity</th>' +
    '</tr>' + rows.join('') + '</table>';
}

async function toggleTimeline(qid) {
  if (open.has(qid)) open.delete(qid); else open.add(qid);
  refresh();
}

async function refresh() {
  const st = await (await fetch('/v1/status')).json();
  const qs = await (await fetch('/v1/query')).json();
  document.getElementById('queries').textContent = qs.length;
  document.getElementById('running').textContent =
      qs.filter(q => !['FINISHED','FAILED','CANCELED'].includes(q.state)).length;
  const mb = st.memoryInfo.reservedBytes / (1024 * 1024);
  document.getElementById('mem').textContent = mb.toFixed(1) + ' MB';
  document.getElementById('state').textContent = st.state;
  const esc = s => String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;')
      .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
  const stateClass = s => ['FINISHED','FAILED','RUNNING','QUEUED','PLANNING']
      .includes(s) ? s : '';
  const sorted = qs.sort((a, b) => b.createTime - a.createTime).slice(0, 50);
  const rows = [];
  for (const q of sorted) {
    rows.push(
      `<tr class="qrow" onclick="toggleTimeline('${esc(q.queryId)}')">` +
      `<td>${esc(q.queryId)}</td><td class="${stateClass(q.state)}">${esc(q.state)}</td>` +
      `<td>${esc(q.user)}</td><td>${esc(q.elapsedTimeMillis)} ms</td>` +
      `<td class="q">${esc(q.query)}</td></tr>`);
    if (open.has(q.queryId)) {
      let tl = {spans: []};
      try {
        tl = await (await fetch(
            '/v1/query/' + encodeURIComponent(q.queryId) + '/timeline')).json();
      } catch (e) { /* timeline unavailable */ }
      rows.push(`<tr><td colspan="5">${renderStages(q)}` +
        `${renderOperators(q)}${renderTimeline(tl)}</td></tr>`);
    }
  }
  document.getElementById('qtable').innerHTML =
    '<tr><th>query id</th><th>state</th><th>user</th><th>elapsed</th><th>query</th></tr>' +
    rows.join('');
}
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
