"""Plan canonicalization + fingerprinting for the cross-query program cache.

Reference: Trino keys its generated-code caches on *canonicalized*
``RowExpression``s with constants bound as fields of the generated class
(``sql/gen/ExpressionCompiler.java:56,94`` — a Guava cache over the
expression shape), so ``x < 24`` and ``x < 25`` share one compiled class.
The TPU-native analog: non-structural ``Constant``s in the optimized plan
are hoisted into an ordered parameter vector (each becomes a
:class:`~trino_tpu.ir.HoistedConstant` carrying its position), and the
fingerprint is a sha256 over the canonical plan serde plus everything
else that shapes the traced program — mesh size, codegen-relevant session
properties, parameter count. Two SQL texts whose optimized plans differ
only in hoisted literals fingerprint identically and share compiled
fragment programs; the literals ride along as device-scalar jit
arguments (``exec/fragments.py`` feeds them through ``__params__``).

What stays baked (structural — changing it changes the traced program):

- LIMIT / TopN counts, partition counts, decimal scales (shape/dtype)
- string literals: they become dictionary truth tables at trace time
- wide DECIMAL literals (|v| >= 2**63): they add hi/lo lanes (rank change)
- arguments that must be concrete at trace time (LIKE patterns,
  ``round`` digits, ``date_trunc`` units, IN-list strings …) — excluded
  automatically because only the whitelisted arithmetic/comparison
  positions below ever hoist
- ``Values`` rows, aggregate arguments, window frame defaults

Runtime *capacities* are deliberately NOT part of the fingerprint: they
live in the per-entry ``_Caps`` signature that keys each traced program
under the fingerprint entry (bucketed via ``bucket_capacity`` on growth
so the overflow ladder lands on few distinct shapes — see
``exec/fragments.py::_retry_traced``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.ir import Call, Constant, HoistedConstant, RowExpr, SpecialForm
from trino_tpu.planner import plan as P

# positions where a numeric literal compiles to a plain broadcast lane:
# direct args of these calls (and the desugared members of IN/BETWEEN).
# Everything else — function args the kernels need concrete, string
# comparisons routed through dictionary truth tables — stays baked.
_HOIST_CALLS = frozenset(
    {"eq", "ne", "lt", "le", "gt", "ge",
     "add", "subtract", "multiply", "divide", "modulus"}
)
_HOIST_FORMS = frozenset({"in", "between"})

# session properties that change what a fragment traces into (capacity
# defaults, execution strategy, lowering decisions). Anything NOT listed
# here must not affect codegen, or same-fingerprint queries would want
# different programs. ``device_profiling`` is deliberately absent: it
# AOT-compiles the SAME jitted program (obs/profiler.py), so toggling it
# must keep the fingerprint — and the cached program, with its captured
# cost/memory stats riding the cache entry's _Meta — stable. Same for
# ``batch_window_ms``/``batch_max_size``: they decide whether queries
# WAIT to share a dispatch (exec/batching.py), not what any of them
# traces — cross-query batching groups by cache entry, so the window
# knobs must not split the fingerprint those groups key on.
_CODEGEN_PROPS = (
    "batch_capacity",
    "broadcast_join_threshold_rows",
    # the dense-join knobs pick which join kernel a fragment traces (and
    # whether spill-sized inputs stay on the compiled path), so sort- and
    # dense-strategy runs of one plan must not share a fingerprint
    "dense_join",
    "dynamic_filtering_max_build_rows",
    "enable_dynamic_filtering",
    "execution_mode",
    "fragment_execution",
    "fusion_max_fragments",
    "join_distribution_type",
    "join_reordering_strategy",
    "join_strategy",
    "matmul_join_max_domain",
    # operator telemetry mints extra traced reductions (op! counters), so
    # on/off runs of one plan compile different programs — unlike
    # device_profiling, which observes the SAME program from outside
    "operator_stats",
    # fusion regroups fragments into multi-fragment programs, and the
    # grouping itself is cached per entry (__fusedunits__), so fused and
    # unfused runs of the same plan must not share a fingerprint
    "pipeline_fusion",
    # history seeding changes starting capacities, and capacities live on
    # the shared cache entry (_Caps per program key) — same reason
    # stats_capacity_seeding is listed. history_dir/history_max_entries
    # stay OUT: they pick where/how much truth is kept, not what a
    # fragment traces into.
    "query_history",
    "skew_handling",
    "skew_hot_k",
    "skew_hot_threshold_frac",
    "spill_enabled",
    "spill_partitions",
    "spill_threshold_rows",
    "stats_capacity_seeding",
    "stream_chunk_rows",
    "stream_device_cache_bytes",
    "stream_device_chunk_rows",
    "stream_group_budget",
    "stream_scan_threshold_rows",
    "task_concurrency",
    "tpu_enabled",
    "worker_execution",
)


def _eligible(c: RowExpr) -> bool:
    """Can this literal move to the parameter vector without changing the
    traced program's shape or concreteness requirements?"""
    if type(c) is not Constant:  # exact: never re-hoist a HoistedConstant
        return False
    if c.value is None:  # NULL handling branches on concreteness
        return False
    if T.is_string(c.type):  # becomes a dictionary truth table
        return False
    if not isinstance(c.value, (int, float)):
        return False
    if isinstance(c.value, int) and abs(c.value) >= 1 << 63:
        return False  # wide decimal: extra hi/lo lanes (rank change)
    return True


def _hoist_expr(e: RowExpr, params: list, hoistable: bool) -> RowExpr:
    """Depth-first rewrite; ``hoistable`` marks positions whose literals
    the compiler lowers to plain broadcast lanes. Parameter order is the
    visit order, which is a pure function of the plan shape — two plans
    with equal shape assign equal indices."""
    if isinstance(e, Call):
        ok = e.name in _HOIST_CALLS and not any(
            T.is_string(a.type) for a in e.args
        )
        args = tuple(_hoist_expr(a, params, ok) for a in e.args)
        return e if args == e.args else Call(type=e.type, name=e.name, args=args)
    if isinstance(e, SpecialForm):
        ok = e.form in _HOIST_FORMS and not any(
            T.is_string(a.type) for a in e.args
        )
        # args[0] is the tested value; members/bounds desugar to eq/ge/le
        args = tuple(
            _hoist_expr(a, params, ok and i > 0) for i, a in enumerate(e.args)
        )
        return (
            e if args == e.args
            else SpecialForm(type=e.type, form=e.form, args=args)
        )
    if hoistable and _eligible(e):
        idx = len(params)
        params.append((e.value, e.type))
        return HoistedConstant(type=e.type, value=e.value, index=idx)
    return e


def _rewrite_node(node: P.PlanNode, params: list) -> P.PlanNode:
    """Top-down: hoist this node's expressions, then recurse into sources.
    Only Filter predicates, Project assignments and Join filters hoist —
    every other expression position needs concrete values (Values rows,
    aggregate masks, window defaults, scan pushdowns)."""
    changes: dict[str, Any] = {}
    if isinstance(node, P.Filter):
        p2 = _hoist_expr(node.predicate, params, False)
        if p2 is not node.predicate:
            changes["predicate"] = p2
    elif isinstance(node, P.Project):
        new = [(s, _hoist_expr(e, params, False)) for s, e in node.assignments]
        if any(e2 is not e for (_, e2), (_, e) in zip(new, node.assignments)):
            changes["assignments"] = new
    elif isinstance(node, P.Join) and node.filter is not None:
        f2 = _hoist_expr(node.filter, params, False)
        if f2 is not node.filter:
            changes["filter"] = f2

    if isinstance(node, P.Join):
        left = _rewrite_node(node.left, params)
        right = _rewrite_node(node.right, params)
        if left is not node.left:
            changes["left"] = left
        if right is not node.right:
            changes["right"] = right
    elif isinstance(node, P.SetOp):
        new_inputs = [_rewrite_node(s, params) for s in node.inputs]
        if any(a is not b for a, b in zip(new_inputs, node.inputs)):
            changes["inputs"] = new_inputs
    elif getattr(node, "source", None) is not None:
        src = _rewrite_node(node.source, params)
        if src is not node.source:
            changes["source"] = src
    return dataclasses.replace(node, **changes) if changes else node


def _strip_scan_constraints(node: P.PlanNode) -> P.PlanNode:
    """Drop advisory scan pushdowns from a parameterized plan.

    ``push_into_scans`` baked this query's literals into
    ``TableScan.constraint`` (split pruning) and ``pushed_predicate``;
    replaying them for a different literal could wrongly prune splits.
    Both are advisory — the enclosing Filter still applies the full
    (now parameterized) predicate — so correctness survives, only the
    pruning shortcut is lost. ``limit``/``topn`` hints are structural
    (never hoisted) and stay.
    """
    if isinstance(node, P.TableScan):
        if node.constraint is not None or node.pushed_predicate is not None:
            return dataclasses.replace(
                node, constraint=None, pushed_predicate=None
            )
        return node
    if isinstance(node, P.Join):
        return dataclasses.replace(
            node,
            left=_strip_scan_constraints(node.left),
            right=_strip_scan_constraints(node.right),
        )
    if isinstance(node, P.SetOp):
        return dataclasses.replace(
            node, inputs=[_strip_scan_constraints(s) for s in node.inputs]
        )
    if getattr(node, "source", None) is not None:
        return dataclasses.replace(
            node, source=_strip_scan_constraints(node.source)
        )
    return node


def _bind_expr(e: RowExpr, values: list) -> RowExpr:
    if isinstance(e, HoistedConstant):
        return Constant(type=e.type, value=values[e.index])
    if isinstance(e, Call):
        args = tuple(_bind_expr(a, values) for a in e.args)
        return e if args == e.args else Call(type=e.type, name=e.name, args=args)
    if isinstance(e, SpecialForm):
        args = tuple(_bind_expr(a, values) for a in e.args)
        return (
            e if args == e.args
            else SpecialForm(type=e.type, form=e.form, args=args)
        )
    return e


def _bind_node(node: P.PlanNode, values: list) -> P.PlanNode:
    """Mirror of ``_rewrite_node``'s positions, replacing each
    ``HoistedConstant`` with a plain ``Constant`` carrying this query's
    literal."""
    changes: dict[str, Any] = {}
    if isinstance(node, P.Filter):
        p2 = _bind_expr(node.predicate, values)
        if p2 is not node.predicate:
            changes["predicate"] = p2
    elif isinstance(node, P.Project):
        new = [(s, _bind_expr(e, values)) for s, e in node.assignments]
        if any(e2 is not e for (_, e2), (_, e) in zip(new, node.assignments)):
            changes["assignments"] = new
    elif isinstance(node, P.Join) and node.filter is not None:
        f2 = _bind_expr(node.filter, values)
        if f2 is not node.filter:
            changes["filter"] = f2

    if isinstance(node, P.Join):
        left = _bind_node(node.left, values)
        right = _bind_node(node.right, values)
        if left is not node.left:
            changes["left"] = left
        if right is not node.right:
            changes["right"] = right
    elif isinstance(node, P.SetOp):
        new_inputs = [_bind_node(s, values) for s in node.inputs]
        if any(a is not b for a, b in zip(new_inputs, node.inputs)):
            changes["inputs"] = new_inputs
    elif getattr(node, "source", None) is not None:
        src = _bind_node(node.source, values)
        if src is not node.source:
            changes["source"] = src
    return dataclasses.replace(node, **changes) if changes else node


def bind_params(plan: P.PlanNode, params: list) -> P.PlanNode:
    """Re-bake a canonical plan's hoisted literals as plain Constants.

    The inverse of hoisting, for executors that cannot carry a parameter
    vector: the cluster scheduler ships fragments over the wire and the
    canonical serde intentionally drops ``HoistedConstant`` values, so a
    cluster (or batched-then-sequential-fallback) run of a cached plan
    must bind THIS query's ``params`` back in before fragmentation.
    ``params`` is the ordered ``(value, type)`` list ``canonicalize_plan``
    returned — for a batch member, its own vector, not the leader's."""
    if not params:
        return plan
    return _bind_node(plan, [v for v, _ in params])


def _alpha_rename(obj: Any, names: dict) -> Any:
    """Positionally rename symbols in the serialized plan (``count_16`` →
    ``s3``). The planner allocates symbol names off a process-global
    counter, so two structurally identical plans planned at different
    times carry different names; first-visit order is a pure function of
    the plan shape, so equal shapes map to equal canonical names. Only
    ``"n"`` values (symbol serde) and ``"name"`` values of ``var`` exprs
    rename — ``call`` names are function names and stay."""
    if isinstance(obj, list):
        return [_alpha_rename(x, names) for x in obj]
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if k == "n" or (k == "name" and obj.get("k") == "var"):
                if v not in names:
                    names[v] = f"s{len(names)}"
                out[k] = names[v]
            else:
                out[k] = _alpha_rename(v, names)
        return out
    return obj


def plan_fingerprint(
    root: P.PlanNode, session: Session, mesh_devices: int = 1, nparams: int = 0
) -> Optional[str]:
    """Stable sha256 over the canonical plan serde + codegen context.

    Returns None when the plan contains nodes the canonical serde cannot
    express (e.g. Unnest) — those statements simply run uncached.
    """
    from trino_tpu.planner.serde import node_to_json

    try:
        doc = _alpha_rename(node_to_json(root), {})
        props = {}
        for name in _CODEGEN_PROPS:
            try:
                props[name] = repr(session.get(name))
            except KeyError:
                continue
        payload = json.dumps(
            {
                "plan": doc,
                "mesh": int(mesh_devices),
                "props": props,
                "nparams": int(nparams),
            },
            sort_keys=True,
            separators=(",", ":"),
            default=str,
        )
    except Exception:  # noqa: BLE001 — unserializable plan: run uncached
        return None
    return hashlib.sha256(payload.encode()).hexdigest()


def canonicalize_plan(
    plan: P.PlanNode, session: Session, mesh_devices: int = 1
) -> tuple[P.PlanNode, list, Optional[str]]:
    """Hoist non-structural literals and fingerprint the optimized plan.

    Returns ``(canonical_plan, params, fingerprint)`` where ``params`` is
    the ordered list of ``(value, type)`` hoisted literals and
    ``fingerprint`` is None for uncacheable shapes. With
    ``constant_hoisting`` off the plan is returned untouched (every
    literal variation then fingerprints — and compiles — separately).
    """
    params: list = []
    root = plan
    if bool(session.get("constant_hoisting")):
        root = _rewrite_node(plan, params)
        if params:
            root = _strip_scan_constraints(root)
    fp = plan_fingerprint(root, session, mesh_devices, nparams=len(params))
    return root, params, fp


# aggregate kinds whose partial state merges exactly by row-wise combine
# of final values: sum/min/max combine with themselves, count combines by
# addition. avg is OUT (final value loses the count weight); distinct and
# filtered aggregates are OUT (their state is not the output value).
_MAINTAINABLE_AGGS = frozenset({"sum", "count", "count_star", "min", "max"})


def _sum_merges_exactly(t) -> bool:
    # float sums are order-dependent: cached + delta would differ in the
    # last ulp from a cold re-execution, breaking bit-identity. Integer
    # and decimal sums are exact under any association.
    return T.is_integer(t) or isinstance(t, T.DecimalType)


def classify_maintainability(root: P.PlanNode) -> Optional[dict]:
    """Can this plan's cached result be maintained incrementally on
    append? Yes only for the shape ``Output <- Aggregate(single) <-
    (Filter|Project)* <- TableScan`` where every aggregate merges exactly
    (:data:`_MAINTAINABLE_AGGS`, exact-sum types) and every group key is
    visible in the output (hidden keys could merge distinct output rows).

    Returns ``{"table": (catalog, schema, table), "cols": (kind, ...)}``
    with one kind per output column — ``"key"``, ``"sum"``, ``"count"``,
    ``"min"`` or ``"max"`` — or None for non-maintainable shapes (joins,
    sorts, limits, avg, distinct, filtered aggregates, multi-scan plans),
    which fall back to plain invalidation.
    """
    if not isinstance(root, P.Output):
        return None
    from trino_tpu.ir import Variable

    # the planner renames aggregate symbols to output names through pure
    # identity Projects (sum_4 -> s); follow each output symbol down the
    # rename chain to the symbol the Aggregate actually produces. Any
    # computed assignment (sum(v) + 1) makes that column non-maintainable.
    rename: dict[str, Optional[str]] = {s.name: s.name for s in root.symbols}
    node = root.source
    while isinstance(node, P.Project):
        sub: dict[str, Optional[str]] = {}
        for sym, expr in node.assignments:
            sub[sym.name] = expr.name if isinstance(expr, Variable) else None
        rename = {
            out: (sub.get(cur) if cur is not None else None)
            for out, cur in rename.items()
        }
        node = node.source
    agg = node
    if not isinstance(agg, P.Aggregate) or agg.step != "single":
        return None
    by_symbol: dict[str, str] = {}
    for s in agg.group_keys:
        by_symbol[s.name] = "key"
    for s, fn in agg.aggregates:
        if fn.kind not in _MAINTAINABLE_AGGS:
            return None
        if fn.distinct or fn.filter is not None:
            return None
        if fn.kind == "sum" and not _sum_merges_exactly(fn.result_type):
            return None
        by_symbol[s.name] = "count" if fn.kind in ("count", "count_star") else fn.kind
    cols = []
    for s in root.symbols:
        src_name = rename.get(s.name)
        kind = by_symbol.get(src_name) if src_name is not None else None
        if kind is None:  # output column that is neither key nor aggregate
            return None
        cols.append(kind)
    visible = {rename[s.name] for s in root.symbols}
    if any(s.name not in visible for s in agg.group_keys):
        return None
    node = agg.source
    while isinstance(node, (P.Filter, P.Project)):
        node = node.source
    if not isinstance(node, P.TableScan):
        return None
    return {
        "table": (node.catalog, node.schema, node.table),
        "cols": tuple(cols),
    }
