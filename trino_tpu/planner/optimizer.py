"""Logical plan optimizer.

Reference: Trino runs ~194 iterative rules plus whole-plan optimizers
(``sql/planner/optimizations/PredicatePushDown.java``,
``iterative/rule/PruneUnreferencedOutputs`` family, ``AddExchanges.java:115``;
sequence in ``PlanOptimizers.java:240``). v1 implements the two rules with
the largest execution impact, as whole-plan recursive passes:

1. predicate pushdown — split conjuncts, inline through projections, push
   to the narrowest subtree (join sides, below sorts, into scan filters)
2. column pruning — scans read only referenced columns; projections and
   aggregations drop dead outputs

Join distribution selection (broadcast vs partitioned) lives in the
fragmenter (parallel/), where the mesh is known.
"""

from __future__ import annotations

import dataclasses

from typing import Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.ir import (
    Call,
    Constant,
    RowExpr,
    SpecialForm,
    Variable,
    referenced_variables,
    special,
    transform,
    variable,
)
from trino_tpu.planner import plan as P


def optimize(root: P.PlanNode, session: Session, catalogs) -> P.PlanNode:
    from trino_tpu.planner.joins import determine_join_distribution, reorder_joins
    from trino_tpu.planner.sanity import PlanSanityChecker, validation_enabled
    from trino_tpu.planner.stats import StatsCalculator

    from trino_tpu.planner.iterative import run_default

    # Reference: PlanSanityChecker.validateIntermediatePlan after every
    # optimizer stage — a broken rewrite fails fast, typed, at plan time.
    validate = validation_enabled(session)

    def checked(stage: str, node: P.PlanNode) -> P.PlanNode:
        if validate:
            PlanSanityChecker.validate_intermediate(node, stage)
        return node

    root = checked("push_down_predicates", push_down_predicates(root))
    root = checked("push_into_scans", push_into_scans(root))
    # iterative rule tier (Memo + pattern rules): simplification, limit
    # merging/TopN creation, connector applyLimit/applyTopN/applyAggregation
    root = checked("iterative_rules", run_default(root, session, catalogs))
    stats = StatsCalculator(catalogs)
    if session.get("join_reordering_strategy") == "AUTOMATIC":
        root = checked("reorder_joins", reorder_joins(root, stats, session))
    root = checked(
        "determine_join_distribution",
        determine_join_distribution(root, stats, session),
    )
    root = prune_columns(root)
    if validate:
        PlanSanityChecker.validate_final(root, "prune_columns")
    return root


# === predicate -> TupleDomain pushdown into scans ==========================
# Reference: iterative/rule/PushPredicateIntoTableScan.java + DomainTranslator.
# The extracted constraint drives connector split pruning and dynamic-filter
# intersection; the Filter stays in place (constraint is unenforced).


def push_into_scans(node: P.PlanNode) -> P.PlanNode:
    from trino_tpu.predicate import extract_tuple_domain

    if isinstance(node, P.Filter) and isinstance(node.source, P.TableScan):
        scan = node.source
        res = extract_tuple_domain(_conjuncts(node.predicate))
        td = res.tuple_domain
        if not td.is_all():
            # rekey symbol names -> connector column names
            sym_to_col = {
                s.name: c for s, c in zip(scan.symbols, scan.column_names)
            }
            if td.is_none():
                constraint = td
            else:
                from trino_tpu.predicate import TupleDomain

                constraint = TupleDomain(
                    {
                        sym_to_col[k]: v
                        for k, v in td.domains.items()
                        if k in sym_to_col
                    }
                )
            if scan.constraint is not None:
                constraint = scan.constraint.intersect(constraint)
            new_scan = dataclasses.replace(scan, constraint=constraint)
            return P.Filter(new_scan, node.predicate)
        return node
    new_sources = [push_into_scans(s) for s in node.sources]
    if new_sources:
        return _replace_sources(node, new_sources)
    return node


# === predicate pushdown ====================================================


def _conjuncts(e: RowExpr) -> list[RowExpr]:
    if isinstance(e, SpecialForm) and e.form == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    e = _extract_common_or_conjuncts(e)
    if isinstance(e, SpecialForm) and e.form == "and":
        return _conjuncts(e)
    return [e]


def _extract_common_or_conjuncts(e: RowExpr) -> RowExpr:
    """OR(A∧X, A∧Y) -> A ∧ OR(X, Y): factor conjuncts common to every OR
    branch so they can push down / become join criteria (reference:
    sql/ExpressionUtils.extractCommonPredicates, essential for TPC-H Q19's
    OR-of-conjunction-with-shared-join-key shape)."""
    if not (isinstance(e, SpecialForm) and e.form == "or"):
        return e
    branches = [_conjuncts_no_or(b) for b in _disjuncts(e)]
    common = [c for c in branches[0] if all(c in b for b in branches[1:])]
    if not common:
        return e
    remainders = []
    for b in branches:
        rem = [c for c in b if c not in common]
        if not rem:  # a branch reduced to TRUE: the OR is implied by common
            remainders = None
            break
        remainders.append(_combine_and(rem))
    parts = list(common)
    if remainders is not None:
        parts.append(SpecialForm(type=T.BOOLEAN, form="or", args=tuple(remainders)))
    return _combine_and(parts)


def _disjuncts(e: RowExpr) -> list[RowExpr]:
    if isinstance(e, SpecialForm) and e.form == "or":
        out = []
        for a in e.args:
            out.extend(_disjuncts(a))
        return out
    return [e]


def _conjuncts_no_or(e: RowExpr) -> list[RowExpr]:
    if isinstance(e, SpecialForm) and e.form == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts_no_or(a))
        return out
    return [e]


def _combine_and(parts: list[RowExpr]) -> RowExpr:
    out = parts[0]
    for p in parts[1:]:
        out = special("and", T.BOOLEAN, out, p)
    return out


def _combine(conjuncts: list[RowExpr]) -> Optional[RowExpr]:
    if not conjuncts:
        return None
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = special("and", T.BOOLEAN, out, c)
    return out


def _with_filter(node: P.PlanNode, conjuncts: list[RowExpr]) -> P.PlanNode:
    pred = _combine(conjuncts)
    return node if pred is None else P.Filter(node, pred)


def push_down_predicates(node: P.PlanNode, inherited: Optional[list[RowExpr]] = None) -> P.PlanNode:
    """Returns a plan where every pushable conjunct sits as low as possible."""
    pending = list(inherited or [])

    if isinstance(node, P.Filter):
        pending.extend(_conjuncts(node.predicate))
        return push_down_predicates(node.source, pending)

    if isinstance(node, P.Project):
        assign = dict((s.name, e) for s, e in node.assignments)
        pushable, kept = [], []
        for c in pending:
            refs = referenced_variables(c)
            if all(r in assign for r in refs):
                # inline assignment expressions into the conjunct
                def repl(e: RowExpr) -> RowExpr:
                    if isinstance(e, Variable) and e.name in assign:
                        return assign[e.name]
                    return e

                pushable.append(transform(c, repl))
            else:
                kept.append(c)
        src = push_down_predicates(node.source, pushable)
        return _with_filter(
            P.Project(src, node.assignments), kept
        )

    if isinstance(node, P.Join):
        left_names = {s.name for s in node.left.output_symbols}
        right_names = {s.name for s in node.right.output_symbols}
        to_left, to_right, kept = [], [], []
        criteria = list(node.criteria)
        join_type = node.join_type
        for c in pending:
            refs = referenced_variables(c)
            # pushing into a null-extended (outer) side would filter before
            # null-extension and wrongly revive rows: LEFT keeps left-side
            # pushes only, RIGHT right-side only, FULL neither
            if refs and refs <= left_names and join_type in (
                "INNER", "CROSS", "LEFT", "SEMI", "ANTI"
            ):
                to_left.append(c)
            elif refs and refs <= right_names and join_type in (
                "INNER", "CROSS", "RIGHT", "SEMI", "ANTI"
            ):
                to_right.append(c)
            else:
                # equality spanning both sides of an inner/cross join
                # becomes a join criterion (reference: PredicatePushDown
                # turning WHERE equalities into JoinNode criteria)
                eq = _as_criterion(c, left_names, right_names)
                if eq is not None and join_type in ("INNER", "CROSS"):
                    criteria.append(eq)
                    join_type = "INNER"
                else:
                    kept.append(c)
        left = push_down_predicates(node.left, to_left)
        right = push_down_predicates(node.right, to_right)
        out = P.Join(
            join_type, left, right, criteria, node.filter,
            node.distribution, node.mark_symbol, node.null_aware,
            node.single_row,
        )
        return _with_filter(out, kept)

    if isinstance(node, P.Aggregate):
        key_names = {s.name for s in node.group_keys}
        pushable, kept = [], []
        for c in pending:
            refs = referenced_variables(c)
            if refs and refs <= key_names:
                pushable.append(c)
            else:
                kept.append(c)
        src = push_down_predicates(node.source, pushable)
        return _with_filter(
            P.Aggregate(src, node.group_keys, node.aggregates, node.step), kept
        )

    if isinstance(node, P.Sort):
        src = push_down_predicates(node.source, pending)
        return P.Sort(src, node.order_by)

    if isinstance(node, (P.Limit, P.TopN, P.Distinct, P.Window, P.SetOp, P.Output)):
        # do not push through row-count-sensitive nodes; recurse inside
        new_sources = [push_down_predicates(s) for s in node.sources]
        out = _replace_sources(node, new_sources)
        return _with_filter(out, pending)

    if isinstance(node, (P.TableScan, P.Values)):
        return _with_filter(node, pending)

    new_sources = [push_down_predicates(s) for s in node.sources]
    return _with_filter(_replace_sources(node, new_sources), pending)


def _as_criterion(c: RowExpr, left_names: set[str], right_names: set[str]):
    if not (isinstance(c, Call) and c.name == "eq" and len(c.args) == 2):
        return None
    a, b = c.args
    if not (isinstance(a, Variable) and isinstance(b, Variable)):
        return None
    if a.name in left_names and b.name in right_names:
        return (P.Symbol(a.name, a.type), P.Symbol(b.name, b.type))
    if b.name in left_names and a.name in right_names:
        return (P.Symbol(b.name, b.type), P.Symbol(a.name, a.type))
    return None


def _replace_sources(node: P.PlanNode, new_sources: list[P.PlanNode]) -> P.PlanNode:
    return P.replace_sources(node, new_sources)


# === column pruning ========================================================


def prune_columns(node: P.PlanNode, required: Optional[set[str]] = None) -> P.PlanNode:
    if required is None:
        required = {s.name for s in node.output_symbols}

    if isinstance(node, P.Output):
        src = prune_columns(node.source, {s.name for s in node.symbols})
        return P.Output(src, node.column_names, node.symbols)

    if isinstance(node, P.Project):
        kept = [(s, e) for s, e in node.assignments if s.name in required]
        needed = set()
        for _, e in kept:
            needed |= referenced_variables(e)
        src = prune_columns(node.source, needed)
        return P.Project(src, kept)

    if isinstance(node, P.Filter):
        needed = set(required) | referenced_variables(node.predicate)
        src = prune_columns(node.source, needed)
        return P.Filter(src, node.predicate)

    if isinstance(node, P.TableScan):
        keep = [
            (s, c)
            for s, c in zip(node.symbols, node.column_names)
            if s.name in required
        ]
        if not keep:  # keep one column for row counting
            keep = [(node.symbols[0], node.column_names[0])]
        return dataclasses.replace(
            node, symbols=[s for s, _ in keep],
            column_names=[c for _, c in keep],
        )

    if isinstance(node, P.Aggregate):
        aggs = [(s, f) for s, f in node.aggregates if s.name in required]
        needed = {s.name for s in node.group_keys}
        for _, f in aggs:
            if f.argument is not None:
                needed |= referenced_variables(f.argument)
            if f.filter is not None:
                needed |= referenced_variables(f.filter)
        src = prune_columns(node.source, needed)
        return P.Aggregate(src, node.group_keys, aggs, node.step)

    if isinstance(node, P.Join):
        needed = set(required)
        for a, b in node.criteria:
            needed.add(a.name)
            needed.add(b.name)
        if node.filter is not None:
            needed |= referenced_variables(node.filter)
        left_names = {s.name for s in node.left.output_symbols}
        right_names = {s.name for s in node.right.output_symbols}
        left = prune_columns(node.left, needed & left_names)
        right = prune_columns(node.right, needed & right_names)
        return P.Join(
            node.join_type, left, right, node.criteria, node.filter,
            node.distribution, node.mark_symbol, node.null_aware,
            node.single_row,
        )

    if isinstance(node, P.Sort):
        needed = set(required) | {o.symbol.name for o in node.order_by}
        return P.Sort(prune_columns(node.source, needed), node.order_by)

    if isinstance(node, P.TopN):
        needed = set(required) | {o.symbol.name for o in node.order_by}
        return P.TopN(
            prune_columns(node.source, needed), node.count, node.order_by, node.step
        )

    if isinstance(node, P.Limit):
        return P.Limit(prune_columns(node.source, set(required)), node.count, node.offset)

    if isinstance(node, P.GroupId):
        # the aggregate above always needs every grouping key + the gid;
        # the source additionally feeds any required agg inputs
        src_required = (set(required) - {node.gid.name}) | {
            s.name for s in node.all_keys
        }
        src = prune_columns(node.source, src_required)
        return P.GroupId(src, node.groups, node.all_keys, node.gid)

    if isinstance(node, P.Distinct):
        # distinct keys are all output columns — everything is required
        src = prune_columns(node.source, {s.name for s in node.output_symbols})
        return P.Distinct(src)

    if isinstance(node, P.Window):
        needed = set(required) | {s.name for s in node.partition_by}
        needed |= {o.symbol.name for o in node.order_by}
        for _, f in node.functions:
            if f.argument is not None:
                needed |= referenced_variables(f.argument)
            if f.default is not None:
                needed |= referenced_variables(f.default)
        src = prune_columns(node.source, needed - {s.name for s, _ in node.functions})
        return P.Window(src, node.partition_by, node.order_by, node.functions, node.frame)

    if isinstance(node, P.SetOp):
        inputs = []
        for inp in node.inputs:
            inputs.append(prune_columns(inp, {s.name for s in inp.output_symbols}))
        return P.SetOp(node.op, node.distinct, inputs, node.symbols)

    return node
