"""Cost-based join optimization.

Reference: ``core/trino-main/src/main/java/io/trino/sql/planner/iterative/rule/``
— ``ReorderJoins.java`` (DP over the flattened inner-join graph, bounded by
``optimizer.max-reordered-joins``) and ``DetermineJoinDistributionType.java``
(broadcast vs partitioned by build-side size). Estimates come from
:mod:`trino_tpu.planner.stats`.

TPU note: "replicated" build sides become an ``all_gather`` over the mesh
(cheap on ICI for small tables); "partitioned" becomes two ``all_to_all``
hash repartitions. The threshold knob is rows-based
(``broadcast_join_threshold_rows``) since HBM, not heap, is the budget.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from trino_tpu import types as T
from trino_tpu.config import Session
from trino_tpu.ir import RowExpr, special
from trino_tpu.planner import plan as P
from trino_tpu.planner.optimizer import _conjuncts, _replace_sources
from trino_tpu.planner.stats import PlanStats, StatsCalculator, SymbolStats

MAX_REORDERED_JOINS = 8  # reference default 9 (optimizer.max-reordered-joins)


# === DetermineJoinDistributionType =========================================


def determine_join_distribution(
    node: P.PlanNode, stats: StatsCalculator, session: Session
) -> P.PlanNode:
    if isinstance(node, P.Join):
        left = determine_join_distribution(node.left, stats, session)
        right = determine_join_distribution(node.right, stats, session)
        dist = node.distribution
        if dist is None:
            forced = session.get("join_distribution_type")
            if forced == "BROADCAST":
                dist = "replicated"
            elif forced == "PARTITIONED":
                dist = "partitioned"
            else:
                build = stats.stats(node.right)
                threshold = session.get("broadcast_join_threshold_rows")
                if build.row_count is not None:
                    dist = (
                        "replicated"
                        if build.row_count <= threshold
                        else "partitioned"
                    )
            # RIGHT/FULL outer joins must see every build row exactly once
            # per output — replicating the build side would duplicate
            # unmatched build rows across shards (DetermineJoinDistributionType
            # has the same mustPartition rule)
            if node.join_type in ("RIGHT", "FULL"):
                dist = "partitioned"
        return P.Join(
            node.join_type, left, right, node.criteria, node.filter,
            dist, node.mark_symbol, node.null_aware, node.single_row,
        )
    new_sources = [determine_join_distribution(s, stats, session) for s in node.sources]
    if new_sources:
        return _replace_sources(node, new_sources)
    return node


# === ReorderJoins ==========================================================


@dataclasses.dataclass
class _JoinGraph:
    """Flattened maximal inner-join region (ReorderJoins' MultiJoinNode)."""

    leaves: list[P.PlanNode]
    edges: list[tuple[P.Symbol, P.Symbol]]  # equality criteria
    filters: list[RowExpr]  # residual non-equi conjuncts


def reorder_joins(
    node: P.PlanNode, stats: StatsCalculator, session: Session
) -> P.PlanNode:
    if isinstance(node, P.Join) and _flattenable(node):
        graph = _flatten(node)
        graph.leaves = [reorder_joins(l, stats, session) for l in graph.leaves]
        if len(graph.leaves) > 2:
            rebuilt = _order_graph(graph, stats)
            if rebuilt is not None:
                return rebuilt
            # ordering bailed (no estimates / degenerate criteria): rebuild
            # left-deep in syntactic order from the already-recursed leaves
            return _syntactic_rebuild(graph)
        # 2 leaves (node.filter, if any, stays on the node): pick build side
        return _orient_binary(_replace_sources(node, graph.leaves), stats)
    new_sources = [reorder_joins(s, stats, session) for s in node.sources]
    if new_sources:
        return _replace_sources(node, new_sources)
    return node


def _syntactic_rebuild(graph: _JoinGraph) -> P.PlanNode:
    """Left-deep join over leaves in original order, consuming each equality
    edge at the first point both sides are available (inner joins commute)."""
    edges = list(graph.edges)
    acc = graph.leaves[0]
    acc_syms = {s.name for s in acc.output_symbols}
    for leaf in graph.leaves[1:]:
        leaf_syms = {s.name for s in leaf.output_symbols}
        criteria, rest = [], []
        for a, b in edges:
            if a.name in acc_syms and b.name in leaf_syms:
                criteria.append((a, b))
            elif b.name in acc_syms and a.name in leaf_syms:
                criteria.append((b, a))
            else:
                rest.append((a, b))
        edges = rest
        join_type = "INNER" if criteria else "CROSS"
        acc = P.Join(join_type, acc, leaf, criteria, None, None, None)
        acc_syms |= leaf_syms
    return _attach_filters(acc, graph.filters)


def _flattenable(j: P.Join) -> bool:
    # CROSS joins from comma-list FROM flatten too: the reorderer then
    # connects their relations through the real equality edges instead of
    # materializing the syntactic cross product (ReorderJoins.java does
    # the same via MultiJoinNode over INNER+CROSS)
    return (
        j.join_type in ("INNER", "CROSS")
        and j.mark_symbol is None
        and not j.single_row
    )


def _flatten(node: P.PlanNode) -> _JoinGraph:
    if isinstance(node, P.Join) and _flattenable(node):
        left = _flatten(node.left)
        right = _flatten(node.right)
        filters = left.filters + right.filters
        if node.filter is not None:
            filters.extend(_conjuncts(node.filter))
        return _JoinGraph(
            left.leaves + right.leaves,
            left.edges + right.edges + list(node.criteria),
            filters,
        )
    return _JoinGraph([node], [], [])


def _order_graph(graph: _JoinGraph, stats: StatsCalculator) -> Optional[P.PlanNode]:
    n = len(graph.leaves)
    if n > MAX_REORDERED_JOINS:
        return _greedy_order(graph, stats)

    leaf_stats = [stats.stats(l) for l in graph.leaves]
    if any(s.row_count is None for s in leaf_stats):
        return None  # no estimates -> keep syntactic order

    leaf_syms = [{s.name for s in l.output_symbols} for l in graph.leaves]

    def owner(symbol: P.Symbol) -> int:
        for i, syms in enumerate(leaf_syms):
            if symbol.name in syms:
                return i
        return -1

    # edge list as (leaf_i, leaf_j, sym_i, sym_j)
    edges = []
    for a, b in graph.edges:
        ia, ib = owner(a), owner(b)
        if ia < 0 or ib < 0 or ia == ib:
            return None  # degenerate criterion; bail to syntactic order
        edges.append((ia, ib, a, b))

    def ndv(leaf: int, sym: P.Symbol) -> Optional[float]:
        ss = leaf_stats[leaf].symbols.get(sym.name)
        return ss.ndv if ss else None

    def subset_rows(mask: int) -> float:
        rows = 1.0
        for i in range(n):
            if mask >> i & 1:
                rows *= max(leaf_stats[i].row_count, 1.0)
        for ia, ib, a, b in edges:
            if mask >> ia & 1 and mask >> ib & 1:
                la, lb = ndv(ia, a), ndv(ib, b)
                if la is None and lb is None:
                    denom = min(leaf_stats[ia].row_count, leaf_stats[ib].row_count)
                else:
                    denom = max(la or 1.0, lb or 1.0)
                rows /= max(denom, 1.0)
        return rows

    rows_memo = {}

    def rows_of(mask: int) -> float:
        if mask not in rows_memo:
            rows_memo[mask] = subset_rows(mask)
        return rows_memo[mask]

    def connected(mask_a: int, mask_b: int) -> bool:
        for ia, ib, _, _ in edges:
            if (mask_a >> ia & 1 and mask_b >> ib & 1) or (
                mask_a >> ib & 1 and mask_b >> ia & 1
            ):
                return True
        return False

    # DP over subsets: best[mask] = (cost, left_mask) — cost counts the
    # intermediate rows produced building this subset (classic DPsize).
    best: dict[int, tuple[float, Optional[int]]] = {}
    for i in range(n):
        best[1 << i] = (0.0, None)
    full = (1 << n) - 1
    for mask in range(1, full + 1):
        if mask in best or bin(mask).count("1") < 2:
            continue
        best_cost, best_split = float("inf"), None
        sub = (mask - 1) & mask
        while sub:
            other = mask ^ sub
            if sub < other:  # each unordered partition once
                if sub in best and other in best and connected(sub, other):
                    cost = best[sub][0] + best[other][0] + rows_of(mask)
                    if cost < best_cost:
                        best_cost, best_split = cost, sub
            sub = (sub - 1) & mask
        if best_split is not None:
            best[mask] = (best_cost, best_split)
    if full not in best:
        # join graph is disconnected: fall back to greedy (introduces
        # cross joins between components, smallest first)
        return _greedy_order(graph, stats)

    def build(mask: int) -> tuple[P.PlanNode, set[str]]:
        split = best[mask][1]
        if split is None:
            i = mask.bit_length() - 1
            return graph.leaves[i], set(leaf_syms[i])
        a_mask, b_mask = split, mask ^ split
        # larger side probes (left), smaller side builds (right)
        if rows_of(a_mask) < rows_of(b_mask):
            a_mask, b_mask = b_mask, a_mask
        left, lsyms = build(a_mask)
        right, rsyms = build(b_mask)
        criteria = []
        for ia, ib, a, b in edges:
            in_left = a_mask >> ia & 1
            in_right = b_mask >> ib & 1
            if in_left and in_right:
                criteria.append((a, b))
            elif (a_mask >> ib & 1) and (b_mask >> ia & 1):
                criteria.append((b, a))
        join_type = "INNER" if criteria else "CROSS"
        return (
            P.Join(join_type, left, right, criteria, None, None, None),
            lsyms | rsyms,
        )

    out, _ = build(full)
    return _attach_filters(out, graph.filters)


def _greedy_order(graph: _JoinGraph, stats: StatsCalculator) -> Optional[P.PlanNode]:
    """Greedy smallest-intermediate-first (used beyond the DP bound and for
    disconnected graphs)."""
    n = len(graph.leaves)
    leaf_stats = [stats.stats(l) for l in graph.leaves]
    if any(s.row_count is None for s in leaf_stats):
        return None
    leaf_syms = [{s.name for s in l.output_symbols} for l in graph.leaves]

    @dataclasses.dataclass
    class Part:
        node: P.PlanNode
        syms: set[str]
        rows: float
        stats: PlanStats

    parts = [
        Part(l, set(sy), max(st.row_count, 1.0), st)
        for l, sy, st in zip(graph.leaves, leaf_syms, leaf_stats)
    ]
    edges = list(graph.edges)

    def edge_between(a: Part, b: Part):
        crit = []
        rest = []
        for la, lb in edges:
            if la.name in a.syms and lb.name in b.syms:
                crit.append((la, lb))
            elif lb.name in a.syms and la.name in b.syms:
                crit.append((lb, la))
            else:
                rest.append((la, lb))
        return crit, rest

    def est_join_rows(a: Part, b: Part, crit) -> float:
        rows = a.rows * b.rows
        for la, lb in crit:
            sa = a.stats.symbols.get(la.name) or SymbolStats()
            sb = b.stats.symbols.get(lb.name) or SymbolStats()
            if sa.ndv is None and sb.ndv is None:
                denom = min(a.rows, b.rows)
            else:
                denom = max(sa.ndv or 1.0, sb.ndv or 1.0)
            rows /= max(denom, 1.0)
        return rows

    while len(parts) > 1:
        best = None
        for i, j in itertools.combinations(range(len(parts)), 2):
            crit, _ = edge_between(parts[i], parts[j])
            rows = est_join_rows(parts[i], parts[j], crit)
            has_edge = bool(crit)
            key = (not has_edge, rows)  # prefer connected pairs, then size
            if best is None or key < best[0]:
                best = (key, i, j, crit, rows)
        _, i, j, crit, rows = best
        a, b = parts[i], parts[j]
        if a.rows < b.rows:
            a, b = b, a
            crit = [(rb, la) for la, rb in crit]
        _, edges = edge_between(a, b)
        join_type = "INNER" if crit else "CROSS"
        node = P.Join(join_type, a.node, b.node, crit, None, None, None)
        merged_stats = PlanStats(rows, {**a.stats.symbols, **b.stats.symbols})
        merged = Part(node, a.syms | b.syms, max(rows, 1.0), merged_stats)
        parts = [p for k, p in enumerate(parts) if k not in (i, j)] + [merged]
    return _attach_filters(parts[0].node, graph.filters)


def _attach_filters(node: P.PlanNode, filters: list[RowExpr]) -> P.PlanNode:
    if not filters:
        return node
    pred = filters[0]
    for f in filters[1:]:
        pred = special("and", T.BOOLEAN, pred, f)
    return P.Filter(node, pred)


def _orient_binary(node: P.PlanNode, stats: StatsCalculator) -> P.PlanNode:
    """For a 2-leaf inner join: make the smaller side the build (right).
    Mirrors ReorderJoins' side-flip for the trivial case."""
    if not (isinstance(node, P.Join) and node.join_type == "INNER" and node.criteria):
        return node
    ls, rs = stats.stats(node.left), stats.stats(node.right)
    if ls.row_count is None or rs.row_count is None:
        return node
    if ls.row_count < rs.row_count:
        return P.Join(
            "INNER", node.right, node.left,
            [(b, a) for a, b in node.criteria],
            node.filter, node.distribution, None,
        )
    return node


