"""Plan statistics + cost estimation (the CBO substrate).

Reference: ``core/trino-main/src/main/java/io/trino/cost/`` —
``StatsCalculator``, ``FilterStatsCalculator`` (UNKNOWN_FILTER_COEFFICIENT
0.9), ``JoinStatsRule`` (equi-join NDV formula), ``AggregationStatsRule``,
``CostCalculatorUsingExchanges``. Estimates flow bottom-up: connector
``TableStats`` at scans, per-node derivation above.

Estimates are host-side floats — never device data. ``None`` means unknown
(propagated, like Trino's ``Estimate.unknown()``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.ir import Call, Constant, RowExpr, SpecialForm, Variable
from trino_tpu.planner import plan as P
from trino_tpu.planner.optimizer import _conjuncts
from trino_tpu.predicate import Domain, TupleDomain, extract_tuple_domain

UNKNOWN_FILTER_COEFFICIENT = 0.9  # cost/FilterStatsCalculator.java


@dataclasses.dataclass
class SymbolStats:
    """Reference: ``cost/SymbolStatsEstimate``."""

    ndv: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Any = None
    max_value: Any = None


@dataclasses.dataclass
class PlanStats:
    """Reference: ``cost/PlanNodeStatsEstimate``."""

    row_count: Optional[float] = None
    symbols: dict[str, SymbolStats] = dataclasses.field(default_factory=dict)

    def symbol(self, name: str) -> SymbolStats:
        return self.symbols.get(name, SymbolStats())

    def scaled(self, factor: float) -> "PlanStats":
        rc = None if self.row_count is None else self.row_count * factor
        syms = {
            k: SymbolStats(
                None if v.ndv is None else min(v.ndv, rc) if rc is not None else v.ndv,
                v.null_fraction,
                v.min_value,
                v.max_value,
            )
            for k, v in self.symbols.items()
        }
        return PlanStats(rc, syms)


class StatsCalculator:
    """Bottom-up recursive estimation, memoized per plan node identity."""

    def __init__(self, catalogs):
        self.catalogs = catalogs
        # memo keeps the node reference alive: id() alone could be reused
        # by a new node after the original is garbage-collected
        self._memo: dict[int, tuple[P.PlanNode, PlanStats]] = {}

    def stats(self, node: P.PlanNode) -> PlanStats:
        key = id(node)
        if key not in self._memo:
            method = getattr(self, f"_stats_{type(node).__name__.lower()}", None)
            self._memo[key] = (
                node,
                method(node) if method is not None else self._stats_default(node),
            )
        return self._memo[key][1]

    def _stats_default(self, node: P.PlanNode) -> PlanStats:
        srcs = node.sources
        if len(srcs) == 1:
            return self.stats(srcs[0])
        return PlanStats()

    # === leaves ===========================================================

    def _stats_tablescan(self, node: P.TableScan) -> PlanStats:
        try:
            connector = self.catalogs.get(node.catalog)
        except KeyError:
            return PlanStats()
        ts = connector.table_stats(node.schema, node.table)
        if ts is None or ts.row_count is None:
            return PlanStats()
        out = PlanStats(float(ts.row_count))
        for sym, col in zip(node.symbols, node.column_names):
            cs = ts.columns.get(col)
            if cs is not None:
                out.symbols[sym.name] = SymbolStats(
                    cs.distinct_count,
                    cs.null_fraction or 0.0,
                    cs.min_value,
                    cs.max_value,
                )
        if node.constraint is not None and not node.constraint.is_all():
            col_to_sym = {c: s.name for s, c in zip(node.symbols, node.column_names)}
            sel = 1.0
            if node.constraint.is_none():
                return out.scaled(0.0)
            for col, dom in node.constraint.domains.items():
                sname = col_to_sym.get(col)
                ss = out.symbols.get(sname) if sname else None
                sel *= _domain_selectivity(dom, ss)
            out = out.scaled(sel)
        return out

    def _stats_values(self, node: P.Values) -> PlanStats:
        return PlanStats(float(len(node.rows)))

    # === unary ============================================================

    def _stats_filter(self, node: P.Filter) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return src
        res = extract_tuple_domain(_conjuncts(node.predicate))
        sel = 1.0
        for _ in res.remaining:
            sel *= UNKNOWN_FILTER_COEFFICIENT
        # domains already pushed into the scan's constraint were applied by
        # _stats_tablescan — don't double-count them here
        applied_below: set[str] = set()
        if (
            isinstance(node.source, P.TableScan)
            and node.source.constraint is not None
            and not node.source.constraint.is_none()
        ):
            col_to_sym = {
                c: s.name
                for s, c in zip(node.source.symbols, node.source.column_names)
            }
            for col in node.source.constraint.domains:
                if col in col_to_sym:
                    applied_below.add(col_to_sym[col])
        out_symbols = dict(src.symbols)
        if not res.tuple_domain.is_none():
            for name, dom in (res.tuple_domain.domains or {}).items():
                if name in applied_below:
                    continue
                ss = src.symbols.get(name)
                sel *= _domain_selectivity(dom, ss)
                # narrow the symbol's range to the domain span
                span = None if dom.values.is_all else dom.values.span()
                if span is not None:
                    prev = out_symbols.get(name, SymbolStats())
                    out_symbols[name] = SymbolStats(
                        prev.ndv, 0.0,
                        span.low if span.low is not None else prev.min_value,
                        span.high if span.high is not None else prev.max_value,
                    )
        else:
            sel = 0.0
        out = PlanStats(src.row_count, out_symbols).scaled(sel)
        return out

    def _stats_project(self, node: P.Project) -> PlanStats:
        src = self.stats(node.source)
        out = PlanStats(src.row_count)
        for sym, expr in node.assignments:
            if isinstance(expr, Variable):
                if expr.name in src.symbols:
                    out.symbols[sym.name] = src.symbols[expr.name]
        return out

    def _stats_aggregate(self, node: P.Aggregate) -> PlanStats:
        src = self.stats(node.source)
        if not node.group_keys:
            return PlanStats(1.0)
        if src.row_count is None:
            return PlanStats()
        ndv_product = 1.0
        known = True
        for k in node.group_keys:
            ss = src.symbols.get(k.name)
            if ss is None or ss.ndv is None:
                known = False
                break
            ndv_product *= max(ss.ndv, 1.0)
        if not known:
            # AggregationStatsRule falls back: group count unknown -> damp
            rows = max(1.0, src.row_count * 0.1)
        else:
            rows = min(src.row_count, ndv_product)
        out = PlanStats(rows)
        for k in node.group_keys:
            if k.name in src.symbols:
                out.symbols[k.name] = src.symbols[k.name]
        return out

    def _stats_distinct(self, node) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return src
        return PlanStats(max(1.0, src.row_count * 0.1), dict(src.symbols))

    def _stats_limit(self, node: P.Limit) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return PlanStats(float(node.count))
        return PlanStats(min(float(node.count), src.row_count), dict(src.symbols))

    def _stats_topn(self, node: P.TopN) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return PlanStats(float(node.count))
        return PlanStats(min(float(node.count), src.row_count), dict(src.symbols))

    # === join =============================================================

    def _stats_join(self, node: P.Join) -> PlanStats:
        left = self.stats(node.left)
        right = self.stats(node.right)
        if left.row_count is None or right.row_count is None:
            return PlanStats()
        symbols = dict(left.symbols)
        symbols.update(right.symbols)
        if node.join_type in ("SEMI", "ANTI"):
            return PlanStats(max(1.0, left.row_count * 0.5), dict(left.symbols))
        if node.join_type == "CROSS" or not node.criteria:
            rows = left.row_count * right.row_count
            return PlanStats(rows, symbols)
        # JoinStatsRule: rows = L * R / prod(max(ndv_l, ndv_r)) over clauses
        rows = left.row_count * right.row_count
        for lk, rk in node.criteria:
            lndv = (left.symbols.get(lk.name) or SymbolStats()).ndv
            rndv = (right.symbols.get(rk.name) or SymbolStats()).ndv
            if lndv is None and rndv is None:
                # unknown key NDVs: assume PK-FK with the smaller side as PK
                denom = min(left.row_count, right.row_count)
            else:
                denom = max(lndv or 1.0, rndv or 1.0)
            rows /= max(denom, 1.0)
        if node.filter is not None:
            rows *= UNKNOWN_FILTER_COEFFICIENT
        if node.join_type == "LEFT":
            rows = max(rows, left.row_count)
        elif node.join_type == "RIGHT":
            rows = max(rows, right.row_count)
        elif node.join_type == "FULL":
            rows = max(rows, left.row_count, right.row_count)
        return PlanStats(rows, symbols)

    def _stats_setop(self, node: P.SetOp) -> PlanStats:
        parts = [self.stats(i) for i in node.inputs]
        if any(p.row_count is None for p in parts):
            return PlanStats()
        if node.op == "union":
            rows = sum(p.row_count for p in parts)
            if node.distinct:
                rows *= 0.5
        elif node.op == "intersect":
            rows = min(p.row_count for p in parts) * 0.5
        else:  # except
            rows = parts[0].row_count * 0.5
        return PlanStats(max(rows, 0.0))


def _domain_selectivity(dom: Domain, ss: Optional[SymbolStats]) -> float:
    """Fraction of rows satisfying ``dom`` (FilterStatsCalculator shapes)."""
    if dom.is_none():
        return 0.0
    if dom.is_all():
        return 1.0
    null_frac = ss.null_fraction if ss is not None else 0.0
    if dom.values.is_none():  # IS NULL only
        return null_frac if dom.null_allowed else 0.0
    if dom.values.is_all:  # IS NOT NULL
        return 1.0 - (0.0 if dom.null_allowed else null_frac)

    discrete = dom.values.discrete_values()
    if discrete is not None:
        if ss is not None and ss.ndv:
            return min(1.0, len(discrete) / ss.ndv)
        return min(1.0, 0.1 * len(discrete))
    # range: fraction of [min, max] covered
    if (
        ss is not None
        and ss.min_value is not None
        and ss.max_value is not None
        and _is_num(ss.min_value)
        and ss.max_value != ss.min_value
    ):
        width = float(ss.max_value) - float(ss.min_value)
        covered = 0.0
        for r in dom.values.ranges:
            lo = float(r.low) if r.low is not None and _is_num(r.low) else float(ss.min_value)
            hi = float(r.high) if r.high is not None and _is_num(r.high) else float(ss.max_value)
            lo = max(lo, float(ss.min_value))
            hi = min(hi, float(ss.max_value))
            covered += max(0.0, hi - lo)
        return max(0.0, min(1.0, covered / width))
    return 0.25  # unknown-range comparison default


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


class FragmentStatsCalculator(StatsCalculator):
    """Stats over one plan *fragment*: ``RemoteSource`` leaves resolve to
    the producer fragment's root estimate instead of the unknown default,
    so exchange/join/agg capacity seeding (``exec/fragments.py``) sees
    realistic cardinalities on the consumer side of every cut."""

    def __init__(self, catalogs, remote_stats: dict):
        super().__init__(catalogs)
        self._remote = remote_stats

    def _stats_remotesource(self, node) -> PlanStats:
        src = self._remote.get(node.fragment_id)
        if src is None or src.row_count is None:
            return PlanStats()
        # the cut preserves symbol names across the exchange, so per-symbol
        # stats (join-key NDVs) survive by name; unmatched names just drop
        syms = {
            s.name: src.symbols[s.name]
            for s in node.symbols
            if s.name in src.symbols
        }
        return PlanStats(src.row_count, syms)


def fragment_output_stats(sub, catalogs) -> dict:
    """Root-row estimates per fragment id for a fragmented plan, computed
    bottom-up over the fragment tree (children first, so every
    ``RemoteSource`` resolves against its producer's estimate)."""
    out: dict = {}

    def walk(sp) -> None:
        for child in sp.children:
            walk(child)
        out[sp.fragment.id] = FragmentStatsCalculator(catalogs, out).stats(
            sp.fragment.root
        )

    walk(sub)
    return out
