"""Plan sanity checkers: typed validation after every optimizer stage.

Reference: ``io.trino.sql.planner.sanity/`` — ``PlanSanityChecker`` runs a
battery of checkers (``TypeValidator``, ``ValidateDependenciesChecker``,
``NoDuplicatePlanNodeIdsChecker``, ``ValidateAggregationsWithDefaultValues``,
…) after each optimizer stage and before execution, so a broken rewrite
fails fast with a typed error instead of a wrong answer at runtime.

Here the battery is:

- ``ValidateDependenciesChecker`` — every symbol a node consumes must be
  produced by its sources (the reference checker of the same name).
- ``TypeValidator`` — bottom-up type propagation: every ``Variable``
  reference must carry the producing symbol's type; node-level typing
  rules (boolean predicates, Project assignment types, comparable join
  criteria) must hold.
- ``NoDuplicatePlanNodesChecker`` — no plan-node *object* may appear at
  two positions in the tree. Our nodes have no ids; aliasing a subtree is
  the analog of the reference's duplicated-plan-node-id bug (the exact
  hazard ``planner/plan.py`` ``instantiate()`` exists to prevent).
- ``AggregationChecker`` — aggregation well-formedness: group keys come
  from the source, aggregate kinds are known, input dtypes are valid for
  the function, partial/final accumulator symbols are consistent.
- ``Decimal128Checker`` — DECIMAL precision/scale invariants for the
  ``ops/decimal128.py`` lowerings: 0 <= scale <= precision <= 38 and the
  reference scale-derivation rules for decimal arithmetic.
- ``ExchangeConsistencyChecker`` — Exchange/RemoteSource shape rules in
  whole plans, plus cross-fragment agreement (``validate_fragments``):
  every RemoteSource must match its feeding fragment's output exchange
  kind, hash keys, and column list.

Entry points mirror the reference's ``validateIntermediatePlan`` /
``validateFinalPlan`` (+ a fragment-tree variant):
``PlanSanityChecker.validate_intermediate`` runs after each optimizer
stage, ``validate_final`` after the last one, ``validate_fragments`` after
fragmentation, and ``validate_deserialized`` on the worker after a
fragment comes off the wire (``planner/serde.py``). All are gated by the
``plan_validation`` session property (on by default).
"""

from __future__ import annotations

from typing import Iterator, Optional

from trino_tpu import types as T
from trino_tpu.ir import Call, Constant, RowExpr, SpecialForm, Variable
from trino_tpu.ops.aggregation import AGG_KINDS
from trino_tpu.planner import plan as P


class PlanValidationError(Exception):
    """A sanity checker rejected a plan.

    Carries the checker's name and the path of node-type names from the
    plan root to the offending node, so the failure points at the broken
    rewrite rather than at a wrong answer downstream.
    """

    def __init__(self, checker: str, message: str, path: str = "", stage: str = ""):
        self.checker = checker
        self.message = message
        self.path = path
        self.stage = stage
        loc = f" at {path}" if path else ""
        st = f" (after {stage})" if stage else ""
        super().__init__(f"[{checker}]{st}{loc}: {message}")


def validation_enabled(session) -> bool:
    if session is None:
        return True
    try:
        return bool(session.get("plan_validation"))
    except KeyError:
        return True


# === tree walking with paths ================================================


def _children(node: P.PlanNode) -> list[tuple[str, P.PlanNode]]:
    """(slot label, child) pairs — labels make error paths readable."""
    if isinstance(node, P.Join):
        return [("left", node.left), ("right", node.right)]
    if isinstance(node, P.SetOp):
        return [(f"inputs[{i}]", c) for i, c in enumerate(node.inputs)]
    return [("source", s) for s in node.sources]


def _walk(node: P.PlanNode, path: tuple[str, ...] = ()) -> Iterator[tuple[P.PlanNode, tuple[str, ...]]]:
    here = path + (type(node).__name__,)
    yield node, here
    for label, child in _children(node):
        yield from _walk(child, here)


def _fmt_path(path: tuple[str, ...]) -> str:
    return ">".join(path)


def _exprs_of(node: P.PlanNode) -> list[RowExpr]:
    """Every RowExpr the node evaluates (not its children's)."""
    out: list[RowExpr] = []
    if isinstance(node, P.Filter):
        out.append(node.predicate)
    elif isinstance(node, P.Project):
        out.extend(e for _, e in node.assignments)
    elif isinstance(node, P.Aggregate):
        if node.step != "final":
            for _, fn in node.aggregates:
                if fn.argument is not None:
                    out.append(fn.argument)
                if fn.filter is not None:
                    out.append(fn.filter)
    elif isinstance(node, P.Join):
        if node.filter is not None:
            out.append(node.filter)
    elif isinstance(node, P.Window):
        for _, fn in node.functions:
            if fn.argument is not None:
                out.append(fn.argument)
            if fn.default is not None:
                out.append(fn.default)
    elif isinstance(node, P.Unnest):
        out.extend(node.array_exprs)
    elif isinstance(node, P.TableScan):
        if node.pushed_predicate is not None:
            out.append(node.pushed_predicate)
    return out


def _walk_expr(e: RowExpr) -> Iterator[RowExpr]:
    yield e
    if isinstance(e, (Call, SpecialForm)):
        for a in e.args:
            yield from _walk_expr(a)


def _source_symbols(node: P.PlanNode) -> dict[str, T.SqlType]:
    env: dict[str, T.SqlType] = {}
    for s in node.sources:
        for sym in s.output_symbols:
            env[sym.name] = sym.type
    return env


# === checkers ===============================================================


class Checker:
    name = "Checker"

    def check(self, root: P.PlanNode) -> None:
        raise NotImplementedError

    def fail(self, message: str, path: tuple[str, ...] = ()) -> None:
        raise PlanValidationError(self.name, message, _fmt_path(path))


class ValidateDependenciesChecker(Checker):
    """Every symbol a node consumes is produced by its sources.

    Reference: ``sanity/ValidateDependenciesChecker.java``.
    """

    name = "ValidateDependenciesChecker"

    def check(self, root: P.PlanNode) -> None:
        for node, path in _walk(root):
            produced = set(_source_symbols(node))
            for needed, what in self._consumed(node):
                if needed not in produced:
                    self.fail(
                        f"{what} references symbol '{needed}' not produced "
                        f"by the node's sources (available: {sorted(produced)[:12]})",
                        path,
                    )
            self._check_scoped(node, path)

    def _consumed(self, node: P.PlanNode) -> list[tuple[str, str]]:
        out: list[tuple[str, str]] = []

        def refs(e: Optional[RowExpr], what: str) -> None:
            if e is None:
                return
            for sub in _walk_expr(e):
                if isinstance(sub, Variable):
                    out.append((sub.name, what))

        if isinstance(node, P.Filter):
            refs(node.predicate, "filter predicate")
        elif isinstance(node, P.Project):
            for s, e in node.assignments:
                refs(e, f"projection '{s.name}'")
        elif isinstance(node, P.Aggregate):
            for k in node.group_keys:
                out.append((k.name, "group-by key"))
            if node.step == "final" and node.acc_symbols is not None:
                # a final step consumes the partial's accumulator columns
                # off the exchange, not the original aggregate inputs
                for v, c in node.acc_symbols:
                    out.append((v.name, "accumulator value"))
                    if c is not None:
                        out.append((c.name, "accumulator count"))
            else:
                for s, fn in node.aggregates:
                    refs(fn.argument, f"aggregate '{s.name}' argument")
                    refs(fn.filter, f"aggregate '{s.name}' filter")
        elif isinstance(node, P.Sort):
            for o in node.order_by:
                out.append((o.symbol.name, "sort key"))
        elif isinstance(node, P.TopN):
            for o in node.order_by:
                out.append((o.symbol.name, "topn key"))
        elif isinstance(node, P.Window):
            for s in node.partition_by:
                out.append((s.name, "window partition key"))
            for o in node.order_by:
                out.append((o.symbol.name, "window order key"))
            for s, fn in node.functions:
                refs(fn.argument, f"window function '{s.name}' argument")
                refs(fn.default, f"window function '{s.name}' default")
        elif isinstance(node, P.Output):
            for s in node.symbols:
                out.append((s.name, "output column"))
        elif isinstance(node, P.GroupId):
            for s in node.all_keys:
                out.append((s.name, "grouping key"))
            for g in node.groups:
                for s in g:
                    out.append((s.name, "grouping-set key"))
        elif isinstance(node, P.Exchange):
            for s in node.keys:
                out.append((s.name, "exchange hash key"))
        elif isinstance(node, P.Unnest):
            for e in node.array_exprs:
                refs(e, "unnest array expression")
        return out

    def _check_scoped(self, node: P.PlanNode, path: tuple[str, ...]) -> None:
        """Join criteria/filter must split across the correct sides."""
        if isinstance(node, P.Join):
            left = {s.name for s in node.left.output_symbols}
            right = {s.name for s in node.right.output_symbols}
            for a, b in node.criteria:
                if a.name not in left:
                    self.fail(
                        f"join criterion left symbol '{a.name}' not produced "
                        f"by the left side", path,
                    )
                if b.name not in right:
                    self.fail(
                        f"join criterion right symbol '{b.name}' not produced "
                        f"by the right side", path,
                    )
        if isinstance(node, P.SetOp):
            for i, inp in enumerate(node.inputs):
                if len(inp.output_symbols) != len(node.symbols):
                    self.fail(
                        f"set-op input {i} produces {len(inp.output_symbols)} "
                        f"columns, node declares {len(node.symbols)}", path,
                    )


class TypeValidator(Checker):
    """Recompute types bottom-up and diff against declarations.

    Reference: ``sanity/TypeValidator.java``.
    """

    name = "TypeValidator"

    def check(self, root: P.PlanNode) -> None:
        for node, path in _walk(root):
            env = _source_symbols(node)
            for e in _exprs_of(node):
                for sub in _walk_expr(e):
                    if isinstance(sub, Variable) and sub.name in env:
                        if sub.type != env[sub.name]:
                            self.fail(
                                f"variable '{sub.name}' declared {sub.type} "
                                f"but its producer outputs {env[sub.name]}",
                                path,
                            )
            self._check_node(node, env, path)

    def _check_node(self, node: P.PlanNode, env, path) -> None:
        def boolean(e: Optional[RowExpr], what: str) -> None:
            if e is not None and e.type not in (T.BOOLEAN, T.UNKNOWN):
                self.fail(f"{what} has type {e.type}, expected boolean", path)

        if isinstance(node, P.Filter):
            boolean(node.predicate, "filter predicate")
        elif isinstance(node, P.Join):
            boolean(node.filter, "join filter")
            for a, b in node.criteria:
                if T.common_super_type(a.type, b.type) is None:
                    self.fail(
                        f"join criterion ({a.name}, {b.name}) compares "
                        f"incomparable types {a.type} and {b.type}", path,
                    )
        elif isinstance(node, P.Project):
            for s, e in node.assignments:
                if s.type != e.type:
                    self.fail(
                        f"projection '{s.name}' declares {s.type} but its "
                        f"expression evaluates to {e.type}", path,
                    )
        elif isinstance(node, P.Output):
            if len(node.column_names) != len(node.symbols):
                self.fail(
                    f"{len(node.column_names)} column names for "
                    f"{len(node.symbols)} symbols", path,
                )
        elif isinstance(node, P.TableScan):
            if len(node.symbols) != len(node.column_names):
                self.fail(
                    f"{len(node.symbols)} symbols for "
                    f"{len(node.column_names)} connector columns", path,
                )
        elif isinstance(node, P.Values):
            for row in node.rows:
                if len(row) != len(node.symbols):
                    self.fail(
                        f"values row has {len(row)} fields, node declares "
                        f"{len(node.symbols)} symbols", path,
                    )
        elif isinstance(node, P.Sort) or isinstance(node, P.TopN):
            for o in node.order_by:
                if not T.is_orderable(o.symbol.type) and not isinstance(
                    o.symbol.type, T.UnknownType
                ):
                    self.fail(
                        f"sort key '{o.symbol.name}' of type {o.symbol.type} "
                        f"is not orderable", path,
                    )


class NoDuplicatePlanNodesChecker(Checker):
    """No plan-node object may appear at two positions in the tree.

    Reference: ``sanity/NoDuplicatePlanNodeIdsChecker.java``. Our nodes
    carry no ids, so identity stands in: the same object reachable through
    two parents means a rewrite aliased a subtree instead of cloning it
    (``instantiate()``), which breaks per-reference symbol ownership.
    """

    name = "NoDuplicatePlanNodesChecker"

    def check(self, root: P.PlanNode) -> None:
        seen: dict[int, str] = {}
        for node, path in _walk(root):
            key = id(node)
            if key in seen:
                self.fail(
                    f"plan node {type(node).__name__} appears twice "
                    f"(also at {seen[key]}) — a rewrite aliased a subtree "
                    f"instead of cloning it", path,
                )
            seen[key] = _fmt_path(path)


class AggregationChecker(Checker):
    """Aggregation well-formedness (reference:
    ``sanity/ValidateAggregationsWithDefaultValues.java`` and the
    AggregationNode constructor invariants)."""

    name = "AggregationChecker"

    _NUMERIC_ONLY = ("sum", "avg")
    _KNOWN = tuple(AGG_KINDS) + ("array_agg",)

    def check(self, root: P.PlanNode) -> None:
        for node, path in _walk(root):
            if not isinstance(node, P.Aggregate):
                continue
            produced = set(_source_symbols(node))
            for k in node.group_keys:
                if k.name not in produced:
                    self.fail(
                        f"group-by key '{k.name}' not produced by the "
                        f"aggregation source", path,
                    )
            if node.step not in ("single", "partial", "final"):
                self.fail(f"unknown aggregation step '{node.step}'", path)
            for s, fn in node.aggregates:
                if fn.kind not in self._KNOWN:
                    self.fail(
                        f"aggregate '{s.name}' has unknown kind "
                        f"'{fn.kind}' (known: {self._KNOWN})", path,
                    )
                if fn.kind in ("count", "count_star") and fn.result_type != T.BIGINT:
                    self.fail(
                        f"aggregate '{s.name}' ({fn.kind}) must produce "
                        f"bigint, declares {fn.result_type}", path,
                    )
                arg_t = fn.argument.type if fn.argument is not None else None
                if fn.kind in self._NUMERIC_ONLY and arg_t is not None:
                    if not T.is_numeric(arg_t) and not isinstance(arg_t, T.UnknownType):
                        self.fail(
                            f"aggregate '{s.name}' ({fn.kind}) over "
                            f"non-numeric input type {arg_t}", path,
                        )
                if fn.kind in ("min", "max") and arg_t is not None:
                    if not T.is_orderable(arg_t) and not isinstance(
                        arg_t, (T.UnknownType, T.ArrayType, T.MapType, T.RowType)
                    ):
                        self.fail(
                            f"aggregate '{s.name}' ({fn.kind}) over "
                            f"non-orderable input type {arg_t}", path,
                        )
                if fn.filter is not None and fn.filter.type not in (
                    T.BOOLEAN, T.UNKNOWN
                ):
                    self.fail(
                        f"aggregate '{s.name}' filter has type "
                        f"{fn.filter.type}, expected boolean", path,
                    )
            if node.step in ("partial", "final") and node.acc_symbols is not None:
                if len(node.acc_symbols) != len(node.aggregates):
                    self.fail(
                        f"{len(node.acc_symbols)} accumulator pairs for "
                        f"{len(node.aggregates)} aggregates", path,
                    )
                for (s, fn), (v, c) in zip(node.aggregates, node.acc_symbols):
                    if fn.kind in ("count", "count_star") and c is not None:
                        self.fail(
                            f"count accumulator for '{s.name}' must not "
                            f"carry a separate count column", path,
                        )


class Decimal128Checker(Checker):
    """DECIMAL precision/scale invariants for the decimal128 lowerings.

    The engine stores DECIMAL(p<=18) as int64 scaled integers and p>18 as
    (hi, lo) int64 limb pairs (``ops/decimal128.py``); both require
    0 <= scale <= precision <= 38. Arithmetic results must follow the
    reference scale derivation (``DecimalOperators``): add/sub take
    max(s1, s2), multiply takes s1+s2, divide/modulus take max(s1, s2) —
    a rewrite that drops a rescale produces silently shifted values.
    """

    name = "Decimal128Checker"

    def check(self, root: P.PlanNode) -> None:
        for node, path in _walk(root):
            for sym in node.output_symbols:
                self._check_type(sym.type, f"symbol '{sym.name}'", path)
            for e in _exprs_of(node):
                for sub in _walk_expr(e):
                    self._check_type(sub.type, "expression", path)
                    if isinstance(sub, Constant):
                        self._check_constant(sub, path)
                    if isinstance(sub, Call):
                        self._check_arith(sub, path)

    def _check_type(self, t: T.SqlType, what: str, path) -> None:
        if not isinstance(t, T.DecimalType):
            return
        if not (0 <= t.scale <= t.precision <= 38):
            self.fail(
                f"{what} has invalid decimal({t.precision},{t.scale}): "
                f"requires 0 <= scale <= precision <= 38", path,
            )

    def _check_constant(self, c: Constant, path) -> None:
        t = c.type
        if not isinstance(t, T.DecimalType) or c.value is None:
            return
        if not isinstance(c.value, int):
            self.fail(
                f"decimal constant stores {type(c.value).__name__}, "
                f"expected an unscaled int", path,
            )
        elif abs(c.value) >= 10 ** t.precision:
            self.fail(
                f"decimal constant {c.value} exceeds {t.precision} digits "
                f"declared by {t}", path,
            )

    def _check_arith(self, call: Call, path) -> None:
        if call.name not in ("add", "subtract", "multiply", "divide", "modulus"):
            return
        if len(call.args) != 2 or not isinstance(call.type, T.DecimalType):
            return
        scales = []
        for a in call.args:
            if isinstance(a.type, T.DecimalType):
                scales.append(a.type.scale)
            elif T.is_integer(a.type):
                scales.append(0)
            else:
                return  # double/real operands produce double, not decimal
        if call.name == "multiply":
            want = scales[0] + scales[1]
        else:
            want = max(scales)
        if call.type.scale != want:
            self.fail(
                f"decimal {call.name} over scales {scales} must produce "
                f"scale {want}, declares {call.type}", path,
            )


class ExchangeConsistencyChecker(Checker):
    """Exchange/RemoteSource shape rules inside one plan tree."""

    name = "ExchangeConsistencyChecker"

    _PARTITIONINGS = ("hash", "broadcast", "single", "round_robin")

    def check(self, root: P.PlanNode) -> None:
        for node, path in _walk(root):
            if isinstance(node, P.Exchange):
                if node.partitioning not in self._PARTITIONINGS:
                    self.fail(
                        f"unknown exchange partitioning "
                        f"'{node.partitioning}'", path,
                    )
                if node.partitioning == "hash" and not node.keys:
                    self.fail("hash exchange with no hash keys", path)
                if node.partitioning != "hash" and node.keys:
                    self.fail(
                        f"{node.partitioning} exchange must not carry "
                        f"hash keys", path,
                    )
            if isinstance(node, P.RemoteSource):
                if node.exchange_type not in (
                    "hash", "broadcast", "single", "source"
                ):
                    self.fail(
                        f"unknown remote-source exchange type "
                        f"'{node.exchange_type}'", path,
                    )
                if node.exchange_type == "hash" and not node.keys:
                    self.fail("hash remote source with no hash keys", path)


# === fragment-tree validation ===============================================


def _validate_fragment_tree(subplan) -> None:
    """Cross-fragment agreement: RemoteSource ↔ feeding fragment.

    Reference intent: a fragment boundary is a contract — the consumer's
    RemoteSource and the producer's output exchange must agree on exchange
    kind, hash keys, and column list, or rows land on the wrong shard (or
    in the wrong columns) at runtime.
    """
    checker = ExchangeConsistencyChecker()
    fragments = {}
    for frag in subplan.all_fragments():
        if frag.id in fragments:
            raise PlanValidationError(
                checker.name, f"duplicate fragment id {frag.id}"
            )
        fragments[frag.id] = frag
    for frag in subplan.all_fragments():
        for node, path in _walk(frag.root):
            if not isinstance(node, P.RemoteSource):
                continue
            where = (f"Fragment {frag.id}",) + path
            child = fragments.get(node.fragment_id)
            if child is None:
                raise PlanValidationError(
                    checker.name,
                    f"remote source references unknown fragment "
                    f"{node.fragment_id}", _fmt_path(where),
                )
            if child.output_exchange != node.exchange_type:
                raise PlanValidationError(
                    checker.name,
                    f"remote source expects '{node.exchange_type}' rows but "
                    f"fragment {child.id} ships "
                    f"'{child.output_exchange}'", _fmt_path(where),
                )
            want_keys = [s.name for s in node.keys]
            have_keys = [s.name for s in child.output_keys]
            if want_keys != have_keys:
                raise PlanValidationError(
                    checker.name,
                    f"remote source hash keys {want_keys} disagree with "
                    f"fragment {child.id} output keys {have_keys}",
                    _fmt_path(where),
                )
            want_cols = [s.name for s in node.symbols]
            have_cols = [s.name for s in child.root.output_symbols]
            if want_cols != have_cols:
                raise PlanValidationError(
                    checker.name,
                    f"remote source columns {want_cols[:8]} disagree with "
                    f"fragment {child.id} output columns {have_cols[:8]}",
                    _fmt_path(where),
                )


# === entry points ===========================================================


class PlanSanityChecker:
    """The checker battery (reference: ``sanity/PlanSanityChecker.java``)."""

    INTERMEDIATE: tuple[Checker, ...] = (
        ValidateDependenciesChecker(),
        NoDuplicatePlanNodesChecker(),
        TypeValidator(),
        AggregationChecker(),
        Decimal128Checker(),
    )
    FINAL: tuple[Checker, ...] = INTERMEDIATE + (ExchangeConsistencyChecker(),)

    @classmethod
    def _run(cls, checkers, plan: P.PlanNode, stage: str) -> None:
        for checker in checkers:
            try:
                checker.check(plan)
            except PlanValidationError as e:
                if stage and not e.stage:
                    raise PlanValidationError(
                        e.checker, e.message, e.path, stage
                    ) from None
                raise

    @classmethod
    def validate_intermediate(cls, plan: P.PlanNode, stage: str = "") -> None:
        """Run after each optimizer stage (reference:
        validateIntermediatePlan)."""
        cls._run(cls.INTERMEDIATE, plan, stage)

    @classmethod
    def validate_final(cls, plan: P.PlanNode, stage: str = "optimizer") -> None:
        """Run on the fully optimized plan before fragmentation/execution."""
        cls._run(cls.FINAL, plan, stage)

    @classmethod
    def validate_fragments(cls, subplan) -> None:
        """Run on the fragment tree after ``fragment_plan``."""
        _validate_fragment_tree(subplan)
        for frag in subplan.all_fragments():
            cls._run(cls.FINAL, frag.root, f"fragmentation (fragment {frag.id})")

    @classmethod
    def validate_deserialized(cls, fragment) -> None:
        """Worker-side: one fragment straight off the wire
        (``planner/serde.py`` / TaskUpdateRequest). Cross-fragment checks
        need the whole tree, so only node-local checkers run here."""
        cls._run(cls.FINAL, fragment.root, f"deserialization (fragment {fragment.id})")
