"""Logical plan nodes.

Reference: ``core/trino-main/src/main/java/io/trino/sql/planner/plan/``
(TableScanNode, FilterNode, ProjectNode, AggregationNode, JoinNode,
SortNode, TopNNode, LimitNode, ExchangeNode, OutputNode, ValuesNode, …).

Every node exposes ``output_symbols`` — a list of :class:`Symbol` (name +
type). Expressions inside nodes are RowExpr trees over ``Variable``
references to those symbols; the physical planner binds them to channels.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.ir import RowExpr
from trino_tpu.ops.sort import SortKey

_counter = itertools.count()


def fresh_name(base: str) -> str:
    return f"{base}_{next(_counter)}"


@dataclasses.dataclass(frozen=True)
class Symbol:
    name: str
    type: T.SqlType

    def __repr__(self):
        return f"{self.name}:{self.type}"


@dataclasses.dataclass(frozen=True)
class Ordering:
    symbol: Symbol
    ascending: bool = True
    nulls_first: bool = False

    def sort_key(self) -> SortKey:
        return SortKey(ascending=self.ascending, nulls_first=self.nulls_first)


class PlanNode:
    @property
    def output_symbols(self) -> list[Symbol]:
        raise NotImplementedError

    @property
    def sources(self) -> list["PlanNode"]:
        return []


@dataclasses.dataclass
class TableScan(PlanNode):
    """Scan of a connector table.

    ``table`` is a connector-specific handle; ``assignments`` maps each
    output symbol to the connector column name.
    Reference: ``plan/TableScanNode.java``.
    """

    catalog: str
    schema: str
    table: str
    symbols: list[Symbol]
    column_names: list[str]
    # predicate pushed into the connector (reference: TupleDomain pushdown)
    pushed_predicate: Optional[RowExpr] = None
    # extracted TupleDomain over *column names*, for split pruning and
    # connector applyFilter (reference: PushPredicateIntoTableScan.java);
    # advisory — the enclosing Filter still applies the full predicate
    constraint: Optional[Any] = None
    # connector applyLimit / applyTopN hints (reference:
    # ConnectorMetadata.java:1064,1090); guarantee-free — the Limit/TopN
    # node above still enforces, the scan just reads less
    limit: Optional[int] = None
    topn: Optional[list] = None  # [(column_name, ascending)]

    @property
    def output_symbols(self):
        return self.symbols


@dataclasses.dataclass
class Values(PlanNode):
    symbols: list[Symbol]
    rows: list[list[Any]]  # storage-representation python values

    @property
    def output_symbols(self):
        return self.symbols


@dataclasses.dataclass
class Filter(PlanNode):
    source: PlanNode
    predicate: RowExpr

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Project(PlanNode):
    source: PlanNode
    assignments: list[tuple[Symbol, RowExpr]]

    @property
    def output_symbols(self):
        return [s for s, _ in self.assignments]

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass(frozen=True)
class AggFunction:
    """One aggregate: kind in ops.aggregation.AGG_KINDS, argument expression
    (None for count(*)), marks distinct/filter (reference:
    ``plan/AggregationNode.Aggregation``)."""

    kind: str
    argument: Optional[RowExpr]
    result_type: T.SqlType
    distinct: bool = False
    filter: Optional[RowExpr] = None


@dataclasses.dataclass
class Aggregate(PlanNode):
    """Group-by aggregation. step: 'single' | 'partial' | 'final'.

    For the partial/final split (the reference's AccumulatorState shipping,
    ``spi/function/AccumulatorStateSerializer.java``), ``acc_symbols`` names
    the accumulator columns on the wire between the two steps: one
    ``(value, count)`` pair per aggregate (count carries NULL semantics and
    avg denominators; for count/count_star the value IS the count and the
    second symbol is None). A partial node *outputs* them; the matching
    final node *consumes* them."""

    source: PlanNode
    group_keys: list[Symbol]
    aggregates: list[tuple[Symbol, AggFunction]]
    step: str = "single"
    acc_symbols: Optional[list[tuple[Symbol, Optional[Symbol]]]] = None

    @property
    def output_symbols(self):
        if self.step == "partial" and self.acc_symbols is not None:
            out = list(self.group_keys)
            for v, c in self.acc_symbols:
                out.append(v)
                if c is not None:
                    out.append(c)
            return out
        return self.group_keys + [s for s, _ in self.aggregates]

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Join(PlanNode):
    """Equi-join. criteria is a list of (left_symbol, right_symbol) pairs;
    ``filter`` is an extra non-equi condition over both sides' symbols.
    distribution: None (undecided) | 'partitioned' | 'replicated'.
    Reference: ``plan/JoinNode.java``."""

    join_type: str  # INNER | LEFT | RIGHT | FULL | CROSS | SEMI | ANTI
    left: PlanNode
    right: PlanNode
    criteria: list[tuple[Symbol, Symbol]]
    filter: Optional[RowExpr] = None
    distribution: Optional[str] = None
    # for SEMI/ANTI: the output mark symbol replaces right outputs
    mark_symbol: Optional[Symbol] = None
    # SEMI/ANTI mark semantics: True = 3-valued IN (NULL keys/build-NULLs
    # yield NULL marks); False = 2-valued EXISTS (TRUE/FALSE only)
    null_aware: bool = True
    # scalar-subquery join: error if a probe row matches >1 build row
    # (reference: EnforceSingleRowNode)
    single_row: bool = False

    @property
    def output_symbols(self):
        if self.join_type in ("SEMI", "ANTI"):
            return self.left.output_symbols + (
                [self.mark_symbol] if self.mark_symbol else []
            )
        return self.left.output_symbols + self.right.output_symbols

    @property
    def sources(self):
        return [self.left, self.right]


@dataclasses.dataclass
class GroupId(PlanNode):
    """Replicates input rows once per grouping set, nulling the key columns
    absent from each set and emitting a group-id column.
    Reference: ``plan/GroupIdNode.java`` + ``operator/GroupIdOperator.java``."""

    source: PlanNode
    groups: list[list[Symbol]]  # key subset per grouping set
    all_keys: list[Symbol]
    gid: Symbol

    @property
    def output_symbols(self):
        return self.source.output_symbols + [self.gid]

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Sort(PlanNode):
    source: PlanNode
    order_by: list[Ordering]

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class TopN(PlanNode):
    source: PlanNode
    count: int
    order_by: list[Ordering]
    step: str = "single"  # single | partial | final

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Limit(PlanNode):
    source: PlanNode
    count: Optional[int]
    offset: int = 0

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Distinct(PlanNode):
    source: PlanNode

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class SetOp(PlanNode):
    op: str  # UNION | INTERSECT | EXCEPT
    distinct: bool
    inputs: list[PlanNode]
    symbols: list[Symbol]
    # per-input mapping: input.output_symbols[i] feeds symbols[i]

    @property
    def output_symbols(self):
        return self.symbols

    @property
    def sources(self):
        return self.inputs


@dataclasses.dataclass(frozen=True)
class WindowFunction:
    kind: str  # rank, row_number, dense_rank, sum, avg, min, max, count, lead, lag
    argument: Optional[RowExpr]
    result_type: T.SqlType
    offset: int = 1  # for lead/lag
    default: Optional[RowExpr] = None


@dataclasses.dataclass
class Window(PlanNode):
    source: PlanNode
    partition_by: list[Symbol]
    order_by: list[Ordering]
    functions: list[tuple[Symbol, WindowFunction]]
    frame: Optional[tuple[str, str, str]] = None

    @property
    def output_symbols(self):
        return self.source.output_symbols + [s for s, _ in self.functions]

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Output(PlanNode):
    """Root node fixing column names/order for the client.
    Reference: ``plan/OutputNode.java``."""

    source: PlanNode
    column_names: list[str]
    symbols: list[Symbol]

    @property
    def output_symbols(self):
        return self.symbols

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class Unnest(PlanNode):
    """Expand array values into rows (reference:
    ``operator/unnest/UnnestOperator.java:39``). Each source row is
    replicated once per element of each unnested array (arrays zipped
    positionally when several are given, NULL-padded to the longest);
    ``ordinality`` adds a 1-based position column."""

    source: PlanNode
    array_exprs: list[RowExpr]  # over source symbols, ARRAY-typed
    element_symbols: list[Symbol]
    ordinality: Optional[Symbol] = None

    @property
    def output_symbols(self):
        out = self.source.output_symbols + self.element_symbols
        if self.ordinality is not None:
            out = out + [self.ordinality]
        return out

    @property
    def sources(self):
        return [self.source]


@dataclasses.dataclass
class RemoteSource(PlanNode):
    """Leaf standing in for another fragment's output
    (reference: ``plan/RemoteSourceNode.java``). ``exchange_type`` records
    how the feeding fragment's rows arrive: 'hash' (co-partitioned by
    ``keys`` over the mesh), 'broadcast' (replicated), 'single' (gathered),
    or 'source' (left in the producer's scan partitioning)."""

    fragment_id: int
    symbols: list[Symbol]
    exchange_type: str = "single"
    keys: list[Symbol] = dataclasses.field(default_factory=list)

    @property
    def output_symbols(self):
        return self.symbols


@dataclasses.dataclass
class Exchange(PlanNode):
    """Repartitioning boundary (reference: ``plan/ExchangeNode.java``).

    scope: 'remote' (cross-shard collective) | 'local' (within shard —
    usually elided on TPU, XLA handles intra-chip parallelism).
    partitioning: 'hash' (keys), 'broadcast', 'single', 'round_robin'.
    """

    source: PlanNode
    partitioning: str
    keys: list[Symbol] = dataclasses.field(default_factory=list)
    scope: str = "remote"

    @property
    def output_symbols(self):
        return self.source.output_symbols

    @property
    def sources(self):
        return [self.source]


def walk_plan(node: PlanNode):
    yield node
    for s in node.sources:
        yield from walk_plan(s)


def node_label(node: PlanNode) -> str:
    """One-line description of a node (PlanPrinter's node header)."""
    name = type(node).__name__
    detail = ""
    if isinstance(node, TableScan):
        detail = f" {node.catalog}.{node.schema}.{node.table}"
    elif isinstance(node, Filter):
        detail = f" predicate={node.predicate!r}"
    elif isinstance(node, Aggregate):
        detail = f" keys={[s.name for s in node.group_keys]} step={node.step}"
    elif isinstance(node, Join):
        detail = (
            f" {node.join_type}"
            f" criteria={[(a.name, b.name) for a, b in node.criteria]}"
            + (f" dist={node.distribution}" if node.distribution else "")
        )
    elif isinstance(node, (TopN,)):
        detail = f" n={node.count}"
    elif isinstance(node, Limit):
        detail = f" n={node.count}"
    elif isinstance(node, Exchange):
        detail = f" {node.scope}/{node.partitioning} keys={[s.name for s in node.keys]}"
    elif isinstance(node, RemoteSource):
        detail = (
            f" fragment={node.fragment_id} {node.exchange_type}"
            + (f" keys={[s.name for s in node.keys]}" if node.keys else "")
        )
    elif isinstance(node, Output):
        detail = f" columns={node.column_names}"
    return f"{name}{detail} -> {[s.name for s in node.output_symbols][:8]}"


def plan_text(node: PlanNode, indent: int = 0) -> str:
    """EXPLAIN-style tree rendering (reference: planprinter/PlanPrinter.java)."""
    pad = "  " * indent
    lines = [f"{pad}{node_label(node)}"]
    for s in node.sources:
        lines.append(plan_text(s, indent + 1))
    return "\n".join(lines)


def replace_sources(node: PlanNode, new_sources: list["PlanNode"]) -> PlanNode:
    """Shallow-copy ``node`` with its child nodes swapped (used by the
    Memo's group-reference rewrites and the whole-plan passes)."""
    import copy

    out = copy.copy(node)
    if isinstance(node, Join):
        out.left, out.right = new_sources
    elif isinstance(node, SetOp):
        out.inputs = list(new_sources)
    elif hasattr(node, "source") and new_sources:
        out.source = new_sources[0]
    return out


# === CTE re-instantiation ===================================================


def instantiate(node: PlanNode) -> tuple[PlanNode, dict[str, Symbol]]:
    """Deep-copy a plan subtree, renaming every Symbol to a fresh name.

    Each WITH-query reference must own distinct symbols: sharing the plan
    object between two references makes a correlation like
    ``t1.k = t2.k`` degenerate into a tautology over one symbol (the
    reference inlines named queries per reference for the same reason —
    ``StatementAnalyzer.java`` named-query analysis). Returns the clone
    plus the old-name -> new-Symbol mapping so callers can re-point their
    scopes.
    """
    from trino_tpu import ir

    mapping: dict[str, Symbol] = {}
    node_cache: dict[int, PlanNode] = {}

    def map_symbol(s: Symbol) -> Symbol:
        got = mapping.get(s.name)
        if got is None:
            got = Symbol(fresh_name(s.name.rsplit("_", 1)[0] or s.name), s.type)
            mapping[s.name] = got
        return got

    def map_expr(e):
        def repl(x):
            if isinstance(x, ir.Variable):
                # every Variable in a CTE body is produced inside it, so
                # mapping-on-first-sight is safe regardless of field order
                return ir.Variable(
                    type=x.type, name=map_symbol(Symbol(x.name, x.type)).name
                )
            return x

        return ir.transform(e, repl)

    def map_value(v):
        if isinstance(v, PlanNode):
            return clone(v)
        if isinstance(v, Symbol):
            return map_symbol(v)
        if isinstance(v, Ordering):
            return dataclasses.replace(v, symbol=map_symbol(v.symbol))
        if isinstance(v, ir.RowExpr):
            return map_expr(v)
        if isinstance(v, (AggFunction, WindowFunction)):
            kw = {}
            for f in dataclasses.fields(v):
                kw[f.name] = map_value(getattr(v, f.name))
            return type(v)(**kw)
        if isinstance(v, list):
            return [map_value(x) for x in v]
        if isinstance(v, tuple):
            return tuple(map_value(x) for x in v)
        if isinstance(v, dict):
            return {map_value(k): map_value(x) for k, x in v.items()}
        return v

    def clone(n: PlanNode) -> PlanNode:
        got = node_cache.get(id(n))
        if got is not None:
            return got
        kw = {}
        for f in dataclasses.fields(n):
            val = getattr(n, f.name)
            # sources first so symbol mappings exist before expressions
            kw[f.name] = map_value(val) if isinstance(val, PlanNode) else val
        for f in dataclasses.fields(n):
            val = getattr(n, f.name)
            if not isinstance(val, PlanNode):
                kw[f.name] = map_value(val)
        out = type(n)(**kw)
        node_cache[id(n)] = out
        return out

    return clone(node), mapping
