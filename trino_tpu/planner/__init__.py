"""Logical planning: plan nodes, analyzer/planner, optimizer, fragmenter.

Reference: ``core/trino-main/src/main/java/io/trino/sql/planner/`` —
``LogicalPlanner.java:190``, plan nodes under ``sql/planner/plan/`` (44
types), optimizer sequence ``PlanOptimizers.java:240``, fragmenter
``PlanFragmenter.java:88``.
"""
