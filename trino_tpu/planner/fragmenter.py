"""Plan fragmenter: split the optimized plan at remote exchanges.

Reference: ``sql/planner/PlanFragmenter.java:88,106`` (createSubPlans cuts
the plan at REMOTE ExchangeNodes into PlanFragments) and
``sql/planner/optimizations/AddExchanges.java:115`` (decides each subtree's
required distribution and inserts the exchanges);
``SystemPartitioningHandle.java:58-66`` names the partitioning handles
(SOURCE / FIXED_HASH / FIXED_BROADCAST / SINGLE).

TPU translation: a fragment is the unit of whole-program compilation — one
pjit/SPMD program over the mesh (SURVEY §7 "Stage = pjit program"). The
partitioning handles map to sharding layouts:

- ``SOURCE``  — rows live where the connector splits were scanned
  (round-robin over mesh shards; Trino's SOURCE_DISTRIBUTION)
- ``HASH``    — rows co-partitioned by key hash (lax.all_to_all shuffle;
  FIXED_HASH_DISTRIBUTION)
- ``SINGLE``  — gathered to one logical partition (final sort/limit/output;
  SINGLE_DISTRIBUTION)

Exchange edges between fragments additionally carry 'broadcast'
(replicate the producer's rows to every shard — FIXED_BROADCAST, used for
the build side of replicated joins).

The aggregation split mirrors the reference's partial/final AggregationNode
steps with accumulator state on the wire (``AggregationNode.Step``,
``AccumulatorStateSerializer``): partial emits per-shard (value, count)
accumulator columns, the hash/single exchange reshuffles them, final
combines.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from trino_tpu import types as T
from trino_tpu.planner import plan as P

SOURCE = "SOURCE"
HASH = "HASH"
SINGLE = "SINGLE"


@dataclasses.dataclass(frozen=True)
class Partitioning:
    """Distribution of a subtree's output rows across the mesh."""

    kind: str  # SOURCE | HASH | SINGLE
    keys: tuple[str, ...] = ()  # symbol names, for HASH


@dataclasses.dataclass
class PlanFragment:
    """One fragment = one SPMD program (reference: PlanFragment.java)."""

    id: int
    root: P.PlanNode  # leaves may be RemoteSource nodes
    partitioning: Partitioning  # where this fragment's work runs
    # how this fragment's output ships to its consumer (None for the root
    # fragment, whose output goes to the client):
    output_exchange: Optional[str] = None  # 'hash' | 'broadcast' | 'single'
    output_keys: list[P.Symbol] = dataclasses.field(default_factory=list)

    @property
    def source_fragment_ids(self) -> list[int]:
        return [
            n.fragment_id
            for n in P.walk_plan(self.root)
            if isinstance(n, P.RemoteSource)
        ]


@dataclasses.dataclass
class SubPlan:
    """Fragment tree, root fragment first (reference: SubPlan.java)."""

    fragment: PlanFragment
    children: list["SubPlan"] = dataclasses.field(default_factory=list)

    def all_fragments(self) -> list[PlanFragment]:
        out = [self.fragment]
        for c in self.children:
            out.extend(c.all_fragments())
        return out


def fragment_plan(root: P.PlanNode, session=None) -> SubPlan:
    """AddExchanges + createSubPlans: the full fragmentation pipeline."""
    from trino_tpu.planner.sanity import PlanSanityChecker, validation_enabled

    with_exchanges, _ = _add_exchanges(root)
    sub = _split(with_exchanges)
    if validation_enabled(session):
        PlanSanityChecker.validate_fragments(sub)
    return sub


# === AddExchanges ===========================================================


def _hash_compatible(part: Partitioning, keys: list[P.Symbol]) -> bool:
    return part.kind == HASH and part.keys == tuple(s.name for s in keys)


def _gather(node: P.PlanNode, part: Partitioning) -> P.PlanNode:
    """Insert a SINGLE exchange unless already single."""
    if part.kind == SINGLE:
        return node
    return P.Exchange(node, "single", [], scope="remote")


def _add_exchanges(node: P.PlanNode) -> tuple[P.PlanNode, Partitioning]:
    """Recursive AddExchanges: returns (rewritten node, output partitioning)."""
    if isinstance(node, P.TableScan):
        return node, Partitioning(SOURCE)
    if isinstance(node, P.Values):
        return node, Partitioning(SINGLE)

    if isinstance(node, (P.Filter, P.Project, P.GroupId, P.Unnest)):
        src, part = _add_exchanges(node.source)
        node = dataclasses.replace(node, source=src)
        return node, part

    if isinstance(node, P.Aggregate):
        return _add_exchanges_aggregate(node)

    if isinstance(node, P.Join):
        return _add_exchanges_join(node)

    if isinstance(node, P.Distinct):
        src, part = _add_exchanges(node.source)
        # v1: gathered distinct (hash-partitioned partial/final later)
        return P.Distinct(_gather(src, part)), Partitioning(SINGLE)

    if isinstance(node, P.Sort):
        src, part = _add_exchanges(node.source)
        return P.Sort(_gather(src, part), node.order_by), Partitioning(SINGLE)

    if isinstance(node, P.TopN):
        src, part = _add_exchanges(node.source)
        if part.kind == SINGLE:
            return P.TopN(src, node.count, node.order_by), Partitioning(SINGLE)
        # partial per shard, gather, final (reference: TopNNode partial/final)
        partial = P.TopN(src, node.count, node.order_by, step="partial")
        gathered = _gather(partial, part)
        return (
            P.TopN(gathered, node.count, node.order_by, step="final"),
            Partitioning(SINGLE),
        )

    if isinstance(node, P.Limit):
        src, part = _add_exchanges(node.source)
        if part.kind == SINGLE:
            return dataclasses.replace(node, source=src), Partitioning(SINGLE)
        if node.offset or node.count is None:
            # OFFSET needs global row order — gather first
            return (
                dataclasses.replace(node, source=_gather(src, part)),
                Partitioning(SINGLE),
            )
        partial = P.Limit(src, node.count)
        gathered = _gather(partial, part)
        return P.Limit(gathered, node.count), Partitioning(SINGLE)

    if isinstance(node, P.Window):
        src, part = _add_exchanges(node.source)
        # v1: gathered window (hash-by-partition-keys later)
        return (
            dataclasses.replace(node, source=_gather(src, part)),
            Partitioning(SINGLE),
        )

    if isinstance(node, P.SetOp):
        inputs = []
        for child in node.inputs:
            src, part = _add_exchanges(child)
            inputs.append(_gather(src, part))
        return dataclasses.replace(node, inputs=inputs), Partitioning(SINGLE)

    if isinstance(node, P.Output):
        src, part = _add_exchanges(node.source)
        return (
            dataclasses.replace(node, source=_gather(src, part)),
            Partitioning(SINGLE),
        )

    if isinstance(node, P.Exchange):  # already placed (idempotence)
        src, _ = _add_exchanges(node.source)
        out_part = (
            Partitioning(HASH, tuple(s.name for s in node.keys))
            if node.partitioning == "hash"
            else Partitioning(SINGLE)
        )
        return dataclasses.replace(node, source=src), out_part

    # unknown node kinds execute wherever their child lives
    if node.sources:
        srcs = [_add_exchanges(s) for s in node.sources]
        return node, srcs[0][1]
    return node, Partitioning(SINGLE)


def _add_exchanges_aggregate(node: P.Aggregate) -> tuple[P.PlanNode, Partitioning]:
    src, part = _add_exchanges(node.source)
    if part.kind == SINGLE or node.step != "single":
        return dataclasses.replace(node, source=src), part
    if any(
        fn.distinct or fn.kind == "array_agg" for _, fn in node.aggregates
    ):
        # DISTINCT / array_agg need a global view of values — gather
        # (reference uses MarkDistinct + hash exchanges; v1 gathers)
        return (
            dataclasses.replace(node, source=_gather(src, part)),
            Partitioning(SINGLE),
        )
    acc = _make_acc_symbols(node)
    partial = P.Aggregate(
        src, node.group_keys, node.aggregates, step="partial", acc_symbols=acc
    )
    if node.group_keys:
        ex = P.Exchange(partial, "hash", list(node.group_keys), scope="remote")
        final = P.Aggregate(
            ex, node.group_keys, node.aggregates, step="final", acc_symbols=acc
        )
        return final, Partitioning(HASH, tuple(s.name for s in node.group_keys))
    ex = P.Exchange(partial, "single", [], scope="remote")
    final = P.Aggregate(
        ex, node.group_keys, node.aggregates, step="final", acc_symbols=acc
    )
    return final, Partitioning(SINGLE)


def _make_acc_symbols(
    node: P.Aggregate,
) -> list[tuple[P.Symbol, Optional[P.Symbol]]]:
    acc = []
    for sym, fn in node.aggregates:
        if fn.kind in ("count", "count_star"):
            acc.append((P.Symbol(P.fresh_name(f"{sym.name}_acc"), T.BIGINT), None))
        else:
            # value column keeps the input/result representation; count
            # column carries non-null cardinality (NULL and avg semantics)
            vt = fn.result_type if fn.kind in ("sum", "avg") else (
                fn.argument.type if fn.argument is not None else fn.result_type
            )
            acc.append(
                (
                    P.Symbol(P.fresh_name(f"{sym.name}_acc"), vt),
                    P.Symbol(P.fresh_name(f"{sym.name}_cnt"), T.BIGINT),
                )
            )
    return acc


def _add_exchanges_join(node: P.Join) -> tuple[P.PlanNode, Partitioning]:
    left, lpart = _add_exchanges(node.left)
    right, rpart = _add_exchanges(node.right)

    if node.join_type == "CROSS" and node.single_row:
        # uncorrelated scalar subquery: broadcast the one-row build so
        # the probe keeps its partitioning (the scalar is an all_gather
        # away on every shard)
        bcast = P.Exchange(right, "broadcast", [], scope="remote")
        return dataclasses.replace(node, left=left, right=bcast), lpart
    gather_kinds = ("CROSS", "SEMI", "ANTI", "RIGHT", "FULL")
    if (
        node.join_type in gather_kinds
        or (node.single_row and node.join_type != "LEFT")
        or not node.criteria
        or (node.join_type == "LEFT" and node.filter is not None)
    ):
        # kinds the SPMD join kernels do not cover yet run gathered
        # (mirrors DistributedExecutor's fallback set)
        return (
            dataclasses.replace(
                node, left=_gather(left, lpart), right=_gather(right, rpart)
            ),
            Partitioning(SINGLE),
        )

    lkeys = [a for a, _ in node.criteria]
    rkeys = [b for _, b in node.criteria]
    if node.distribution == "replicated":
        # probe side stays put; build side replicates to every shard
        bcast = P.Exchange(right, "broadcast", [], scope="remote")
        return dataclasses.replace(node, right=bcast), lpart
    # partitioned: co-partition both sides on the join keys
    if not _hash_compatible(lpart, lkeys):
        left = P.Exchange(left, "hash", lkeys, scope="remote")
    if not _hash_compatible(rpart, rkeys):
        right = P.Exchange(right, "hash", rkeys, scope="remote")
    return (
        dataclasses.replace(node, left=left, right=right),
        Partitioning(HASH, tuple(s.name for s in lkeys)),
    )


# === createSubPlans =========================================================


def _split(root: P.PlanNode) -> SubPlan:
    """Cut at remote Exchange nodes (reference: Fragmenter visitor)."""
    counter = itertools.count(1)
    children_of: dict[int, list[SubPlan]] = {}

    def cut(node: P.PlanNode, current: int) -> P.PlanNode:
        if isinstance(node, P.Exchange) and node.scope == "remote":
            fid = next(counter)
            child_root = cut(node.source, fid)
            frag = PlanFragment(
                fid,
                child_root,
                _fragment_partitioning(child_root),
                output_exchange=node.partitioning,
                output_keys=list(node.keys),
            )
            children_of.setdefault(current, []).append(
                SubPlan(frag, children_of.get(fid, []))
            )
            return P.RemoteSource(
                fid,
                list(node.output_symbols),
                exchange_type=node.partitioning,
                keys=list(node.keys),
            )
        replacements = {}
        for name, value in vars(node).items():
            if isinstance(value, P.PlanNode):
                replacements[name] = cut(value, current)
            elif isinstance(value, list) and value and isinstance(value[0], P.PlanNode):
                replacements[name] = [cut(v, current) for v in value]
        if replacements:
            node = dataclasses.replace(node, **replacements)
        return node

    root_cut = cut(root, 0)
    frag0 = PlanFragment(0, root_cut, _fragment_partitioning(root_cut))
    return SubPlan(frag0, children_of.get(0, []))


def _fragment_partitioning(root: P.PlanNode) -> Partitioning:
    """A fragment runs where its leaves put it: scans → SOURCE, hash
    remote-sources → HASH, otherwise SINGLE."""
    hash_keys: tuple[str, ...] = ()
    kind = SINGLE
    for n in P.walk_plan(root):
        if isinstance(n, P.TableScan):
            return Partitioning(SOURCE)
        if isinstance(n, P.RemoteSource) and n.exchange_type == "hash":
            kind = HASH
            hash_keys = tuple(s.name for s in n.keys)
    return Partitioning(kind, hash_keys)


# === whole-pipeline fusion ==================================================


@dataclasses.dataclass
class FusedFragment:
    """A chain/tree of exchange-connected fragments compiled as ONE
    program: interior HASH (or gather) exchanges become in-jit
    collectives instead of fragment boundaries, so the whole group costs
    a single dispatch round-trip. ``fragments`` is in bottom-up execution
    order — producers first, the consumer root LAST (the root's output
    exchange is the unit's output exchange)."""

    fragments: tuple[PlanFragment, ...]

    @property
    def root(self) -> PlanFragment:
        return self.fragments[-1]

    @property
    def id(self) -> int:
        return self.fragments[-1].id

    @property
    def fragment_ids(self) -> tuple[int, ...]:
        return tuple(f.id for f in self.fragments)

    @property
    def member_ids(self) -> frozenset:
        return frozenset(f.id for f in self.fragments)

    @property
    def external_source_ids(self) -> tuple[int, ...]:
        """Source fragment ids the unit pulls from OUTSIDE itself — the
        unit's recovery lineage. Interior links are in-jit collectives
        with no retained pages, so healing a unit means healing exactly
        these (each is itself a unit root or a plain fragment: a
        fragment tree has a single consumer per exchange, so an external
        producer can never be the interior of another unit)."""
        inside = self.member_ids
        out: list[int] = []
        for f in self.fragments:
            for fid in f.source_fragment_ids:
                if fid not in inside and fid not in out:
                    out.append(fid)
        return tuple(out)


def partitioned_join_pairs(sub) -> list[tuple[int, int]]:
    """(probe_fid, build_fid) producer pairs of every partitioned
    hash/hash equi-join (the skew-role pairing — mirrors
    ``FragmentedExecutor._skew_roles``). The fusion pass keeps each pair
    in the same unit or out of fusion entirely: the probe exchange
    detects heavy hitters and the build exchange salts with the
    resulting hot set, so splitting a pair across a fusion boundary
    would break their ordering/co-partitioning contract.

    ``sub`` is a :class:`SubPlan` or a bare fragment iterable (workers
    hold only the shipped member list, never the SubPlan)."""
    pairs: list[tuple[int, int]] = []
    frags = sub.all_fragments() if isinstance(sub, SubPlan) else sub
    for frag in frags:
        for node in P.walk_plan(frag.root):
            if (
                isinstance(node, P.Join)
                and node.join_type in ("INNER", "LEFT")
                and node.criteria
                and not node.single_row
                and isinstance(node.left, P.RemoteSource)
                and node.left.exchange_type == "hash"
                and isinstance(node.right, P.RemoteSource)
                and node.right.exchange_type == "hash"
            ):
                pairs.append(
                    (node.left.fragment_id, node.right.fragment_id)
                )
    return pairs


def filtered_broadcast_fids(sub) -> set[int]:
    """Fragment ids of broadcast producers carrying a selective Filter.

    Absorbing such a build into its consumer's fused unit (the
    ``broadcast_links`` star-join path) would erase the dynamic-filter
    boundary: worker-side DF prunes probe splits/rows from a
    *materialized* build, and a fused interior member never
    materializes. A selective dim build is exactly where DF pays more
    than the saved dispatch round-trip, so callers keep these links
    unfused when dynamic filtering is enabled; predicate-free dim
    builds (full-domain DF, nothing to prune) still fuse."""
    fids: set[int] = set()
    frags = sub.all_fragments() if isinstance(sub, SubPlan) else sub
    for frag in frags:
        if frag.output_exchange != "broadcast":
            continue
        if any(isinstance(n, P.Filter) for n in P.walk_plan(frag.root)):
            fids.add(frag.id)
    return fids


def fuse_groups(
    sub: SubPlan,
    *,
    fusable,
    max_fragments: int = 8,
    blocked: frozenset = frozenset(),
    skew_pairs=(),
    include_root: bool = True,
    broadcast_links: bool = False,
):
    """Post-fragmentation grouping: partition the fragment tree into
    fused units. Returns a list of units in bottom-up execution order;
    each unit is either a plain :class:`PlanFragment` (unfused) or a
    :class:`FusedFragment` of 2+ members.

    A producer fuses into its consumer's unit when every leg of the link
    is eligible:

    - both sides trace (``fusable(frag)`` — the exec layer passes
      ``fragment_fusable``) and neither is in ``blocked`` (the caller
      blocks spill-sized / streaming-eligible fragments, and cluster
      callers block spool-required boundaries);
    - the connecting exchange is plain or skew-salted HASH, or a gather
      ('single' — e.g. into a final global aggregation). Broadcast links
      stay fragment boundaries unless ``broadcast_links`` is set (the
      dense join tier): then REPLICATE/broadcast dim builds ride inside
      their consumer's unit, so a star-join fact chain probes every dim
      in ONE fused program instead of pairwise join fragments;
    - skew-paired producers (``skew_pairs``) are absorbed atomically —
      both or neither;
    - the unit stays within ``max_fragments`` members.

    Grouping is greedy consumer-down: a consumer claims its eligible
    producers, and claimed producers extend the same unit with their own
    producers transitively. ``include_root=False`` keeps the root
    fragment (coordinator-executed in cluster mode) out of any unit.
    """
    order: list[PlanFragment] = []
    children: dict[int, list[PlanFragment]] = {}

    def visit(sp: SubPlan) -> None:
        children[sp.fragment.id] = [c.fragment for c in sp.children]
        for c in sp.children:
            visit(c)
        order.append(sp.fragment)

    visit(sub)
    peer: dict[int, int] = {}
    for a, b in skew_pairs:
        peer[a] = b
        peer[b] = a
    max_fragments = max(1, int(max_fragments))
    links = (
        ("hash", "single", "broadcast")
        if broadcast_links
        else ("hash", "single")
    )
    ok = {
        f.id
        for f in order
        if f.id not in blocked and fusable(f)
    }
    owner: dict[int, int] = {}  # fid -> unit-root fid
    size: dict[int, int] = {}
    for frag in reversed(order):  # consumers before their producers
        if frag.id not in ok:
            continue
        if frag.id == sub.fragment.id and not include_root:
            continue
        ru = owner.setdefault(frag.id, frag.id)
        size.setdefault(ru, 1)
        kids = children.get(frag.id, [])
        kid_ids = {k.id for k in kids}
        claimed: set[int] = set()
        for child in kids:
            if child.id in claimed:
                continue
            group = [child]
            mate = peer.get(child.id)
            if mate is not None:
                if mate not in kid_ids:
                    continue  # pair split across consumers: stay unfused
                group.append(next(k for k in kids if k.id == mate))
            claimed.update(c.id for c in group)
            if any(c.id not in ok for c in group):
                continue
            if any(c.output_exchange not in links for c in group):
                continue
            if size[ru] + len(group) > max_fragments:
                continue
            for c in group:
                owner[c.id] = ru
            size[ru] += len(group)
    units: list = []
    for frag in order:
        if owner.get(frag.id, frag.id) != frag.id:
            continue  # interior member; emitted with its unit root
        members = [f for f in order if owner.get(f.id, f.id) == frag.id]
        units.append(
            FusedFragment(tuple(members)) if len(members) > 1 else frag
        )
    return units


# === EXPLAIN rendering ======================================================


def subplan_text(subplan: SubPlan) -> str:
    """Fragment-structured EXPLAIN (reference: PlanPrinter.textDistributedPlan)."""
    lines = []
    for frag in sorted(subplan.all_fragments(), key=lambda f: f.id):
        head = f"Fragment {frag.id} [{frag.partitioning.kind}"
        if frag.partitioning.keys:
            head += "(" + ", ".join(frag.partitioning.keys) + ")"
        head += "]"
        if frag.output_exchange:
            head += f" -> {frag.output_exchange}"
            if frag.output_keys:
                head += "(" + ", ".join(s.name for s in frag.output_keys) + ")"
        lines.append(head)
        lines.append(P.plan_text(frag.root, indent=1))
        lines.append("")
    return "\n".join(lines).rstrip()
