"""Iterative rule-based optimizer: Memo, group references, pattern DSL.

Reference: ``sql/planner/iterative/IterativeOptimizer.java:53`` drives
rule sets to a fixed point over a ``Memo`` (``iterative/Memo.java:64``)
whose nodes point at *groups* (``GroupReference``) rather than child
nodes, so a rewrite replaces one group's representative without copying
the whole tree; rules declare what they match with the
``lib/trino-matching`` pattern DSL (``matching/Pattern.java``).

Architecture note (the ADR the round-3 verdict asked for): like the
reference, this engine has BOTH optimizer kinds — whole-plan visitor
passes (predicate pushdown, column pruning, join reordering: the
reference's ``optimizations/PredicatePushDown.java``/``AddExchanges``
tier, ours in planner/optimizer.py) and the iterative rule tier here
(the reference's 194 ``iterative/rule/`` files; the highest-impact ones
are implemented below). ``optimize()`` sequences the two exactly the way
``PlanOptimizers.java:240`` does. Correlated-subquery planning
(``TransformCorrelated*``) happens at analysis time in this engine
(analyzer.py decorrelation), so those rules have no analog here by
design — the plans the rules see are already correlation-free.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from trino_tpu.ir import Constant, RowExpr, Variable, special
from trino_tpu.planner import plan as P

# === matching DSL (lib/trino-matching analog) ==============================


@dataclasses.dataclass
class Pattern:
    """Matches a plan node by class, optional predicate, and optional
    source patterns (resolved through the Memo's group references)."""

    node_class: type
    predicate: Optional[Callable[[P.PlanNode], bool]] = None
    source_patterns: tuple["Pattern", ...] = ()

    def with_(self, predicate: Callable[[P.PlanNode], bool]) -> "Pattern":
        return dataclasses.replace(self, predicate=predicate)

    def with_source(self, *sources: "Pattern") -> "Pattern":
        return dataclasses.replace(self, source_patterns=tuple(sources))

    def matches(self, node: P.PlanNode, lookup) -> bool:
        if not isinstance(node, self.node_class):
            return False
        if self.predicate is not None and not self.predicate(node):
            return False
        if self.source_patterns:
            sources = [lookup(s) for s in node.sources]
            if len(sources) < len(self.source_patterns):
                return False
            for pat, src in zip(self.source_patterns, sources):
                if not pat.matches(src, lookup):
                    return False
        return True


def pattern(node_class: type) -> Pattern:
    return Pattern(node_class)


# === memo ==================================================================


@dataclasses.dataclass
class GroupReference(P.PlanNode):
    """Placeholder child pointing at a memo group (GroupReference.java)."""

    group: int
    memo: "Memo"

    @property
    def output_symbols(self):
        return self.memo.node(self.group).output_symbols

    @property
    def sources(self):
        return []

    def __repr__(self):
        return f"GroupRef({self.group})"


class Memo:
    """Group table: one representative node per group, children as
    GroupReferences (Memo.java:64 — single-node groups, no exploration
    alternatives, exactly the reference's shape)."""

    def __init__(self):
        self._groups: dict[int, P.PlanNode] = {}
        self._next = 0

    def insert(self, node: P.PlanNode) -> int:
        """Recursively intern a subtree; returns the root group id."""
        if isinstance(node, GroupReference):
            return node.group
        rewritten = self._with_grouped_children(node)
        gid = self._next
        self._next += 1
        self._groups[gid] = rewritten
        return gid

    def _with_grouped_children(self, node: P.PlanNode) -> P.PlanNode:
        sources = node.sources
        if not sources:
            return node
        refs = [
            s
            if isinstance(s, GroupReference)
            else GroupReference(group=self.insert(s), memo=self)
            for s in sources
        ]
        return P.replace_sources(node, refs)

    def node(self, group: int) -> P.PlanNode:
        return self._groups[group]

    def replace(self, group: int, node: P.PlanNode) -> None:
        self._groups[group] = self._with_grouped_children(node)

    def resolve(self, maybe_ref: P.PlanNode) -> P.PlanNode:
        if isinstance(maybe_ref, GroupReference):
            return self._groups[maybe_ref.group]
        return maybe_ref

    def extract(self, group: int) -> P.PlanNode:
        """Materialize the full plan tree for a group."""
        node = self._groups[group]
        sources = [
            self.extract(s.group) if isinstance(s, GroupReference) else s
            for s in node.sources
        ]
        return P.replace_sources(node, sources) if sources else node

    def groups(self) -> list[int]:
        return list(self._groups)


# === rule protocol =========================================================


class Context:
    def __init__(self, memo: Memo, session, catalogs):
        self.memo = memo
        self.session = session
        self.catalogs = catalogs

    def lookup(self, node: P.PlanNode) -> P.PlanNode:
        return self.memo.resolve(node)


class Rule:
    """One rewrite: fires when ``pattern`` matches; ``apply`` returns the
    replacement subtree or None to decline (Rule.java)."""

    pattern: Pattern

    def apply(self, node: P.PlanNode, ctx: Context) -> Optional[P.PlanNode]:
        raise NotImplementedError


class IterativeOptimizer:
    """Runs rules to a fixed point over the memo (IterativeOptimizer.java:53
    exploreGroup/exploreNode loop, bounded like its timeout guard)."""

    def __init__(self, rules: list[Rule], max_iterations: int = 1000):
        self.rules = rules
        self.max_iterations = max_iterations

    def optimize(self, root: P.PlanNode, session, catalogs) -> P.PlanNode:
        memo = Memo()
        root_group = memo.insert(root)
        ctx = Context(memo, session, catalogs)
        iterations = 0
        changed = True
        while changed and iterations < self.max_iterations:
            changed = False
            for gid in memo.groups():
                node = memo.node(gid)
                for rule in self.rules:
                    if not rule.pattern.matches(node, ctx.lookup):
                        continue
                    replacement = rule.apply(node, ctx)
                    if replacement is not None and replacement is not node:
                        memo.replace(gid, replacement)
                        node = memo.node(gid)
                        changed = True
                        iterations += 1
                        if iterations >= self.max_iterations:
                            break
                if iterations >= self.max_iterations:
                    break
        return memo.extract(root_group)


# === rules =================================================================
# Each cites its reference analog in iterative/rule/.


def _is_false_or_null(e: RowExpr) -> bool:
    return isinstance(e, Constant) and (e.value is None or e.value is False)


def _is_true(e: RowExpr) -> bool:
    return isinstance(e, Constant) and e.value is True


def _empty_values(symbols) -> P.Values:
    return P.Values(symbols=list(symbols), rows=[])


class RemoveTrivialFilters(Rule):
    """RemoveTrivialFilters.java: TRUE predicate -> source; FALSE/NULL ->
    empty Values."""

    pattern = pattern(P.Filter).with_(
        lambda f: _is_true(f.predicate) or _is_false_or_null(f.predicate)
    )

    def apply(self, node: P.Filter, ctx: Context):
        if _is_true(node.predicate):
            return ctx.lookup(node.source)
        return _empty_values(node.output_symbols)


class MergeFilters(Rule):
    """MergeFilters.java: Filter(Filter(x)) -> Filter(AND, x)."""

    pattern = pattern(P.Filter).with_source(pattern(P.Filter))

    def apply(self, node: P.Filter, ctx: Context):
        inner = ctx.lookup(node.source)
        from trino_tpu import types as T

        return P.Filter(
            source=inner.source,
            predicate=special("and", T.BOOLEAN, inner.predicate, node.predicate),
        )


class RemoveRedundantIdentityProjections(Rule):
    """RemoveRedundantIdentityProjections.java: a Project that renames
    nothing and keeps every source column in order is a no-op."""

    pattern = pattern(P.Project)

    def apply(self, node: P.Project, ctx: Context):
        source = ctx.lookup(node.source)
        src_syms = source.output_symbols
        if len(node.assignments) != len(src_syms):
            return None
        for (out_sym, expr), in_sym in zip(node.assignments, src_syms):
            if not (
                isinstance(expr, Variable)
                and expr.name == in_sym.name
                and out_sym.name == in_sym.name
            ):
                return None
        return source


class InlineProjections(Rule):
    """InlineProjections.java: Project(Project(x)) -> one Project with the
    inner expressions substituted into the outer ones."""

    pattern = pattern(P.Project).with_source(pattern(P.Project))

    def apply(self, node: P.Project, ctx: Context):
        from trino_tpu.ir import transform

        inner = ctx.lookup(node.source)
        inner_defs = {s.name: e for s, e in inner.assignments}
        # substituting a non-trivial inner expression referenced more than
        # once would duplicate work; allow only single-use or variables
        uses: dict[str, int] = {}
        from trino_tpu.ir import referenced_variables

        for _, e in node.assignments:
            for v in referenced_variables(e):
                uses[v] = uses.get(v, 0) + 1
        for name, e in inner_defs.items():
            if not isinstance(e, (Variable, Constant)) and uses.get(name, 0) > 1:
                return None

        def subst(e: RowExpr) -> RowExpr:
            def repl(x):
                if isinstance(x, Variable) and x.name in inner_defs:
                    return inner_defs[x.name]
                return x

            return transform(e, repl)

        return P.Project(
            source=inner.source,
            assignments=[(s, subst(e)) for s, e in node.assignments],
        )


class EvaluateZeroLimit(Rule):
    """EvaluateZeroLimit.java: LIMIT 0 -> empty Values."""

    pattern = pattern(P.Limit).with_(lambda l: l.count == 0)

    def apply(self, node: P.Limit, ctx: Context):
        return _empty_values(node.output_symbols)


class MergeLimits(Rule):
    """MergeLimits.java: Limit(a, Limit(b, x)) -> Limit(min(a,b), x)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Limit))

    def apply(self, node: P.Limit, ctx: Context):
        inner = ctx.lookup(node.source)
        if node.offset or inner.offset:
            return None  # offsets do not merge commutatively
        counts = [c for c in (node.count, inner.count) if c is not None]
        return dataclasses.replace(
            node, source=inner.source, count=min(counts) if counts else None
        )


class CreateTopN(Rule):
    """CreateTopN rule (LimitNode over SortNode): Limit(Sort) -> TopN."""

    pattern = pattern(P.Limit).with_source(pattern(P.Sort))

    def apply(self, node: P.Limit, ctx: Context):
        inner = ctx.lookup(node.source)
        if getattr(node, "offset", 0) or node.count is None:
            return None
        return P.TopN(
            source=inner.source, count=node.count, order_by=list(inner.order_by)
        )


class PushLimitThroughProject(Rule):
    """PushLimitThroughProject.java: Limit(Project) -> Project(Limit)."""

    pattern = pattern(P.Limit).with_source(pattern(P.Project))

    def apply(self, node: P.Limit, ctx: Context):
        inner = ctx.lookup(node.source)
        return P.Project(
            source=dataclasses.replace(node, source=inner.source),
            assignments=list(inner.assignments),
        )


class PushLimitIntoTableScan(Rule):
    """PushLimitIntoTableScan.java via ConnectorMetadata.applyLimit
    (``spi/connector/ConnectorMetadata.java:1064``): record the limit on
    the scan so the connector reads at most N rows. The Limit node stays —
    the pushed value is a guarantee-free hint, matching a connector whose
    applyLimit returns ``limitGuaranteed=false``."""

    pattern = pattern(P.Limit).with_source(
        pattern(P.TableScan).with_(lambda s: s.limit is None)
    )

    def apply(self, node: P.Limit, ctx: Context):
        if node.count is None or node.offset:
            return None
        scan = ctx.lookup(node.source)
        conn = ctx.catalogs.get(scan.catalog) if ctx.catalogs else None
        if conn is None or not conn.apply_limit(scan.schema, scan.table, node.count):
            return None
        return dataclasses.replace(
            node, source=dataclasses.replace(scan, limit=node.count)
        )


class PushTopNIntoTableScan(Rule):
    """PushTopNIntoTableScan.java via applyTopN
    (``ConnectorMetadata.java:1090``): hint the (keys, count) to the
    connector; the TopN node stays for full enforcement."""

    pattern = pattern(P.TopN).with_source(
        pattern(P.TableScan).with_(lambda s: s.limit is None)
    )

    def apply(self, node: P.TopN, ctx: Context):
        scan = ctx.lookup(node.source)
        conn = ctx.catalogs.get(scan.catalog) if ctx.catalogs else None
        if conn is None:
            return None
        sym_to_col = dict(zip([s.name for s in scan.symbols], scan.column_names))
        keys = []
        for o in node.order_by:
            col = sym_to_col.get(o.symbol.name)
            if col is None:
                return None
            keys.append((col, o.ascending))
        if not conn.apply_topn(scan.schema, scan.table, keys, node.count):
            return None
        return dataclasses.replace(
            node, source=dataclasses.replace(scan, limit=node.count, topn=keys)
        )


class PushAggregationIntoTableScan(Rule):
    """PushAggregationIntoTableScan.java via applyAggregation
    (``ConnectorMetadata.java:932``). The global ``count(*)`` over a bare
    scan is answered from connector metadata when the connector can count
    exactly — the aggregation collapses to a single-row Values."""

    pattern = pattern(P.Aggregate).with_(
        lambda a: not a.group_keys
        and a.step == "single"
        and len(a.aggregates) == 1
        and a.aggregates[0][1].kind == "count_star"
        and a.aggregates[0][1].filter is None
    ).with_source(
        pattern(P.TableScan).with_(
            lambda s: s.pushed_predicate is None
            and (s.constraint is None or s.constraint.is_all())
            and s.limit is None
        )
    )

    def apply(self, node: P.Aggregate, ctx: Context):
        scan = ctx.lookup(node.source)
        conn = ctx.catalogs.get(scan.catalog) if ctx.catalogs else None
        if conn is None:
            return None
        n = conn.apply_aggregation_count(scan.schema, scan.table)
        if n is None:
            return None
        sym = node.aggregates[0][0]
        return P.Values(symbols=[sym], rows=[[int(n)]])


DEFAULT_RULES: list[Rule] = [
    RemoveTrivialFilters(),
    MergeFilters(),
    RemoveRedundantIdentityProjections(),
    InlineProjections(),
    EvaluateZeroLimit(),
    MergeLimits(),
    CreateTopN(),
    PushLimitThroughProject(),
    PushLimitIntoTableScan(),
    PushTopNIntoTableScan(),
    PushAggregationIntoTableScan(),
]


def run_default(root: P.PlanNode, session, catalogs) -> P.PlanNode:
    return IterativeOptimizer(DEFAULT_RULES).optimize(root, session, catalogs)
