"""Plan / expression JSON serialization — the TaskUpdateRequest wire format.

Reference: Trino ships each fragment to workers as JSON inside
``TaskUpdateRequest`` (``server/TaskResource.java:127`` — body carries the
serialized ``PlanFragment`` plus split assignments); Jackson serializers
live on the plan-node classes themselves. Here: explicit to/from-JSON for
the 6-kind RowExpr IR, plan nodes, and PlanFragment.

Notes:
- types round-trip through ``str(type)`` / ``T.parse_type``
- scan ``constraint``/``pushed_predicate`` are advisory (the enclosing
  Filter re-applies the full predicate) and do not cross the wire; split
  pruning already happened on the coordinator during scheduling
"""

from __future__ import annotations

from decimal import Decimal
from typing import Any

from trino_tpu import types as T
from trino_tpu.ir import (
    Call,
    Constant,
    HoistedConstant,
    InputRef,
    RowExpr,
    SpecialForm,
    Variable,
)
from trino_tpu.ops.sort import SortKey
from trino_tpu.planner import plan as P
from trino_tpu.planner.fragmenter import Partitioning, PlanFragment


# === expressions ============================================================


def expr_to_json(e: RowExpr | None) -> Any:
    if e is None:
        return None
    t = str(e.type)
    if isinstance(e, InputRef):
        return {"k": "input", "t": t, "channel": e.channel}
    if isinstance(e, HoistedConstant):
        # canonical by construction: the literal lives in the query's
        # parameter vector, not the plan, so literal variants serialize —
        # and fingerprint — identically (planner/canonicalize.py)
        return {"k": "hoisted", "t": t, "index": e.index}
    if isinstance(e, Constant):
        v = e.value
        if isinstance(v, Decimal):
            v = {"$decimal": str(v)}
        return {"k": "const", "t": t, "value": v}
    if isinstance(e, Variable):
        return {"k": "var", "t": t, "name": e.name}
    if isinstance(e, Call):
        return {
            "k": "call",
            "t": t,
            "name": e.name,
            "args": [expr_to_json(a) for a in e.args],
        }
    if isinstance(e, SpecialForm):
        return {
            "k": "special",
            "t": t,
            "form": e.form,
            "args": [expr_to_json(a) for a in e.args],
        }
    raise TypeError(f"unserializable expression {type(e).__name__}")


def expr_from_json(d: Any) -> RowExpr | None:
    if d is None:
        return None
    t = T.parse_type(d["t"])
    k = d["k"]
    if k == "input":
        return InputRef(type=t, channel=d["channel"])
    if k == "hoisted":
        # the value is deliberately absent; execution must supply a
        # parameter vector (interpreter paths re-bake from it too)
        return HoistedConstant(type=t, value=None, index=d["index"])
    if k == "const":
        v = d["value"]
        if isinstance(v, dict) and "$decimal" in v:
            v = Decimal(v["$decimal"])
        return Constant(type=t, value=v)
    if k == "var":
        return Variable(type=t, name=d["name"])
    if k == "call":
        return Call(
            type=t, name=d["name"], args=tuple(expr_from_json(a) for a in d["args"])
        )
    if k == "special":
        return SpecialForm(
            type=t, form=d["form"], args=tuple(expr_from_json(a) for a in d["args"])
        )
    raise TypeError(f"unknown expression kind {k!r}")


# === symbols / orderings ====================================================


def _sym(s: P.Symbol) -> dict:
    return {"n": s.name, "t": str(s.type)}


def _sym_from(d: dict) -> P.Symbol:
    return P.Symbol(d["n"], T.parse_type(d["t"]))


def _ord(o: P.Ordering) -> dict:
    return {"s": _sym(o.symbol), "asc": o.ascending, "nf": o.nulls_first}


def _ord_from(d: dict) -> P.Ordering:
    return P.Ordering(_sym_from(d["s"]), d["asc"], d["nf"])


# === plan nodes =============================================================


def node_to_json(node: P.PlanNode) -> dict:
    if isinstance(node, P.TableScan):
        return {
            "k": "tablescan",
            "catalog": node.catalog,
            "schema": node.schema,
            "table": node.table,
            "symbols": [_sym(s) for s in node.symbols],
            "columns": list(node.column_names),
            "limit": node.limit,
            "topn": node.topn,
        }
    if isinstance(node, P.RemoteSource):
        return {
            "k": "remotesource",
            "fragment": node.fragment_id,
            "symbols": [_sym(s) for s in node.symbols],
            "exchange": node.exchange_type,
            "keys": [_sym(s) for s in node.keys],
        }
    if isinstance(node, P.Values):
        rows = [
            [
                {"$decimal": str(v)} if isinstance(v, Decimal) else v
                for v in row
            ]
            for row in node.rows
        ]
        return {
            "k": "values",
            "symbols": [_sym(s) for s in node.symbols],
            "rows": rows,
        }
    if isinstance(node, P.Filter):
        return {
            "k": "filter",
            "source": node_to_json(node.source),
            "predicate": expr_to_json(node.predicate),
        }
    if isinstance(node, P.Project):
        return {
            "k": "project",
            "source": node_to_json(node.source),
            "assignments": [
                [_sym(s), expr_to_json(e)] for s, e in node.assignments
            ],
        }
    if isinstance(node, P.Aggregate):
        return {
            "k": "aggregate",
            "source": node_to_json(node.source),
            "keys": [_sym(s) for s in node.group_keys],
            "aggs": [
                {
                    "s": _sym(s),
                    "kind": fn.kind,
                    "arg": expr_to_json(fn.argument),
                    "rt": str(fn.result_type),
                    "distinct": fn.distinct,
                    "filter": expr_to_json(fn.filter),
                }
                for s, fn in node.aggregates
            ],
            "step": node.step,
            "acc": [
                [_sym(v), _sym(c) if c is not None else None]
                for v, c in node.acc_symbols
            ]
            if node.acc_symbols is not None
            else None,
        }
    if isinstance(node, P.Join):
        return {
            "k": "join",
            "type": node.join_type,
            "left": node_to_json(node.left),
            "right": node_to_json(node.right),
            "criteria": [[_sym(a), _sym(b)] for a, b in node.criteria],
            "filter": expr_to_json(node.filter),
            "distribution": node.distribution,
            "mark": _sym(node.mark_symbol) if node.mark_symbol else None,
            "null_aware": node.null_aware,
            "single_row": node.single_row,
        }
    if isinstance(node, P.GroupId):
        return {
            "k": "groupid",
            "source": node_to_json(node.source),
            "groups": [[_sym(s) for s in g] for g in node.groups],
            "all_keys": [_sym(s) for s in node.all_keys],
            "gid": _sym(node.gid),
        }
    if isinstance(node, P.Sort):
        return {
            "k": "sort",
            "source": node_to_json(node.source),
            "order": [_ord(o) for o in node.order_by],
        }
    if isinstance(node, P.TopN):
        return {
            "k": "topn",
            "source": node_to_json(node.source),
            "count": node.count,
            "order": [_ord(o) for o in node.order_by],
            "step": node.step,
        }
    if isinstance(node, P.Limit):
        return {
            "k": "limit",
            "source": node_to_json(node.source),
            "count": node.count,
            "offset": node.offset,
        }
    if isinstance(node, P.Distinct):
        return {"k": "distinct", "source": node_to_json(node.source)}
    if isinstance(node, P.SetOp):
        return {
            "k": "setop",
            "op": node.op,
            "distinct": node.distinct,
            "inputs": [node_to_json(s) for s in node.inputs],
            "symbols": [_sym(s) for s in node.symbols],
        }
    if isinstance(node, P.Window):
        return {
            "k": "window",
            "source": node_to_json(node.source),
            "partition": [_sym(s) for s in node.partition_by],
            "order": [_ord(o) for o in node.order_by],
            "functions": [
                {
                    "s": _sym(s),
                    "kind": fn.kind,
                    "arg": expr_to_json(fn.argument),
                    "rt": str(fn.result_type),
                    "offset": fn.offset,
                    "default": expr_to_json(fn.default),
                }
                for s, fn in node.functions
            ],
            "frame": list(node.frame) if node.frame else None,
        }
    if isinstance(node, P.Output):
        return {
            "k": "output",
            "source": node_to_json(node.source),
            "names": list(node.column_names),
            "symbols": [_sym(s) for s in node.symbols],
        }
    if isinstance(node, P.Exchange):
        return {
            "k": "exchange",
            "source": node_to_json(node.source),
            "partitioning": node.partitioning,
            "keys": [_sym(s) for s in node.keys],
            "scope": node.scope,
        }
    raise TypeError(f"unserializable plan node {type(node).__name__}")


def node_from_json(d: dict) -> P.PlanNode:
    k = d["k"]
    if k == "tablescan":
        return P.TableScan(
            d["catalog"],
            d["schema"],
            d["table"],
            [_sym_from(s) for s in d["symbols"]],
            list(d["columns"]),
            limit=d.get("limit"),
            topn=d.get("topn"),
        )
    if k == "remotesource":
        return P.RemoteSource(
            d["fragment"],
            [_sym_from(s) for s in d["symbols"]],
            d["exchange"],
            [_sym_from(s) for s in d["keys"]],
        )
    if k == "values":
        rows = [
            [
                Decimal(v["$decimal"]) if isinstance(v, dict) and "$decimal" in v else v
                for v in row
            ]
            for row in d["rows"]
        ]
        return P.Values([_sym_from(s) for s in d["symbols"]], rows)
    if k == "filter":
        return P.Filter(node_from_json(d["source"]), expr_from_json(d["predicate"]))
    if k == "project":
        return P.Project(
            node_from_json(d["source"]),
            [(_sym_from(s), expr_from_json(e)) for s, e in d["assignments"]],
        )
    if k == "aggregate":
        aggs = [
            (
                _sym_from(a["s"]),
                P.AggFunction(
                    a["kind"],
                    expr_from_json(a["arg"]),
                    T.parse_type(a["rt"]),
                    a["distinct"],
                    expr_from_json(a["filter"]),
                ),
            )
            for a in d["aggs"]
        ]
        acc = None
        if d.get("acc") is not None:
            acc = [
                (_sym_from(v), _sym_from(c) if c is not None else None)
                for v, c in d["acc"]
            ]
        return P.Aggregate(
            node_from_json(d["source"]),
            [_sym_from(s) for s in d["keys"]],
            aggs,
            d["step"],
            acc,
        )
    if k == "join":
        return P.Join(
            d["type"],
            node_from_json(d["left"]),
            node_from_json(d["right"]),
            [(_sym_from(a), _sym_from(b)) for a, b in d["criteria"]],
            expr_from_json(d["filter"]),
            d["distribution"],
            _sym_from(d["mark"]) if d["mark"] else None,
            d["null_aware"],
            d["single_row"],
        )
    if k == "groupid":
        return P.GroupId(
            node_from_json(d["source"]),
            [[_sym_from(s) for s in g] for g in d["groups"]],
            [_sym_from(s) for s in d["all_keys"]],
            _sym_from(d["gid"]),
        )
    if k == "sort":
        return P.Sort(node_from_json(d["source"]), [_ord_from(o) for o in d["order"]])
    if k == "topn":
        return P.TopN(
            node_from_json(d["source"]),
            d["count"],
            [_ord_from(o) for o in d["order"]],
            d["step"],
        )
    if k == "limit":
        return P.Limit(node_from_json(d["source"]), d["count"], d["offset"])
    if k == "distinct":
        return P.Distinct(node_from_json(d["source"]))
    if k == "setop":
        return P.SetOp(
            d["op"],
            d["distinct"],
            [node_from_json(s) for s in d["inputs"]],
            [_sym_from(s) for s in d["symbols"]],
        )
    if k == "window":
        fns = [
            (
                _sym_from(f["s"]),
                P.WindowFunction(
                    f["kind"],
                    expr_from_json(f["arg"]),
                    T.parse_type(f["rt"]),
                    f["offset"],
                    expr_from_json(f["default"]),
                ),
            )
            for f in d["functions"]
        ]
        return P.Window(
            node_from_json(d["source"]),
            [_sym_from(s) for s in d["partition"]],
            [_ord_from(o) for o in d["order"]],
            fns,
            tuple(d["frame"]) if d["frame"] else None,
        )
    if k == "output":
        return P.Output(
            node_from_json(d["source"]),
            list(d["names"]),
            [_sym_from(s) for s in d["symbols"]],
        )
    if k == "exchange":
        return P.Exchange(
            node_from_json(d["source"]),
            d["partitioning"],
            [_sym_from(s) for s in d["keys"]],
            d["scope"],
        )
    raise TypeError(f"unknown plan node kind {k!r}")


# === fragments ==============================================================


def fragment_to_json(f: PlanFragment) -> dict:
    return {
        "id": f.id,
        "root": node_to_json(f.root),
        "partitioning": {"kind": f.partitioning.kind, "keys": list(f.partitioning.keys)},
        "output_exchange": f.output_exchange,
        "output_keys": [_sym(s) for s in f.output_keys],
    }


def fragment_from_json(d: dict, validate: bool = False) -> PlanFragment:
    frag = PlanFragment(
        d["id"],
        node_from_json(d["root"]),
        Partitioning(d["partitioning"]["kind"], tuple(d["partitioning"]["keys"])),
        d["output_exchange"],
        [_sym_from(s) for s in d["output_keys"]],
    )
    if validate:
        # worker-side trust boundary: a fragment off the wire gets the same
        # sanity battery as the coordinator-side plan before it executes
        from trino_tpu.planner.sanity import PlanSanityChecker

        PlanSanityChecker.validate_deserialized(frag)
    return frag
