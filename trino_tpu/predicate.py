"""TupleDomain predicate algebra.

Reference: ``core/trino-spi/src/main/java/io/trino/spi/predicate/`` —
``Domain.java``, ``Range.java``, ``SortedRangeSet.java``, ``TupleDomain.java``,
translated from/to expressions by
``core/trino-main/src/main/java/io/trino/sql/planner/DomainTranslator.java``.

This algebra is shared by three engine features (as in the reference):
  1. scan pruning — skip splits whose min/max stats cannot satisfy the domain
     (``lib/trino-orc/.../TupleDomainOrcPredicate.java:74``);
  2. connector filter pushdown (``ConnectorMetadata.applyFilter``,
     ``iterative/rule/PushPredicateIntoTableScan.java``);
  3. dynamic filtering — build-side key domains shipped to probe-side scans
     (``server/DynamicFilterService.java:95``).

Values are Python scalars in *storage* representation (scaled ints for
decimals, day-ints for dates, raw ``str`` for varchar — comparable), so the
algebra is device-free: it runs on the coordinator/host, never inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional, Sequence

from trino_tpu import types as T
from trino_tpu.ir import Call, Constant, RowExpr, SpecialForm, Variable, special


_NEG_INF = object()
_POS_INF = object()


def _lt(a: Any, b: Any) -> bool:
    if a is _NEG_INF or b is _POS_INF:
        return True
    if a is _POS_INF or b is _NEG_INF:
        return False
    return a < b


def _le(a: Any, b: Any) -> bool:
    return not _lt(b, a)


@dataclasses.dataclass(frozen=True)
class Range:
    """[low, high] interval with open/closed bounds; None bound = unbounded.

    Mirrors ``spi/predicate/Range.java`` (Marker low/high).
    """

    low: Any = None  # None = -inf
    low_inclusive: bool = False
    high: Any = None  # None = +inf
    high_inclusive: bool = False

    @staticmethod
    def all() -> "Range":
        return Range()

    @staticmethod
    def equal(value: Any) -> "Range":
        return Range(value, True, value, True)

    @staticmethod
    def greater_than(value: Any) -> "Range":
        return Range(low=value, low_inclusive=False)

    @staticmethod
    def greater_or_equal(value: Any) -> "Range":
        return Range(low=value, low_inclusive=True)

    @staticmethod
    def less_than(value: Any) -> "Range":
        return Range(high=value, high_inclusive=False)

    @staticmethod
    def less_or_equal(value: Any) -> "Range":
        return Range(high=value, high_inclusive=True)

    def _lo(self):
        return _NEG_INF if self.low is None else self.low

    def _hi(self):
        return _POS_INF if self.high is None else self.high

    @property
    def is_single_value(self) -> bool:
        return (
            self.low is not None
            and self.low == self.high
            and self.low_inclusive
            and self.high_inclusive
        )

    def is_empty(self) -> bool:
        lo, hi = self._lo(), self._hi()
        if _lt(hi, lo):
            return True
        if lo is not _NEG_INF and lo == hi and not (self.low_inclusive and self.high_inclusive):
            return True
        return False

    def contains_value(self, v: Any) -> bool:
        lo, hi = self._lo(), self._hi()
        if _lt(v, lo) or _lt(hi, v):
            return False
        if v == lo and not self.low_inclusive and lo is not _NEG_INF:
            return False
        if v == hi and not self.high_inclusive and hi is not _POS_INF:
            return False
        return True

    def overlaps(self, other: "Range") -> bool:
        return not self.intersect(other).is_empty()

    def intersect(self, other: "Range") -> "Range":
        # max of lows
        if _lt(self._lo(), other._lo()):
            low, low_inc = other.low, other.low_inclusive
        elif _lt(other._lo(), self._lo()):
            low, low_inc = self.low, self.low_inclusive
        else:
            low = self.low
            low_inc = self.low_inclusive and other.low_inclusive
        # min of highs
        if _lt(self._hi(), other._hi()):
            high, high_inc = self.high, self.high_inclusive
        elif _lt(other._hi(), self._hi()):
            high, high_inc = other.high, other.high_inclusive
        else:
            high = self.high
            high_inc = self.high_inclusive and other.high_inclusive
        return Range(low, low_inc, high, high_inc)

    def _adjacent(self, other: "Range") -> bool:
        """True if self ∪ other is a single contiguous range."""
        if self.overlaps(other):
            return True
        # self.high touches other.low or vice versa
        for a, b in ((self, other), (other, self)):
            if a.high is not None and b.low is not None and a.high == b.low:
                if a.high_inclusive or b.low_inclusive:
                    return True
        return False

    def span(self, other: "Range") -> "Range":
        if _lt(self._lo(), other._lo()):
            low, low_inc = self.low, self.low_inclusive
        elif _lt(other._lo(), self._lo()):
            low, low_inc = other.low, other.low_inclusive
        else:
            low = self.low
            low_inc = self.low_inclusive or other.low_inclusive
        if _lt(other._hi(), self._hi()):
            high, high_inc = self.high, self.high_inclusive
        elif _lt(self._hi(), other._hi()):
            high, high_inc = other.high, other.high_inclusive
        else:
            high = self.high
            high_inc = self.high_inclusive or other.high_inclusive
        return Range(low, low_inc, high, high_inc)


@dataclasses.dataclass(frozen=True)
class ValueSet:
    """Union of disjoint sorted ranges (``SortedRangeSet.java``), or ALL.

    ``ranges`` is normalized: sorted by low bound, non-overlapping,
    non-adjacent, none empty.
    """

    ranges: tuple[Range, ...] = ()
    is_all: bool = False

    @staticmethod
    def all() -> "ValueSet":
        return ValueSet(is_all=True)

    @staticmethod
    def none() -> "ValueSet":
        return ValueSet(())

    @staticmethod
    def of_values(values: Iterable[Any]) -> "ValueSet":
        return ValueSet.of_ranges([Range.equal(v) for v in values])

    @staticmethod
    def of_ranges(ranges: Sequence[Range]) -> "ValueSet":
        rs = [r for r in ranges if not r.is_empty()]
        if not rs:
            return ValueSet.none()
        rs.sort(key=lambda r: (0 if r.low is None else 1, r.low if r.low is not None else 0, not r.low_inclusive))
        merged: list[Range] = [rs[0]]
        for r in rs[1:]:
            if merged[-1]._adjacent(r):
                merged[-1] = merged[-1].span(r)
            else:
                merged.append(r)
        return ValueSet(tuple(merged))

    def is_none(self) -> bool:
        return not self.is_all and not self.ranges

    @property
    def is_single_value(self) -> bool:
        return len(self.ranges) == 1 and self.ranges[0].is_single_value

    def discrete_values(self) -> Optional[list[Any]]:
        """Values if the set is a finite list of points, else None."""
        if self.is_all:
            return None
        vals = []
        for r in self.ranges:
            if not r.is_single_value:
                return None
            vals.append(r.low)
        return vals

    def contains_value(self, v: Any) -> bool:
        if self.is_all:
            return True
        return any(r.contains_value(v) for r in self.ranges)

    def intersect(self, other: "ValueSet") -> "ValueSet":
        if self.is_all:
            return other
        if other.is_all:
            return self
        out = []
        for a in self.ranges:
            for b in other.ranges:
                c = a.intersect(b)
                if not c.is_empty():
                    out.append(c)
        return ValueSet.of_ranges(out)

    def union(self, other: "ValueSet") -> "ValueSet":
        if self.is_all or other.is_all:
            return ValueSet.all()
        return ValueSet.of_ranges(list(self.ranges) + list(other.ranges))

    def overlaps(self, other: "ValueSet") -> bool:
        return not self.intersect(other).is_none()

    def span(self) -> Optional[Range]:
        if self.is_all or not self.ranges:
            return None
        out = self.ranges[0]
        for r in self.ranges[1:]:
            out = out.span(r)
        return out


@dataclasses.dataclass(frozen=True)
class Domain:
    """ValueSet + null admissibility (``spi/predicate/Domain.java``)."""

    values: ValueSet
    null_allowed: bool = False
    type: Optional[T.SqlType] = None

    @staticmethod
    def all(type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.all(), True, type_)

    @staticmethod
    def none(type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.none(), False, type_)

    @staticmethod
    def only_null(type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.none(), True, type_)

    @staticmethod
    def not_null(type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.all(), False, type_)

    @staticmethod
    def single_value(v: Any, type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.of_values([v]), False, type_)

    @staticmethod
    def of_values(vs: Iterable[Any], type_: Optional[T.SqlType] = None) -> "Domain":
        return Domain(ValueSet.of_values(vs), False, type_)

    def is_all(self) -> bool:
        return self.values.is_all and self.null_allowed

    def is_none(self) -> bool:
        return self.values.is_none() and not self.null_allowed

    def intersect(self, other: "Domain") -> "Domain":
        return Domain(
            self.values.intersect(other.values),
            self.null_allowed and other.null_allowed,
            self.type or other.type,
        )

    def union(self, other: "Domain") -> "Domain":
        return Domain(
            self.values.union(other.values),
            self.null_allowed or other.null_allowed,
            self.type or other.type,
        )

    def contains(self, v: Any) -> bool:
        if v is None:
            return self.null_allowed
        return self.values.contains_value(v)

    def overlaps_stats(self, min_v: Any, max_v: Any, has_null: bool = False) -> bool:
        """Can any value in [min_v, max_v] (± null) satisfy this domain?
        The split/stripe pruning test (``TupleDomainOrcPredicate.java:92``)."""
        if self.is_none():
            return False
        if has_null and self.null_allowed:
            return True
        if min_v is None or max_v is None:  # no stats -> cannot prune
            return True
        stats = ValueSet.of_ranges([Range(min_v, True, max_v, True)])
        return self.values.overlaps(stats)


@dataclasses.dataclass(frozen=True)
class TupleDomain:
    """Conjunction of per-column Domains; ``domains is None`` = NONE
    (contradiction). Mirrors ``spi/predicate/TupleDomain.java``."""

    domains: Optional[dict[str, Domain]] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.domains is not None:
            # normalize: drop ALL domains; collapse to NONE on any none()
            d = {k: v for k, v in self.domains.items() if not v.is_all()}
            if any(v.is_none() for v in d.values()):
                object.__setattr__(self, "domains", None)
            else:
                object.__setattr__(self, "domains", d)

    @staticmethod
    def all() -> "TupleDomain":
        return TupleDomain({})

    @staticmethod
    def none() -> "TupleDomain":
        return TupleDomain(None)

    def is_all(self) -> bool:
        return self.domains is not None and not self.domains

    def is_none(self) -> bool:
        return self.domains is None

    def domain(self, column: str) -> Domain:
        if self.domains is None:
            return Domain.none()
        return self.domains.get(column, Domain.all())

    def intersect(self, other: "TupleDomain") -> "TupleDomain":
        if self.is_none() or other.is_none():
            return TupleDomain.none()
        out = dict(self.domains)
        for k, v in other.domains.items():
            out[k] = out[k].intersect(v) if k in out else v
        return TupleDomain(out)

    def column_wise_union(self, other: "TupleDomain") -> "TupleDomain":
        """Loose union: per-column union for columns in BOTH (others drop to
        ALL). Sound over-approximation (``TupleDomain.columnWiseUnion``)."""
        if self.is_none():
            return other
        if other.is_none():
            return self
        out = {}
        for k in set(self.domains) & set(other.domains):
            out[k] = self.domains[k].union(other.domains[k])
        return TupleDomain(out)

    def overlaps_stats(self, stats: dict[str, tuple[Any, Any, bool]]) -> bool:
        """stats: column -> (min, max, has_null). Missing column = no stats."""
        if self.is_none():
            return False
        for col, dom in self.domains.items():
            if col in stats:
                mn, mx, hn = stats[col]
                if not dom.overlaps_stats(mn, mx, hn):
                    return False
        return True


# === expression <-> domain translation =====================================
# Reference: sql/planner/DomainTranslator.java (fromPredicate / toPredicate)

_COMPARISONS = {"eq", "ne", "lt", "le", "gt", "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


@dataclasses.dataclass
class ExtractionResult:
    """Mirrors DomainTranslator.ExtractionResult: the extracted TupleDomain
    plus the conjuncts it could NOT express (to keep as a residual filter)."""

    tuple_domain: TupleDomain
    remaining: list[RowExpr]


def extract_tuple_domain(conjuncts: Sequence[RowExpr]) -> ExtractionResult:
    td = TupleDomain.all()
    remaining: list[RowExpr] = []
    for c in conjuncts:
        sub = _extract_one(c)
        if sub is None:
            remaining.append(c)
        else:
            td = td.intersect(sub)
    return ExtractionResult(td, remaining)


def _as_var_const(e: RowExpr) -> Optional[tuple[Variable, Any, str]]:
    """Match  var OP const  or  const OP var  -> (var, value, op)."""
    if not (isinstance(e, Call) and e.name in _COMPARISONS and len(e.args) == 2):
        return None
    a, b = e.args
    if isinstance(a, Variable) and isinstance(b, Constant):
        return (a, b.value, e.name)
    if isinstance(a, Constant) and isinstance(b, Variable):
        return (b, a.value, _FLIP[e.name])
    return None


def _extract_one(e: RowExpr) -> Optional[TupleDomain]:
    # comparisons
    m = _as_var_const(e)
    if m is not None:
        var, value, op = m
        if value is None:
            return TupleDomain.none()  # x <op> NULL is never true
        if op == "eq":
            dom = Domain(ValueSet.of_values([value]), False, var.type)
        elif op == "ne":
            dom = Domain(
                ValueSet.of_ranges([Range.less_than(value), Range.greater_than(value)]),
                False,
                var.type,
            )
        elif op == "lt":
            dom = Domain(ValueSet.of_ranges([Range.less_than(value)]), False, var.type)
        elif op == "le":
            dom = Domain(ValueSet.of_ranges([Range.less_or_equal(value)]), False, var.type)
        elif op == "gt":
            dom = Domain(ValueSet.of_ranges([Range.greater_than(value)]), False, var.type)
        else:  # ge
            dom = Domain(ValueSet.of_ranges([Range.greater_or_equal(value)]), False, var.type)
        return TupleDomain({var.name: dom})

    if isinstance(e, SpecialForm):
        if e.form == "is_null" and len(e.args) == 1 and isinstance(e.args[0], Variable):
            v = e.args[0]
            return TupleDomain({v.name: Domain.only_null(v.type)})
        if e.form == "not" and len(e.args) == 1:
            inner = e.args[0]
            if (
                isinstance(inner, SpecialForm)
                and inner.form == "is_null"
                and len(inner.args) == 1
                and isinstance(inner.args[0], Variable)
            ):
                v = inner.args[0]
                return TupleDomain({v.name: Domain.not_null(v.type)})
            return None
        if e.form == "in" and e.args and isinstance(e.args[0], Variable):
            v = e.args[0]
            vals = []
            for a in e.args[1:]:
                if not isinstance(a, Constant):
                    return None
                if a.value is None:
                    continue  # NULL in the list can't make IN true for extraction
                vals.append(a.value)
            if not vals:
                return TupleDomain.none()
            return TupleDomain({v.name: Domain.of_values(vals, v.type)})
        if e.form == "between" and len(e.args) == 3 and isinstance(e.args[0], Variable):
            v, lo, hi = e.args
            if isinstance(lo, Constant) and isinstance(hi, Constant):
                if lo.value is None or hi.value is None:
                    return TupleDomain.none()
                return TupleDomain(
                    {v.name: Domain(
                        ValueSet.of_ranges([Range(lo.value, True, hi.value, True)]),
                        False,
                        v.type,
                    )}
                )
            return None
        if e.form == "and":
            out = TupleDomain.all()
            for a in e.args:
                sub = _extract_one(a)
                if sub is None:
                    return None
                out = out.intersect(sub)
            return out
        if e.form == "or":
            # OR of single-column constraints -> column-wise union only when
            # every branch constrains exactly the same one column (sound).
            subs = []
            for a in e.args:
                sub = _extract_one(a)
                if sub is None or sub.is_none() or sub.is_all() or len(sub.domains) != 1:
                    return None
                subs.append(sub)
            cols = {next(iter(s.domains)) for s in subs}
            if len(cols) != 1:
                return None
            out = subs[0]
            for s in subs[1:]:
                out = out.column_wise_union(s)
            return out
    return None


def to_row_expr(td: TupleDomain, types: dict[str, T.SqlType]) -> Optional[RowExpr]:
    """TupleDomain -> predicate expression (DomainTranslator.toPredicate).
    Returns None for ALL; a FALSE constant for NONE."""
    if td.is_all():
        return None
    if td.is_none():
        return Constant(type=T.BOOLEAN, value=False)
    conj: list[RowExpr] = []
    for col, dom in td.domains.items():
        ty = dom.type or types.get(col, T.BIGINT)
        var = Variable(type=ty, name=col)
        conj.append(_domain_to_expr(var, dom))
    out = conj[0]
    for c in conj[1:]:
        out = special("and", T.BOOLEAN, out, c)
    return out


def _domain_to_expr(var: Variable, dom: Domain) -> RowExpr:
    def cmp(op: str, v: Any) -> RowExpr:
        return Call(type=T.BOOLEAN, name=op, args=(var, Constant(type=var.type, value=v)))

    null_test = special("is_null", T.BOOLEAN, var)
    if dom.values.is_none():
        return null_test if dom.null_allowed else Constant(type=T.BOOLEAN, value=False)
    if dom.values.is_all:
        if dom.null_allowed:
            return Constant(type=T.BOOLEAN, value=True)
        return special("not", T.BOOLEAN, null_test)

    discrete = dom.values.discrete_values()
    if discrete is not None and len(discrete) > 1:
        value_expr: RowExpr = special(
            "in", T.BOOLEAN, var, *[Constant(type=var.type, value=v) for v in discrete]
        )
    else:
        parts: list[RowExpr] = []
        for r in dom.values.ranges:
            if r.is_single_value:
                parts.append(cmp("eq", r.low))
                continue
            sub: list[RowExpr] = []
            if r.low is not None:
                sub.append(cmp("ge" if r.low_inclusive else "gt", r.low))
            if r.high is not None:
                sub.append(cmp("le" if r.high_inclusive else "lt", r.high))
            if not sub:
                parts.append(Constant(type=T.BOOLEAN, value=True))
            else:
                e = sub[0]
                for s in sub[1:]:
                    e = special("and", T.BOOLEAN, e, s)
                parts.append(e)
        value_expr = parts[0]
        for p in parts[1:]:
            value_expr = special("or", T.BOOLEAN, value_expr, p)
    if dom.null_allowed:
        return special("or", T.BOOLEAN, value_expr, null_test)
    return value_expr
