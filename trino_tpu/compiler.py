"""Expression compiler: RowExpr IR -> jnp ops with SQL NULL semantics.

This is the TPU analog of Trino's bytecode codegen tier
(``core/trino-main/src/main/java/io/trino/sql/gen/ExpressionCompiler.java:56``):
instead of generating JVM classes for fused filter/project loops, we evaluate
the IR symbolically over device arrays inside a traced function and let XLA
fuse everything into one kernel.

Every expression evaluates to a pair ``(data, valid)`` of arrays (SQL
three-valued logic). String predicates are evaluated host-side over the
column dictionary and gathered on device (dictionary-first string design).

Known deviations from reference semantics (documented, to fix later):
- Division by zero yields NULL instead of failing the query.
- DECIMAL accumulation beyond 18 digits can overflow int64 (Trino uses
  128-bit; ``spi/type/UnscaledDecimal128Arithmetic.java``).
"""

from __future__ import annotations

import re
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Column, Dictionary
from trino_tpu.ir import (
    Call,
    Constant,
    HoistedConstant,
    InputRef,
    RowExpr,
    SpecialForm,
)

Pair = tuple[jnp.ndarray, jnp.ndarray]  # (data, valid)


def _storage_constant(expr: Constant, dictionary: Dictionary | None, n: int) -> Pair:
    t = expr.type
    if expr.value is None:
        return (
            jnp.zeros(n, dtype=t.storage_dtype),
            jnp.zeros(n, dtype=jnp.bool_),
        )
    v = expr.value
    if T.is_string(t):
        assert dictionary is not None  # guarded by _eval
        code = dictionary.encode(v)
        return jnp.full(n, code, dtype=jnp.int32), jnp.ones(n, dtype=jnp.bool_)
    if isinstance(t, T.DecimalType) and isinstance(v, int) and abs(v) >= 1 << 63:
        # literal beyond int64: wide (n, 2) lanes
        from trino_tpu.ops.decimal128 import int_to_pair

        hi, lo = int_to_pair(v)
        data = jnp.stack(
            [jnp.full(n, hi, dtype=jnp.int64), jnp.full(n, lo, dtype=jnp.int64)],
            axis=1,
        )
        return data, jnp.ones(n, dtype=jnp.bool_)
    return (
        jnp.full(n, v, dtype=t.storage_dtype),
        jnp.ones(n, dtype=jnp.bool_),
    )


def _all_valid(a: Pair, b: Pair) -> jnp.ndarray:
    return a[1] & b[1]


def _is_wide(data) -> bool:
    """Wide DECIMAL storage: (n, 2) int64 (hi, lo) lanes."""
    return getattr(data, "ndim", 1) == 2


def _as_pair128(data, scale: int, target_scale: int):
    """Any decimal storage -> (hi, lo) lanes rescaled up to target_scale."""
    from trino_tpu.ops import decimal128 as D128

    if _is_wide(data):
        hi, lo = data[:, 0], data[:, 1]
    else:
        hi, lo = D128.widen_i64(data.astype(jnp.int64))
    if target_scale > scale:
        hi, lo = D128.rescale_up_wide(hi, lo, target_scale - scale)
    elif target_scale < scale:
        raise NotImplementedError("DECIMAL(38) downscale")
    return hi, lo


def _check_int_overflow(name, rt, a64, b64, r64, valid):
    """Raise on integer overflow like the reference (eager paths only —
    under tracing the check is skipped and int64 semantics apply)."""
    try:
        if rt.bits < 64:
            info = np.iinfo(rt.storage_dtype)
            bad = valid & ((r64 < info.min) | (r64 > info.max))
        elif name == "multiply":
            from trino_tpu.ops.decimal128 import mul_i64_overflows

            bad = valid & mul_i64_overflows(a64, b64)
        else:
            same_sign = (a64 >= 0) == (
                (b64 >= 0) if name == "add" else (b64 < 0)
            )
            bad = valid & same_sign & ((r64 >= 0) != (a64 >= 0))
        any_bad = bool(jnp.any(bad))
    except Exception:  # noqa: BLE001 — traced values can't concretize
        return
    if any_bad:
        raise ArithmeticError(f"{rt.name} overflow")


def _pool_values_pair(et, vals, codes, valid, ec) -> Pair:
    """Per-pool-code values -> (data, valid) pair of element type ``et``
    (strings re-enter the dictionary machinery via the compiler's unified
    dictionary when present)."""
    present = np.asarray([v is not None for v in vals] + [False], dtype=np.bool_)
    if T.is_string(et):
        # string elements need the projection's unified dictionary to
        # absorb pool values — UNNEST covers that shape today
        raise NotImplementedError(
            "element_at over ARRAY(varchar) — use UNNEST"
        )
    table = np.asarray(
        [v if v is not None else 0 for v in vals] + [0],
        dtype=et.storage_dtype,
    )
    out = jnp.asarray(table)[jnp.clip(codes, 0, len(table) - 1)]
    out_valid = valid & jnp.asarray(present)[jnp.clip(codes, 0, len(present) - 1)]
    return out, out_valid


def _avalanche64(x):
    """xxhash64/murmur3 finalizer over int64 lanes."""
    x = x.astype(jnp.uint64)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> jnp.uint64(33))
    return x.astype(jnp.int64)


def _widen_storage(data):
    """Any decimal storage -> wide (n, 2) lanes."""
    if _is_wide(data):
        return data
    from trino_tpu.ops.decimal128 import widen_i64

    hi, lo = widen_i64(data.astype(jnp.int64))
    return jnp.stack([hi, lo], axis=1)


def _where_pair(mask, x, y):
    """jnp.where that follows wide (n, 2) operands (mask stays (n,))."""
    if _is_wide(x) or _is_wide(y):
        return jnp.where(mask[:, None], _widen_storage(x), _widen_storage(y))
    return jnp.where(mask, x, y)


def _wide_to_double(data, scale: int):
    lo_u = data[:, 1].astype(jnp.float64) + jnp.where(
        data[:, 1] < 0, jnp.float64(2**64), jnp.float64(0)
    )
    f = data[:, 0].astype(jnp.float64) * jnp.float64(2**64) + lo_u
    return f / (10**scale)


def _narrow_checked(data, what: str):
    """Wide storage -> int64, erroring if any value does not fit."""
    if not _is_wide(data):
        return data.astype(jnp.int64)
    hi, lo = data[:, 0], data[:, 1]
    fits = hi == (lo >> jnp.int64(63))
    try:
        ok = bool(jnp.all(fits))  # eager: concrete check
    except Exception:  # traced: fused path excludes these shapes upstream
        ok = True
    if not ok:
        raise ArithmeticError(f"{what}: DECIMAL value exceeds 18 digits")
    return lo


def _rescale(data: jnp.ndarray, from_scale: int, to_scale: int) -> jnp.ndarray:
    if to_scale == from_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    # scale down with round-half-up (Trino semantics)
    f = 10 ** (from_scale - to_scale)
    half = f // 2
    return jnp.where(data >= 0, (data + half) // f, -((-data + half) // f))


def _dec_scale(t: T.SqlType) -> int:
    return t.scale if isinstance(t, T.DecimalType) else 0


class ExprCompiler:
    """Evaluates a RowExpr tree over a batch's columns.

    The instance is constructed per (expression, input schema, dictionaries)
    and its ``__call__`` is traced under jit — dictionaries are compile-time
    constants, so host-evaluated string predicates become baked-in gathers.
    """

    def __init__(
        self,
        columns: Sequence[Column],
        string_dictionary: Dictionary | None = None,
        params: Sequence | None = None,
    ):
        self.columns = list(columns)
        self.n = self.columns[0].capacity if self.columns else 1
        # unified dictionary context: when set, string constants encode
        # against it (the executor remaps referenced string columns into it
        # first — see exec.local._unify_strings)
        self.string_dictionary = string_dictionary
        # ordered parameter vector of a canonicalized plan (device scalars
        # under tracing, host scalars eagerly); HoistedConstants read it
        # so literal variants share one traced program
        self.params = params

    # -- entry points -----------------------------------------------------
    def evaluate(self, expr: RowExpr) -> Pair:
        return self._eval(expr)

    def predicate_mask(self, expr: RowExpr) -> jnp.ndarray:
        """NULL -> false, per SQL WHERE semantics."""
        data, valid = self._eval(expr)
        return data & valid

    # -- dispatch ---------------------------------------------------------
    def _eval(self, expr: RowExpr) -> Pair:
        if isinstance(expr, InputRef):
            c = self.columns[expr.channel]
            return c.data, c.valid_mask()
        if isinstance(expr, HoistedConstant):
            if self.params is not None:
                p = self.params[expr.index]
                data = jnp.broadcast_to(
                    jnp.asarray(p).astype(expr.type.storage_dtype), (self.n,)
                )
                return data, jnp.ones(self.n, dtype=jnp.bool_)
            if expr.value is None:
                # only reachable by executing a serde round-tripped
                # canonical plan without its parameter vector
                raise ValueError(
                    f"hoisted constant param[{expr.index}] evaluated "
                    "without a parameter vector"
                )
            # no params supplied: fall through and bake the kept value
        if isinstance(expr, Constant):
            if T.is_string(expr.type) and expr.value is not None:
                if self.string_dictionary is None:
                    # String literals are evaluable inside comparisons/LIKE
                    # (column dictionary context) or under a unified
                    # dictionary (string-valued projections).
                    raise NotImplementedError(
                        "string literal outside a comparison context"
                    )
                return _storage_constant(expr, self.string_dictionary, self.n)
            return _storage_constant(expr, None, self.n)
        if isinstance(expr, SpecialForm):
            return self._special(expr)
        if isinstance(expr, Call):
            return self._call(expr)
        raise TypeError(f"unknown IR node {expr!r}")

    def _arg_dictionary(self, e: RowExpr) -> Dictionary | None:
        if isinstance(e, InputRef):
            return self.columns[e.channel].dictionary
        return None

    # -- special forms ----------------------------------------------------
    def _special(self, expr: SpecialForm) -> Pair:
        form = expr.form
        if form == "and":
            acc = None
            for a in expr.args:
                p = self._eval(a)
                acc = p if acc is None else _kleene_and(acc, p)
            return acc
        if form == "or":
            acc = None
            for a in expr.args:
                p = self._eval(a)
                acc = p if acc is None else _kleene_or(acc, p)
            return acc
        if form == "not":
            d, v = self._eval(expr.args[0])
            return ~d, v
        if form == "if":
            cond, then, other = (self._eval(a) for a in expr.args)
            take_then = cond[0] & cond[1]
            data = _where_pair(take_then, then[0], other[0])
            valid = jnp.where(take_then, then[1], other[1])
            return data, valid
        if form == "coalesce":
            data, valid = self._eval(expr.args[0])
            for a in expr.args[1:]:
                d2, v2 = self._eval(a)
                data = _where_pair(valid, data, d2)
                valid = valid | v2
            return data, valid
        if form == "is_null":
            _, v = self._eval(expr.args[0])
            return ~v, jnp.ones_like(v)
        if form == "null_if":
            a, b = self._eval(expr.args[0]), self._eval(expr.args[1])
            if _is_wide(a[0]) or _is_wide(b[0]):
                from trino_tpu.ops.decimal128 import compare128

                sa = _dec_scale(expr.args[0].type)
                sb = _dec_scale(expr.args[1].type)
                s = max(sa, sb)
                ahi, alo = _as_pair128(a[0], sa, s)
                bhi, blo = _as_pair128(b[0], sb, s)
                same = compare128(ahi, alo, bhi, blo) == 0
                eq = same & a[1] & b[1]
            else:
                sa = _dec_scale(expr.args[0].type)
                sb = _dec_scale(expr.args[1].type)
                if sa != sb:
                    s = max(sa, sb)
                    ad = _rescale(a[0].astype(jnp.int64), sa, s)
                    bd = _rescale(b[0].astype(jnp.int64), sb, s)
                    eq = (ad == bd) & a[1] & b[1]
                else:
                    eq = (a[0] == b[0]) & a[1] & b[1]
            return a[0], a[1] & ~eq
        if form == "in":
            # args[0] IN (args[1:]) — chain of equality ORs (small lists)
            needle = expr.args[0]
            acc: Pair | None = None
            for candidate in expr.args[1:]:
                eq = self._call(
                    Call(type=T.BOOLEAN, name="eq", args=(needle, candidate))
                )
                acc = eq if acc is None else _kleene_or(acc, eq)
            return acc
        if form == "between":
            val, lo, hi = expr.args
            ge = Call(type=T.BOOLEAN, name="ge", args=(val, lo))
            le = Call(type=T.BOOLEAN, name="le", args=(val, hi))
            return _kleene_and(self._eval(ge), self._eval(le))
        raise NotImplementedError(f"special form {form}")

    # -- calls ------------------------------------------------------------
    def _call(self, expr: Call) -> Pair:
        name = expr.name
        if name in ("add", "subtract", "multiply", "divide", "modulus"):
            return self._arith(expr)
        if name in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._compare(expr)
        if name == "negate":
            d, v = self._eval(expr.args[0])
            if _is_wide(d):
                from trino_tpu.ops.decimal128 import neg128

                hi, lo = neg128(d[:, 0], d[:, 1])
                return jnp.stack([hi, lo], axis=1), v
            return -d, v
        if name == "abs":
            d, v = self._eval(expr.args[0])
            if _is_wide(d):
                from trino_tpu.ops.decimal128 import neg128

                hi, lo = neg128(d[:, 0], d[:, 1])
                neg = d[:, 0] < 0
                return (
                    jnp.stack(
                        [
                            jnp.where(neg, hi, d[:, 0]),
                            jnp.where(neg, lo, d[:, 1]),
                        ],
                        axis=1,
                    ),
                    v,
                )
            return jnp.abs(d), v
        if name == "cast":
            return self._cast(expr)
        if name in ("year", "month", "day"):
            return self._extract(expr)
        if name == "date_add_days":
            d, v = self._eval(expr.args[0])
            delta, dv = self._eval(expr.args[1])
            return (d + delta.astype(d.dtype)), v & dv
        if name == "date_add_months":
            d, v = self._eval(expr.args[0])
            months, mv = self._eval(expr.args[1])
            y, m, dd = _civil_from_days(d.astype(jnp.int32))
            total = y * 12 + (m - 1) + months.astype(jnp.int32)
            y2 = total // 12
            m2 = total % 12 + 1
            dd2 = jnp.minimum(dd, _days_in_month_vec(y2, m2))
            out = _days_from_civil_vec(y2, m2, dd2)
            return out.astype(d.dtype), v & mv
        if name == "power":
            a, av = self._eval(expr.args[0])
            b, bv = self._eval(expr.args[1])
            return jnp.power(a, b), av & bv
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_right_shift_arithmetic"):
            a, av = self._eval(expr.args[0])
            b, bv = self._eval(expr.args[1])
            a = a.astype(jnp.int64)
            b = b.astype(jnp.int64)
            if name == "bitwise_and":
                r = a & b
            elif name == "bitwise_or":
                r = a | b
            elif name == "bitwise_xor":
                r = a ^ b
            elif name == "bitwise_left_shift":
                shifted = (
                    a.astype(jnp.uint64) << (b.astype(jnp.uint64) & jnp.uint64(63))
                ).astype(jnp.int64)
                r = jnp.where(b >= 64, jnp.int64(0), shifted)
            elif name == "bitwise_right_shift":
                shifted = (
                    a.astype(jnp.uint64) >> (b.astype(jnp.uint64) & jnp.uint64(63))
                ).astype(jnp.int64)
                r = jnp.where(b >= 64, jnp.int64(0), shifted)
            else:  # arithmetic right shift: >=64 saturates to the sign fill
                r = jnp.where(b >= 64, a >> jnp.int64(63), a >> (b & jnp.int64(63)))
            return r, av & bv
        if name == "bitwise_not":
            d, v = self._eval(expr.args[0])
            return ~d.astype(jnp.int64), v
        if name == "hash64":
            # xxhash64-style avalanche finalizer (checksum building block)
            d, v = self._eval(expr.args[0])
            at = expr.args[0].type
            if _is_wide(d):
                # mix both 64-bit lanes
                lanes = _avalanche64(d[:, 0]) ^ _avalanche64(
                    d[:, 1] ^ jnp.int64(0x5851F42D4C957F2D - 2**63)
                )
                out = lanes
            elif isinstance(at, (T.DoubleType, T.RealType)):
                # decompose (no f64 bitcasts on TPU x64): mantissa + exponent
                m, e = jnp.frexp(d.astype(jnp.float64))
                im = (m * (2.0**53)).astype(jnp.int64)
                out = _avalanche64(im ^ (e.astype(jnp.int64) << jnp.int64(53)))
            else:
                out = _avalanche64(d.astype(jnp.int64))
            # NULL hashes to a fixed constant so checksum reflects NULLs
            return jnp.where(v, out, jnp.int64(0x9E3779B97F4A7C15 - 2**63)), jnp.ones_like(v)
        if name == "str_hash64":
            # content hash of a dictionary string column (deterministic
            # across processes/dictionary assignments)
            import hashlib

            col_e = expr.args[0]
            d, v = self._eval(col_e)
            dictionary = self._arg_dictionary(col_e)
            if dictionary is None:
                raise ValueError("str_hash64 on string column without dictionary")
            table = np.asarray(
                [
                    int.from_bytes(
                        hashlib.blake2b(
                            s.encode("utf-8", "surrogatepass"), digest_size=8
                        ).digest(),
                        "little",
                        signed=True,
                    )
                    for s in dictionary.values
                ]
                + [0],
                dtype=np.int64,
            )
            out = jnp.asarray(table)[jnp.clip(d, 0, len(table) - 1)]
            return jnp.where(v, out, jnp.int64(0x9E3779B97F4A7C15 - 2**63)), jnp.ones_like(v)
        if name == "width_bucket":
            x, xv = self._eval(expr.args[0])
            lo, lov = self._eval(expr.args[1])
            hi, hiv = self._eval(expr.args[2])
            nb, nbv = self._eval(expr.args[3])
            nb = nb.astype(jnp.int64)
            valid = xv & lov & hiv & nbv
            try:
                if bool(jnp.any(valid & (hi == lo))):
                    raise ArithmeticError("width_bucket bounds cannot be equal")
            except ArithmeticError:
                raise
            except Exception:  # noqa: BLE001 — traced: skip the eager check
                pass
            span = jnp.where(hi == lo, 1.0, hi - lo)
            raw = (jnp.floor((x - lo) / span * nb.astype(jnp.float64)) + 1).astype(
                jnp.int64
            )
            asc = jnp.where(
                x < lo, jnp.int64(0), jnp.where(x >= hi, nb + 1, raw)
            )
            # descending bounds (bound1 > bound2): reference supports both
            desc = jnp.where(
                x > lo, jnp.int64(0), jnp.where(x <= hi, nb + 1, raw)
            )
            r = jnp.where(lo <= hi, asc, desc)
            return r.astype(jnp.int64), valid
        if name == "like":
            return self._like(expr)
        if name in ("length", "strpos", "starts_with"):
            return self._string_table(expr)
        if name in ("cardinality", "element_at", "array_contains"):
            return self._array_table(expr)
        if name in ("map_cardinality", "map_element_at", "row_field"):
            return self._map_row_table(expr)
        if name == "substr_pred":  # reserved for host-eval string predicates
            raise NotImplementedError
        if name == "sqrt":
            d, v = self._eval(expr.args[0])
            return jnp.sqrt(d), v
        if name in ("floor", "ceil"):
            d, v = self._eval(expr.args[0])
            t = expr.args[0].type
            if isinstance(t, T.DecimalType):
                f = t.unscale
                q = jnp.floor_divide(d, f) if name == "floor" else -jnp.floor_divide(-d, f)
                return q * f, v
            fn = jnp.floor if name == "floor" else jnp.ceil
            return fn(d), v
        if name == "round":
            return self._round(expr)
        if name == "string_pred":
            # host-compiled predicate over a dictionary column:
            # args = (col, Constant(mask_table)) — see analyzer lowering
            raise NotImplementedError
        if name in _UNARY_MATH:
            d, v = self._eval(expr.args[0])
            return _UNARY_MATH[name](d), v
        if name == "atan2":
            a, av = self._eval(expr.args[0])
            b, bv = self._eval(expr.args[1])
            return jnp.arctan2(a, b), av & bv
        if name == "sign":
            d, v = self._eval(expr.args[0])
            return jnp.sign(d), v
        if name == "truncate":
            d, v = self._eval(expr.args[0])
            return jnp.trunc(d), v
        if name in ("greatest", "least"):
            # SQL: NULL if ANY argument is NULL (spi semantics)
            pairs = [self._eval(a) for a in expr.args]
            fn = jnp.maximum if name == "greatest" else jnp.minimum
            out, valid = pairs[0]
            for d, v in pairs[1:]:
                out = fn(out, d)
                valid = valid & v
            return out, valid
        if name in ("regexp_like", "codepoint"):
            return self._string_table(expr)
        if name == "date_trunc":
            return self._date_trunc(expr)
        if name == "date_diff_days":
            a, av = self._eval(expr.args[0])
            b, bv = self._eval(expr.args[1])
            return (
                b.astype(jnp.int64) - a.astype(jnp.int64),
                av & bv,
            )
        if name in ("day_of_week", "day_of_year", "week", "quarter",
                    "last_day_of_month"):
            return self._date_field(expr)
        raise NotImplementedError(f"scalar function {name}")

    def _date_field(self, expr: Call) -> Pair:
        d, v = self._eval(expr.args[0])
        st = expr.args[0].type
        if isinstance(st, T.TimestampType):
            days = (d // 86_400_000_000).astype(jnp.int32)
        else:
            days = d.astype(jnp.int32)
        name = expr.name
        if name == "day_of_week":
            # ISO: Monday=1..Sunday=7 (1970-01-01 was a Thursday)
            return ((days + 3) % 7 + 1).astype(jnp.int64), v
        y, m, dd = _civil_from_days(days)
        if name == "quarter":
            return ((m - 1) // 3 + 1).astype(jnp.int64), v
        if name == "last_day_of_month":
            return _days_from_civil_vec(y, m, _days_in_month_vec(y, m)).astype(
                jnp.int32
            ), v
        jan1 = _days_from_civil_vec(y, jnp.ones_like(m), jnp.ones_like(dd))
        doy = (days - jan1 + 1).astype(jnp.int64)
        if name == "day_of_year":
            return doy, v
        # ISO 8601 week number
        dow = (days + 3) % 7 + 1  # Monday=1
        w = (doy - dow + 10) // 7
        # w == 0: belongs to the previous year's last week (52 or 53)
        prev_y = y - 1
        prev_len = jnp.where(_is_leap(prev_y), 366, 365)
        prev_jan1_dow = ((jan1 - prev_len) + 3) % 7 + 1
        prev_has53 = (prev_jan1_dow == 4) | (_is_leap(prev_y) & (prev_jan1_dow == 3))
        w0 = jnp.where(prev_has53, 53, 52)
        # w == 53: only valid when this year has 53 ISO weeks
        jan1_dow = (jan1 + 3) % 7 + 1
        has53 = (jan1_dow == 4) | (_is_leap(y) & (jan1_dow == 3))
        w = jnp.where(w == 0, w0, w)
        w = jnp.where((w == 53) & ~has53, 1, w)
        return w.astype(jnp.int64), v

    def _date_trunc(self, expr: Call) -> Pair:
        unit_e = expr.args[0]
        assert isinstance(unit_e, Constant)
        unit = str(unit_e.value).lower()
        d, v = self._eval(expr.args[1])
        st = expr.args[1].type
        if isinstance(st, T.TimestampType):
            us_per = {"second": 10**6, "minute": 60 * 10**6, "hour": 3600 * 10**6,
                      "day": 86_400 * 10**6}
            if unit in us_per:
                p = us_per[unit]
                return (d // p) * p, v
            days = (d // 86_400_000_000).astype(jnp.int32)
            trunc_days = self._trunc_days(days, unit)
            return trunc_days.astype(jnp.int64) * 86_400_000_000, v
        days = d.astype(jnp.int32)
        if unit == "day":
            return days, v
        return self._trunc_days(days, unit), v

    def _trunc_days(self, days, unit: str):
        y, m, dd = _civil_from_days(days)
        if unit == "year":
            m = jnp.ones_like(m)
            dd = jnp.ones_like(dd)
        elif unit == "quarter":
            m = ((m - 1) // 3) * 3 + 1
            dd = jnp.ones_like(dd)
        elif unit == "month":
            dd = jnp.ones_like(dd)
        elif unit == "week":
            # ISO-style: truncate to Monday (1970-01-01 was a Thursday)
            dow = (days + 3) % 7  # 0 = Monday
            return days - dow
        else:
            raise NotImplementedError(f"date_trunc unit {unit}")
        return _days_from_civil_vec(y, m, dd)

    def _arith(self, expr: Call) -> Pair:
        a_t, b_t = expr.args[0].type, expr.args[1].type
        a, b = self._eval(expr.args[0]), self._eval(expr.args[1])
        valid = _all_valid(a, b)
        rt = expr.type
        name = expr.name
        if isinstance(rt, T.DecimalType) and (
            rt.wide or _is_wide(a[0]) or _is_wide(b[0])
        ):
            return self._arith_wide(expr, a, b, valid)
        if isinstance(rt, T.DecimalType):
            rs = rt.scale
            sa, sb = _dec_scale(a_t), _dec_scale(b_t)
            ad = a[0].astype(jnp.int64)
            bd = b[0].astype(jnp.int64)
            if name == "add":
                return _rescale(ad, sa, rs) + _rescale(bd, sb, rs), valid
            if name == "subtract":
                return _rescale(ad, sa, rs) - _rescale(bd, sb, rs), valid
            if name == "multiply":
                raw = ad * bd  # scale sa+sb
                return _rescale(raw, sa + sb, rs), valid
            if name in ("divide", "modulus"):
                return self._arith_narrow_decimal(
                    expr, (ad, a[1]), (bd, b[1]), valid, sa, sb, rs
                )
        # float/int paths: cast both to result dtype
        dt = rt.storage_dtype
        ad = _cast_numeric(a[0], a_t, rt)
        bd = _cast_numeric(b[0], b_t, rt)
        if T.is_integer(rt) and name in ("add", "subtract", "multiply"):
            # compute in int64 and range-check: the reference raises
            # "integer overflow" instead of wrapping (eager paths only;
            # traced fragments inherit int64 behavior)
            a64 = ad.astype(jnp.int64)
            b64 = bd.astype(jnp.int64)
            if name == "add":
                r64 = a64 + b64
            elif name == "subtract":
                r64 = a64 - b64
            else:
                r64 = a64 * b64
            _check_int_overflow(name, rt, a64, b64, r64, valid)
            return r64.astype(dt), valid
        if name == "add":
            return ad + bd, valid
        if name == "subtract":
            return ad - bd, valid
        if name == "multiply":
            return ad * bd, valid
        if name == "divide":
            if np.issubdtype(dt, np.integer):
                bz = jnp.where(bd == 0, 1, bd)
                q = jnp.where((ad >= 0) == (bd >= 0), jnp.abs(ad) // jnp.abs(bz),
                              -(jnp.abs(ad) // jnp.abs(bz)))
                return q.astype(dt), valid & (bd != 0)
            bz = jnp.where(bd == 0, jnp.asarray(1, dtype=dt), bd)
            return ad / bz, valid & (bd != 0)
        if name == "modulus":
            bz = jnp.where(bd == 0, 1, bd)
            # fmod truncates toward zero (sign of dividend) = Trino MOD
            return jnp.fmod(ad, bz), valid & (bd != 0)
        raise AssertionError(name)

    def _arith_wide(self, expr: Call, a: Pair, b: Pair, valid) -> Pair:
        """DECIMAL arithmetic in 128-bit (hi, lo) lanes (reference:
        UnscaledDecimal128Arithmetic add/multiply). Division/modulus of
        wide values narrows at runtime when the operands fit int64 and
        errors otherwise (the fused path excludes these shapes)."""
        from trino_tpu.ops import decimal128 as D128

        rt = expr.type
        a_t, b_t = expr.args[0].type, expr.args[1].type
        sa, sb = _dec_scale(a_t), _dec_scale(b_t)
        name = expr.name
        if name == "multiply":
            # result scale == sa + sb: no rescale needed
            aw, bw = _is_wide(a[0]), _is_wide(b[0])
            if not aw and not bw:
                hi, lo = D128.mul_i64_to_i128(
                    a[0].astype(jnp.int64), b[0].astype(jnp.int64)
                )
            elif aw and not bw:
                hi, lo = D128.mul128_by_i64(
                    a[0][:, 0], a[0][:, 1], b[0].astype(jnp.int64)
                )
            elif bw and not aw:
                hi, lo = D128.mul128_by_i64(
                    b[0][:, 0], b[0][:, 1], a[0].astype(jnp.int64)
                )
            else:
                raise NotImplementedError("DECIMAL(38) * DECIMAL(38)")
            return jnp.stack([hi, lo], axis=1), valid
        if name in ("add", "subtract"):
            ahi, alo = _as_pair128(a[0], sa, rt.scale)
            bhi, blo = _as_pair128(b[0], sb, rt.scale)
            if name == "subtract":
                bhi, blo = D128.neg128(bhi, blo)
            hi, lo = D128.add128(ahi, alo, bhi, blo)
            return jnp.stack([hi, lo], axis=1), valid
        if name == "divide":
            # exact 128-bit long division with HALF_UP rounding
            # (reference: UnscaledDecimal128Arithmetic.divideRoundUp) —
            # fully traced, so wide division fuses
            shift = rt.scale - sa + sb
            ahi, alo = _as_pair128(a[0], 0, 0)
            bhi, blo = _as_pair128(b[0], 0, 0)
            if shift < 0:
                bhi, blo = D128.rescale_up_wide(bhi, blo, -shift)
                shift = 0
            qhi, qlo, ok = D128.div128_round(ahi, alo, bhi, blo, shift)
            valid = valid & ok
            if rt.wide:
                return jnp.stack([qhi, qlo], axis=1), valid
            return qlo, valid
        if name == "modulus":
            # narrow at runtime (exact when operands fit int64); queries
            # whose operands genuinely exceed int64 error rather than
            # silently truncate
            ad = _narrow_checked(a[0], "decimal modulus")
            bd = _narrow_checked(b[0], "decimal modulus")
            narrowed = Call(
                type=T.decimal(18, rt.scale), name=name, args=expr.args
            )
            return self._arith_narrow_decimal(
                narrowed, (ad, a[1]), (bd, b[1]), valid, sa, sb, rt.scale
            )
        raise AssertionError(name)

    def _arith_narrow_decimal(self, expr, a, b, valid, sa, sb, rs):
        """int64 decimal divide/modulus, shared by the narrow type path and
        the runtime-narrowed wide path."""
        name = expr.name
        ad = a[0].astype(jnp.int64)
        bd = b[0].astype(jnp.int64)
        if name == "divide":
            # result scale rs: q = round(a * 10^(rs - sa + sb) / b)
            shift = rs - sa + sb
            num = ad * (10 ** max(shift, 0))
            den = jnp.where(bd == 0, 1, bd)
            if shift < 0:
                den = den * (10 ** (-shift))
            half = jnp.abs(den) // 2
            q = jnp.where(
                (num >= 0) == (den > 0),
                (jnp.abs(num) + half) // jnp.abs(den),
                -((jnp.abs(num) + half) // jnp.abs(den)),
            )
            return q, valid & (bd != 0)
        if name == "modulus":
            # Trino MOD: operands aligned to a common scale, truncating
            # division (result keeps the dividend's sign)
            s = max(sa, sb)
            an = _rescale(ad, sa, s)
            bn = _rescale(bd, sb, s)
            bz = jnp.where(bn == 0, 1, bn)
            q = jnp.where(
                (an >= 0) == (bz > 0),
                jnp.abs(an) // jnp.abs(bz),
                -(jnp.abs(an) // jnp.abs(bz)),
            )
            r = an - q * bz
            return _rescale(r, s, rs), valid & (bn != 0)
        raise AssertionError(name)

    def _pool_for_arg(self, expr: Call):
        """Shared constant/column scaffolding for pool-coded values
        (ARRAY/MAP/ROW): returns (codes, valid, pool)."""
        col_e = expr.args[0]
        if isinstance(col_e, Constant):
            from trino_tpu.columnar import Dictionary

            pool = Dictionary([col_e.value if col_e.value is not None else ()])
            d = jnp.zeros(self.n, dtype=jnp.int32)
            v = jnp.full(self.n, col_e.value is not None, dtype=jnp.bool_)
        else:
            d, v = self._eval(col_e)
            pool = self._arg_dictionary(col_e)
        if pool is None:
            raise ValueError(f"{expr.name} on column without value pool")
        return d, v, pool

    def _pool_length_table(self, entries, d, v) -> Pair:
        table = np.asarray([len(e) for e in entries] + [0], dtype=np.int64)
        out = jnp.asarray(table)[jnp.clip(d, 0, len(table) - 1)]
        return out, v

    def _array_table(self, expr: Call) -> Pair:
        """Array functions over pool-coded arrays: per-code host lookup
        tables gathered on device (the dictionary-function pattern —
        reference scalars: ArrayFunctions / spi/block/ArrayBlock)."""
        d, v, pool = self._pool_for_arg(expr)
        tuples = pool.values
        name = expr.name
        if name == "cardinality":
            return self._pool_length_table(tuples, d, v)
        if name == "element_at":
            idx_e = expr.args[1]
            if not isinstance(idx_e, Constant) or idx_e.value is None:
                raise NotImplementedError("element_at index must be a literal")
            i = int(idx_e.value)
            et = expr.type
            vals = []
            for t_ in tuples:
                j = i - 1 if i > 0 else len(t_) + i
                vals.append(t_[j] if 0 <= j < len(t_) else None)
            return _pool_values_pair(et, vals, d, v, self)
        # array_contains
        lit_e = expr.args[1]
        if not isinstance(lit_e, Constant) or lit_e.value is None:
            raise NotImplementedError("contains value must be a literal")
        needle = lit_e.value
        table = np.asarray(
            [needle in t_ for t_ in tuples] + [False], dtype=np.bool_
        )
        out = jnp.asarray(table)[jnp.clip(d, 0, len(table) - 1)]
        return out, v

    def _map_row_table(self, expr: Call) -> Pair:
        """MAP/ROW functions over pool-coded values: per-code host lookup
        tables gathered on device (the same dictionary-function pattern as
        arrays — reference: MapBlock/RowBlock accessors)."""
        d, v, pool = self._pool_for_arg(expr)
        entries = pool.values
        name = expr.name
        if name == "map_cardinality":
            return self._pool_length_table(entries, d, v)
        if name == "map_element_at":
            key_e = expr.args[1]
            if not isinstance(key_e, Constant) or key_e.value is None:
                raise NotImplementedError("map subscript key must be a literal")
            needle = key_e.value
            vals = []
            for e in entries:
                hit = None
                for k, val in e:
                    if k == needle:
                        hit = val
                        break
                vals.append(hit)
            return _pool_values_pair(expr.type, vals, d, v, self)
        # row_field: 1-based constant index
        i = int(expr.args[1].value)
        vals = [
            (e[i - 1] if 0 < i <= len(e) else None) for e in entries
        ]
        return _pool_values_pair(expr.type, vals, d, v, self)

    def _compare(self, expr: Call) -> Pair:
        a_e, b_e = expr.args
        a_t, b_t = a_e.type, b_e.type
        # string comparisons
        if T.is_string(a_t) or T.is_string(b_t):
            return self._string_compare(expr)
        a, b = self._eval(a_e), self._eval(b_e)
        valid = _all_valid(a, b)
        sa, sb = _dec_scale(a_t), _dec_scale(b_t)
        if _is_wide(a[0]) or _is_wide(b[0]):
            if isinstance(a_t, (T.DoubleType, T.RealType)) or isinstance(
                b_t, (T.DoubleType, T.RealType)
            ):
                # mixed wide-decimal / float: compare in double space
                ad = _wide_to_double(a[0], sa) if _is_wide(a[0]) else a[0]
                bd = _wide_to_double(b[0], sb) if _is_wide(b[0]) else b[0]
                return _cmp_op(expr.name, ad, bd), valid
            from trino_tpu.ops.decimal128 import compare128

            s = max(sa, sb)
            ahi, alo = _as_pair128(a[0], sa, s)
            bhi, blo = _as_pair128(b[0], sb, s)
            sign = compare128(ahi, alo, bhi, blo)
            return _cmp_op(expr.name, sign, jnp.zeros_like(sign)), valid
        if isinstance(a_t, T.DecimalType) or isinstance(b_t, T.DecimalType):
            s = max(sa, sb)
            ad = _rescale(a[0].astype(jnp.int64), sa, s)
            bd = _rescale(b[0].astype(jnp.int64), sb, s)
        else:
            ct = T.common_super_type(a_t, b_t) or a_t
            ad = _cast_numeric(a[0], a_t, ct)
            bd = _cast_numeric(b[0], b_t, ct)
        return _cmp_op(expr.name, ad, bd), valid

    def _string_compare(self, expr: Call) -> Pair:
        a_e, b_e = expr.args
        # Column vs constant: encode constant against the column's dictionary.
        col_e, lit_e, flipped = a_e, b_e, False
        if isinstance(a_e, Constant):
            col_e, lit_e, flipped = b_e, a_e, True
        col = self._eval(col_e)
        dictionary = self._arg_dictionary(col_e)
        if isinstance(lit_e, Constant):
            if dictionary is None:
                raise ValueError("string column without dictionary")
            lit = lit_e.value
            name = expr.name
            if flipped:
                name = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(name, name)
            if name in ("eq", "ne"):
                code = dictionary.encode(lit)
                res = col[0] == code if name == "eq" else col[0] != code
                if code < 0 and name == "eq":
                    res = jnp.zeros_like(res)
                if code < 0 and name == "ne":
                    res = jnp.ones_like(res)
                return res, col[1]
            # ordered compare: precompute per-code truth table on host
            vals = np.asarray(dictionary.values, dtype=object)
            py_op = {"lt": lambda x: x < lit, "le": lambda x: x <= lit,
                     "gt": lambda x: x > lit, "ge": lambda x: x >= lit}[name]
            table = np.asarray([bool(py_op(v)) for v in dictionary.values] + [False],
                               dtype=np.bool_)
            t = jnp.asarray(table)
            return t[jnp.maximum(col[0], 0)] & (col[0] >= 0), col[1]
        # column vs column, same dictionary: compare via rank arrays
        other = self._eval(b_e if not flipped else a_e)
        d2 = self._arg_dictionary(b_e if not flipped else a_e)
        if dictionary is d2 and dictionary is not None:
            if expr.name in ("eq", "ne"):
                res = col[0] == other[0] if expr.name == "eq" else col[0] != other[0]
                return res, col[1] & other[1]
            ranks = jnp.asarray(dictionary.ranks())
            return (
                _cmp_op(expr.name, ranks[jnp.maximum(col[0], 0)], ranks[jnp.maximum(other[0], 0)]),
                col[1] & other[1],
            )
        if dictionary is not None and d2 is not None:
            # cross-dictionary compare: remap A's codes into B's code space
            # (equality) or into a merged rank space (ordered) — host-built
            # tables, one gather each on device
            if expr.name in ("eq", "ne"):
                # null codes (-1) gather the -2 sentinel, which never equals
                # any valid B code or B's -1; validity masks them regardless
                remap = np.asarray(
                    [d2.encode(v) for v in dictionary.values] + [-2],
                    dtype=np.int64,
                )
                idx = jnp.where(col[0] >= 0, col[0], len(dictionary.values))
                a_in_b = jnp.asarray(remap)[idx]
                res = a_in_b == other[0] if expr.name == "eq" else a_in_b != other[0]
                return res, col[1] & other[1]
            # ordered: compare through the merged dictionary's rank space
            md, remap_b = d2.merged(dictionary)
            mranks = np.asarray(md.ranks())
            rb = jnp.asarray(np.append(mranks[: len(d2.values)], 0))
            ra = jnp.asarray(np.append(mranks[remap_b], 0))
            return (
                _cmp_op(
                    expr.name,
                    ra[jnp.maximum(col[0], 0)],
                    rb[jnp.maximum(other[0], 0)],
                ),
                col[1] & other[1],
            )
        raise NotImplementedError("cross-dictionary string comparison (remap first)")

    def _string_table(self, expr: Call) -> Pair:
        """String->numeric scalar via per-dictionary-code lookup table
        (host precomputed, one device gather)."""
        col_e = expr.args[0]
        dictionary = self._arg_dictionary(col_e)
        if dictionary is None:
            raise ValueError(f"{expr.name} on string column without dictionary")
        col = self._eval(col_e)
        name = expr.name
        if name == "length":
            table = np.asarray([len(v) for v in dictionary.values] + [0], dtype=np.int64)
        elif name == "codepoint":
            table = np.asarray(
                [ord(v[0]) if v else 0 for v in dictionary.values] + [0],
                dtype=np.int64,
            )
        elif name == "regexp_like":
            import re as _re

            pat_e = expr.args[1]
            if not isinstance(pat_e, Constant) or pat_e.value is None:
                raise NotImplementedError("regexp pattern must be a literal")
            rx = _re.compile(str(pat_e.value))
            table = np.asarray(
                [rx.search(v) is not None for v in dictionary.values] + [False],
                dtype=np.bool_,
            )
        else:
            lit_e = expr.args[1]
            if not isinstance(lit_e, Constant) or lit_e.value is None:
                raise NotImplementedError(f"{name} argument must be a literal")
            lit = str(lit_e.value)
            if name == "strpos":
                table = np.asarray(
                    [v.find(lit) + 1 for v in dictionary.values] + [0],
                    dtype=np.int64,
                )
            else:  # starts_with
                table = np.asarray(
                    [v.startswith(lit) for v in dictionary.values] + [False],
                    dtype=np.bool_,
                )
        t = jnp.asarray(table)
        out = t[jnp.maximum(col[0], 0)]
        if table.dtype == np.bool_:
            out = out & (col[0] >= 0)
        return out, col[1]

    def _like(self, expr: Call) -> Pair:
        col_e, pat_e = expr.args
        if not isinstance(pat_e, Constant):
            raise NotImplementedError("LIKE pattern must be a literal")
        dictionary = self._arg_dictionary(col_e)
        if dictionary is None:
            raise ValueError("LIKE on string column without dictionary")
        col = self._eval(col_e)
        regex = _like_to_regex(pat_e.value)
        table = np.asarray(
            [regex.fullmatch(v) is not None for v in dictionary.values] + [False],
            dtype=np.bool_,
        )
        t = jnp.asarray(table)
        return t[jnp.maximum(col[0], 0)] & (col[0] >= 0), col[1]

    def _cast(self, expr: Call) -> Pair:
        src = expr.args[0]
        d, v = self._eval(src)
        st, rt = src.type, expr.type
        if st == rt:
            return d, v
        if _is_wide(d):
            if isinstance(rt, (T.DoubleType, T.RealType)) and isinstance(
                st, T.DecimalType
            ):
                return _wide_to_double(d, st.scale).astype(rt.storage_dtype), v
            if (
                isinstance(rt, T.DecimalType)
                and isinstance(st, T.DecimalType)
                and rt.wide
                and rt.scale >= st.scale
            ):
                # wide -> wide upscale stays in (hi, lo) lanes
                hi, lo = _as_pair128(d, st.scale, rt.scale)
                return jnp.stack([hi, lo], axis=1), v
            if (
                isinstance(rt, T.DecimalType)
                and isinstance(st, T.DecimalType)
                and st.scale - rt.scale <= 18
            ):
                # wide -> narrow: HALF_UP rescale via exact long division
                # (traceable). Values that genuinely exceed the target
                # become NULL (the eager reference path raises instead;
                # overflow inputs are errors either way)
                from trino_tpu.ops import decimal128 as D128

                hi, lo = d[:, 0], d[:, 1]
                shift = rt.scale - st.scale
                if shift >= 0:
                    hi, lo = D128.rescale_up_wide(hi, lo, shift)
                else:
                    dhi, dlo = D128.widen_i64(
                        jnp.full_like(lo, 10 ** (-shift))
                    )
                    hi, lo, _ok = D128.div128_round(hi, lo, dhi, dlo, 0)
                fits = hi == (lo >> jnp.int64(63))  # sign-extension check
                if rt.wide:
                    return jnp.stack([hi, lo], axis=1), v
                return lo, v & fits
            # other casts narrow at runtime (exact when values fit int64)
            d = _narrow_checked(d, f"cast {st} -> {rt}")
        if isinstance(rt, T.DecimalType):
            if isinstance(st, T.DecimalType):
                if rt.wide and rt.scale >= st.scale:
                    hi, lo = _as_pair128(d, st.scale, rt.scale)
                    return jnp.stack([hi, lo], axis=1), v
                return _rescale(d.astype(jnp.int64), st.scale, rt.scale), v
            if T.is_integer(st):
                return d.astype(jnp.int64) * rt.unscale, v
            if isinstance(st, (T.DoubleType, T.RealType)):
                scaled = d.astype(jnp.float64) * rt.unscale
                return _round_half_up(scaled).astype(jnp.int64), v
        if isinstance(rt, (T.DoubleType, T.RealType)):
            if isinstance(st, T.DecimalType):
                return (d.astype(jnp.float64) / st.unscale).astype(rt.storage_dtype), v
            return d.astype(rt.storage_dtype), v
        if T.is_integer(rt):
            if isinstance(st, T.DecimalType):
                return _rescale(d.astype(jnp.int64), st.scale, 0).astype(rt.storage_dtype), v
            if isinstance(st, (T.DoubleType, T.RealType)):
                return _round_half_up(d).astype(rt.storage_dtype), v
            return d.astype(rt.storage_dtype), v
        if isinstance(rt, T.TimestampType) and isinstance(st, T.DateType):
            return d.astype(jnp.int64) * 86_400_000_000, v
        if isinstance(rt, T.DateType) and isinstance(st, T.TimestampType):
            return (d // 86_400_000_000).astype(jnp.int32), v
        raise NotImplementedError(f"cast {st} -> {rt}")

    def _extract(self, expr: Call) -> Pair:
        d, v = self._eval(expr.args[0])
        st = expr.args[0].type
        if isinstance(st, T.TimestampType):
            days = (d // 86_400_000_000).astype(jnp.int32)
        else:
            days = d.astype(jnp.int32)
        y, m, dd = _civil_from_days(days)
        out = {"year": y, "month": m, "day": dd}[expr.name]
        return out.astype(jnp.int64), v

    def _round(self, expr: Call) -> Pair:
        d, v = self._eval(expr.args[0])
        st = expr.args[0].type
        nd = 0
        if len(expr.args) > 1:
            assert isinstance(expr.args[1], Constant)
            nd = int(expr.args[1].value)
        if isinstance(st, T.DecimalType):
            if nd >= st.scale:
                return d, v
            scaled = _rescale(d.astype(jnp.int64), st.scale, nd)
            return _rescale(scaled, nd, st.scale), v
        if nd == 0:
            return _round_half_up(d), v
        f = 10.0**nd
        return _round_half_up(d * f) / f, v


def _cmp_op(name: str, a, b):
    return {
        "eq": lambda: a == b,
        "ne": lambda: a != b,
        "lt": lambda: a < b,
        "le": lambda: a <= b,
        "gt": lambda: a > b,
        "ge": lambda: a >= b,
    }[name]()


def _cast_numeric(data, from_t: T.SqlType, to_t: T.SqlType):
    if from_t == to_t:
        return data
    if isinstance(from_t, T.DecimalType):
        if isinstance(to_t, (T.DoubleType, T.RealType)):
            if _is_wide(data):
                return _wide_to_double(data, from_t.scale).astype(
                    to_t.storage_dtype
                )
            return (data.astype(jnp.float64) / from_t.unscale).astype(to_t.storage_dtype)
        return data  # decimal handled by caller
    if isinstance(from_t, T.DateType) and isinstance(to_t, T.TimestampType):
        return data.astype(jnp.int64) * 86_400_000_000
    return data.astype(to_t.storage_dtype)


def _round_half_up(data):
    """Trino rounds doubles half away from zero; jnp.round is half-to-even."""
    return jnp.sign(data) * jnp.floor(jnp.abs(data) + 0.5)


def _kleene_and(a: Pair, b: Pair) -> Pair:
    av = jnp.where(a[1], a[0], True)
    bv = jnp.where(b[1], b[0], True)
    value = av & bv
    valid = (a[1] & b[1]) | (a[1] & ~a[0]) | (b[1] & ~b[0])
    return value, valid


def _kleene_or(a: Pair, b: Pair) -> Pair:
    av = jnp.where(a[1], a[0], False)
    bv = jnp.where(b[1], b[0], False)
    value = av | bv
    valid = (a[1] & b[1]) | (a[1] & a[0]) | (b[1] & b[0])
    return value, valid


# unary double-valued math kernels (analyzer coerces args to DOUBLE)
_UNARY_MATH = {
    "ln": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "exp": jnp.exp,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "cbrt": jnp.cbrt,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
}


def _civil_from_days(days: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day). Hinnant's algorithm,
    all int32 ops (vectorizes cleanly on TPU)."""
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _is_leap(y: jnp.ndarray) -> jnp.ndarray:
    return ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)


def _days_in_month_vec(y: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    lengths = jnp.asarray([31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], dtype=jnp.int32)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    base = lengths[m - 1]
    return jnp.where((m == 2) & leap, 29, base)


def _days_from_civil_vec(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """Vectorized inverse of _civil_from_days (Hinnant)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + jnp.where(m > 2, -3, 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def days_from_civil(y: int, m: int, d: int) -> int:
    """Host-side inverse (for date literals)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)
