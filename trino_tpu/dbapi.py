"""DB-API 2.0 (PEP 249) driver over the statement protocol.

Reference tier: ``client/trino-jdbc/.../TrinoConnection.java`` /
``TrinoResultSet.java`` — the standard-interface driver wrapped around the
protocol client (our :mod:`trino_tpu.client`). JDBC's java.sql surface maps
to Python's DB-API: Connection/Cursor, ``description``, ``rowcount``,
``fetch*``, qmark parameter binding, and the standard exception hierarchy.
"""

from __future__ import annotations

import datetime
from decimal import Decimal
from typing import Any, Iterator, Optional, Sequence

from trino_tpu.client import ClientSession, QueryFailure, StatementClient

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


# --- PEP 249 exception hierarchy -------------------------------------------


class Warning(Exception):  # noqa: A001 (PEP 249 name)
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


# --- type singletons (PEP 249 §Type Objects) --------------------------------


class _DBAPIType:
    def __init__(self, *names: str):
        self.names = frozenset(names)

    def __eq__(self, other):  # type: ignore[override]
        base = str(other).split("(")[0].lower()
        return base in self.names

    def __hash__(self):
        return hash(self.names)


STRING = _DBAPIType("varchar", "char", "json")
BINARY = _DBAPIType("varbinary")
NUMBER = _DBAPIType(
    "tinyint", "smallint", "integer", "bigint", "real", "double", "decimal"
)
DATETIME = _DBAPIType("date", "time", "timestamp")
ROWID = _DBAPIType()

Date = datetime.date
Time = datetime.time
Timestamp = datetime.datetime
Binary = bytes


def DateFromTicks(ticks: float) -> datetime.date:
    return datetime.date.fromtimestamp(ticks)


def TimeFromTicks(ticks: float) -> datetime.time:
    return datetime.datetime.fromtimestamp(ticks).time()


def TimestampFromTicks(ticks: float) -> datetime.datetime:
    return datetime.datetime.fromtimestamp(ticks)


# --- literal binding (qmark) -------------------------------------------------


def _quote_literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, Decimal):
        return f"DECIMAL '{v}'"
    if isinstance(v, datetime.datetime):
        return f"TIMESTAMP '{v.strftime('%Y-%m-%d %H:%M:%S.%f')[:-3]}'"
    if isinstance(v, datetime.date):
        return f"DATE '{v.isoformat()}'"
    if isinstance(v, datetime.time):
        return f"TIME '{v.isoformat()}'"
    if isinstance(v, (bytes, bytearray)):
        return "X'" + v.hex() + "'"
    if isinstance(v, str):
        return "'" + v.replace("'", "''") + "'"
    raise ProgrammingError(f"cannot bind parameter of type {type(v).__name__}")


def _bind(sql: str, params: Optional[Sequence[Any]]) -> str:
    """Substitute ``?`` placeholders outside string literals/comments."""
    if not params:
        return sql
    out = []
    it = iter(params)
    i, n = 0, len(sql)
    used = 0
    while i < n:
        ch = sql[i]
        if ch == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    j += 2
                    continue
                if sql[j] == "'":
                    break
                j += 1
            out.append(sql[i : j + 1])
            i = j + 1
        elif ch == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            j = n if j < 0 else j
            out.append(sql[i:j])
            i = j
        elif ch == "?":
            try:
                out.append(_quote_literal(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters for placeholders")
            used += 1
            i += 1
        else:
            out.append(ch)
            i += 1
    if used != len(params):
        raise ProgrammingError(
            f"statement has {used} placeholders but {len(params)} parameters given"
        )
    return "".join(out)


# --- Cursor / Connection -----------------------------------------------------


class Cursor:
    arraysize = 1

    def __init__(self, connection: "Connection"):
        self.connection = connection
        self.description: Optional[list[tuple]] = None
        self.rowcount = -1
        self._rows: Optional[Iterator[tuple]] = None
        self._client: Optional[StatementClient] = None
        self._closed = False

    # -- execution --

    def execute(self, operation: str, parameters: Optional[Sequence[Any]] = None):
        self._check_open()
        sql = _bind(operation, parameters)
        client = StatementClient(
            self.connection._base_uri, sql, self.connection._session
        )
        self._client = client
        try:
            rows_iter = client.rows()
            first = next(rows_iter, _SENTINEL)
        except QueryFailure as e:
            raise _map_failure(e) from e
        except OSError as e:
            raise OperationalError(str(e)) from e
        self.description = (
            [
                (c.name, c.type, None, None, None, None, None)
                for c in client.columns
            ]
            if client.columns
            else None
        )
        if client.update_count is not None:
            self.rowcount = client.update_count
            self._rows = iter(())
        else:
            self.rowcount = -1
            self._rows = (
                iter(()) if first is _SENTINEL else _chain_first(first, rows_iter)
            )
        return self

    def executemany(self, operation: str, seq_of_parameters: Sequence[Sequence[Any]]):
        total = 0
        for params in seq_of_parameters:
            self.execute(operation, params)
            if self.rowcount > 0:
                total += self.rowcount
        self.rowcount = total
        return self

    # -- fetch --

    def fetchone(self) -> Optional[tuple]:
        self._check_results()
        try:
            return next(self._rows)  # type: ignore[arg-type]
        except StopIteration:
            return None
        except QueryFailure as e:
            raise _map_failure(e) from e

    def fetchmany(self, size: Optional[int] = None) -> list[tuple]:
        size = size or self.arraysize
        out = []
        for _ in range(size):
            row = self.fetchone()
            if row is None:
                break
            out.append(row)
        return out

    def fetchall(self) -> list[tuple]:
        self._check_results()
        try:
            return list(self._rows)  # type: ignore[arg-type]
        except QueryFailure as e:
            raise _map_failure(e) from e

    def __iter__(self):
        self._check_results()
        return self._rows

    # -- misc --

    def setinputsizes(self, sizes):
        pass

    def setoutputsize(self, size, column=None):
        pass

    def cancel(self):
        if self._client is not None:
            self._client.cancel()

    def close(self):
        self.cancel()
        self._closed = True
        self._rows = None

    def _check_open(self):
        if self._closed or self.connection._closed:
            raise InterfaceError("cursor is closed")

    def _check_results(self):
        self._check_open()
        if self._rows is None:
            raise ProgrammingError("no query has been executed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_SENTINEL = object()


def _chain_first(first: tuple, rest: Iterator[tuple]) -> Iterator[tuple]:
    yield first
    yield from rest


def _map_failure(e: QueryFailure) -> DatabaseError:
    name = (e.error or {}).get("errorName", "")
    if "SYNTAX" in name or "COLUMN_NOT_FOUND" in name or "SEMANTIC" in name:
        return ProgrammingError(str(e))
    return OperationalError(str(e))


class Connection:
    def __init__(
        self,
        host: str = "localhost",
        port: int = 8080,
        user: str = "user",
        catalog: Optional[str] = "tpch",
        schema: Optional[str] = "tiny",
        session_properties: Optional[dict] = None,
        base_uri: Optional[str] = None,
    ):
        self._base_uri = base_uri or f"http://{host}:{port}"
        self._session = ClientSession(
            user=user,
            catalog=catalog,
            schema=schema,
            properties=dict(session_properties or {}),
        )
        self._closed = False

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def _run(self, sql: str) -> None:
        cur = self.cursor()
        cur.execute(sql)
        cur.fetchall()

    def commit(self) -> None:
        # autocommit unless an explicit transaction was started via
        # cursor.execute("START TRANSACTION") — COMMIT then rides the
        # X-Trino-Transaction-Id header kept in the shared ClientSession
        if self._session.transaction_id:
            self._run("COMMIT")

    def rollback(self) -> None:
        if self._session.transaction_id:
            self._run("ROLLBACK")

    def close(self) -> None:
        if not self._closed and self._session.transaction_id:
            try:
                self._run("ROLLBACK")
            except Exception:  # noqa: BLE001
                pass
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            try:
                self.commit()
            finally:
                self.close()
        else:
            self.close()


def connect(*args, **kwargs) -> Connection:
    return Connection(*args, **kwargs)
