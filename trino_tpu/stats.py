"""Execution statistics: per-node stats chain + EXPLAIN ANALYZE rendering.

Reference: ``operator/OperatorStats.java`` rolled up through
Driver→Pipeline→Task→Stage→Query (``operator/DriverContext.java``,
``execution/QueryStats.java``), surfaced by ``ExplainAnalyzeOperator.java:34``
via ``sql/planner/planprinter/PlanPrinter.java:148``.

Our executor materializes one plan node at a time, so stats attach per
plan node (the reference's per-operator granularity at our altitude).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from trino_tpu.planner import plan as P


@dataclasses.dataclass
class NodeStats:
    """One plan node's execution record (OperatorStats analog)."""

    node_type: str
    wall_seconds: float = 0.0
    output_rows: int = 0
    output_bytes: int = 0
    detail: str = ""


class StatsCollector:
    """Keyed by plan-node identity; nodes kept alive by the plan itself."""

    def __init__(self):
        self.by_node: dict[int, NodeStats] = {}
        self._inclusive: dict[int, float] = {}
        self._keep: list = []  # retain node refs so id() stays valid
        # fused execution reports per-FRAGMENT stats (one compiled program
        # per fragment has no per-operator boundaries to time)
        self.fragments: list[dict] = []

    def record_fragment(self, fragment_id, info: dict) -> None:
        self.fragments.append({"fragment": fragment_id, **info})

    def record(self, node, wall: float, rows: int, bytes_: int, detail: str = ""):
        """``wall`` is inclusive of children (the executor times the whole
        subtree); stored per-node time is exclusive — children's inclusive
        times are subtracted so nothing double-counts."""
        self._keep.append(node)
        self._inclusive[id(node)] = wall
        children = sum(self._inclusive.get(id(s), 0.0) for s in node.sources)
        self.by_node[id(node)] = NodeStats(
            type(node).__name__, max(0.0, wall - children), rows, bytes_, detail
        )

    def total_wall(self) -> float:
        if self.by_node:
            return sum(s.wall_seconds for s in self.by_node.values())
        return sum(f.get("wall_s", 0.0) for f in self.fragments)


def render_fragment_stats(fragments: list[dict]) -> str:
    """EXPLAIN ANALYZE section for fused execution: one compiled program
    per fragment (ref ExplainAnalyzeOperator.java:34 — here the unit of
    profiling matches the unit of compilation)."""
    lines = ["Fragments (fused single-program execution):"]
    for f in fragments:
        parts = [
            f"  fragment {f['fragment']}: mode={f.get('mode', 'fused')}",
            f"wall={f.get('wall_s', 0.0) * 1000:.1f}ms",
        ]
        # only report what was actually measured (streamed fragments have
        # no single compile attempt count or static input size)
        if "attempts" in f:
            parts.append(f"compile_attempts={f['attempts']}")
        if "input_rows" in f:
            parts.append(f"input_rows={f['input_rows']:,}")
        parts.append(f"output_rows={f.get('output_rows', 0):,}")
        lines.append(" ".join(parts))
    return "\n".join(lines)


def render_plan_with_stats(
    node: P.PlanNode, collector: Optional[StatsCollector], indent: int = 0
) -> str:
    """PlanPrinter.textDistributedPlan-with-stats analog: the logical plan
    annotated with wall time / rows / bytes per node."""
    pad = "  " * indent
    line = f"{pad}{P.node_label(node)}"
    if collector is not None:
        st = collector.by_node.get(id(node))
        if st is not None:
            line += (
                f"   [wall: {st.wall_seconds * 1000:.1f}ms, "
                f"rows: {st.output_rows:,}, bytes: {st.output_bytes:,}]"
            )
    out = [line]
    for s in node.sources:
        out.append(render_plan_with_stats(s, collector, indent + 1))
    return "\n".join(out)
