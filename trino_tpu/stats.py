"""Execution statistics: per-node stats chain + EXPLAIN ANALYZE rendering.

Reference: ``operator/OperatorStats.java`` rolled up through
Driver→Pipeline→Task→Stage→Query (``operator/DriverContext.java``,
``execution/QueryStats.java``), surfaced by ``ExplainAnalyzeOperator.java:34``
via ``sql/planner/planprinter/PlanPrinter.java:148``.

Our executor materializes one plan node at a time, so stats attach per
plan node (the reference's per-operator granularity at our altitude).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from trino_tpu.planner import plan as P


@dataclasses.dataclass
class NodeStats:
    """One plan node's execution record (OperatorStats analog)."""

    node_type: str
    wall_seconds: float = 0.0
    output_rows: int = 0
    output_bytes: int = 0
    detail: str = ""


class StatsCollector:
    """Keyed by plan-node identity; nodes kept alive by the plan itself."""

    def __init__(self):
        self.by_node: dict[int, NodeStats] = {}
        self._inclusive: dict[int, float] = {}
        self._keep: list = []  # retain node refs so id() stays valid
        # fused execution reports per-FRAGMENT stats (one compiled program
        # per fragment has no per-operator boundaries to time)
        self.fragments: list[dict] = []

    def record_fragment(self, fragment_id, info: dict) -> None:
        self.fragments.append({"fragment": fragment_id, **info})

    def record(self, node, wall: float, rows: int, bytes_: int, detail: str = ""):
        """``wall`` is inclusive of children (the executor times the whole
        subtree); stored per-node time is exclusive — children's inclusive
        times are subtracted so nothing double-counts."""
        self._keep.append(node)
        self._inclusive[id(node)] = wall
        children = sum(self._inclusive.get(id(s), 0.0) for s in node.sources)
        self.by_node[id(node)] = NodeStats(
            type(node).__name__, max(0.0, wall - children), rows, bytes_, detail
        )

    def total_wall(self) -> float:
        if self.by_node:
            return sum(s.wall_seconds for s in self.by_node.values())
        return sum(f.get("wall_s", 0.0) for f in self.fragments)


def render_fragment_stats(fragments: list[dict]) -> str:
    """EXPLAIN ANALYZE section for fused execution: one compiled program
    per fragment (ref ExplainAnalyzeOperator.java:34 — here the unit of
    profiling matches the unit of compilation)."""
    lines = ["Fragments (fused single-program execution):"]
    for f in fragments:
        parts = [
            f"  fragment {f['fragment']}: mode={f.get('mode', 'fused')}",
            f"wall={f.get('wall_s', 0.0) * 1000:.1f}ms",
        ]
        # only report what was actually measured (streamed fragments have
        # no single compile attempt count or static input size)
        if "attempts" in f:
            parts.append(f"compile_attempts={f['attempts']}")
        if "input_rows" in f:
            parts.append(f"input_rows={f['input_rows']:,}")
        parts.append(f"output_rows={f.get('output_rows', 0):,}")
        lines.append(" ".join(parts))
    return "\n".join(lines)


def render_device_stats(device_stats: dict) -> str:
    """EXPLAIN ANALYZE section for the device profiler: per-program XLA
    cost/memory analysis (obs/profiler.py) plus the query rollup. Every
    field is backend-dependent and rendered only when captured."""
    lines = ["Device programs (XLA cost/memory analysis):"]
    for label, st in sorted((device_stats.get("programs") or {}).items()):
        parts = [f"  {label}:"]
        if "flops" in st:
            parts.append(f"flops={st['flops']:.4g}")
        if "bytes_accessed" in st:
            parts.append(f"bytes_accessed={int(st['bytes_accessed']):,}")
        if "peak_hbm_bytes" in st:
            parts.append(f"peak_hbm={int(st['peak_hbm_bytes']):,}B")
        if st.get("compile_ms"):
            parts.append(f"compile={st['compile_ms']:.1f}ms")
        parts.append(f"executions={st.get('executions', 0)}")
        lines.append(" ".join(parts))
    totals = []
    if device_stats.get("total_flops") is not None:
        totals.append(f"total_flops={device_stats['total_flops']:.4g}")
    if device_stats.get("peak_hbm_bytes") is not None:
        totals.append(f"peak_hbm={int(device_stats['peak_hbm_bytes']):,}B")
    if totals:
        lines.append("  query: " + " ".join(totals))
    return "\n".join(lines)


def render_capacity_stats(capacities: dict) -> str:
    """EXPLAIN ANALYZE section for capacity sites: final value +
    provenance per site, with the estimated-vs-observed drift summary.
    A ``+grown``/``+halved`` suffix marks exactly where the estimate
    (default or seeded) missed and the retry ladder had to correct it;
    ``history``-provenance sites started from observed truth
    (obs/history.py) and should show no suffix on warm repeats."""
    lines = ["Capacity sites (final value, provenance):"]
    grown = halved = history = 0
    for name, ent in sorted(
        capacities.items(), key=lambda kv: str(kv[1].get("site", kv[0]))
    ):
        prov = str(ent.get("provenance", "default"))
        lines.append(f"  {ent.get('site', name)}: {ent.get('value')} ({prov})")
        if "+grown" in prov:
            grown += 1
        if "+halved" in prov:
            halved += 1
        if prov.startswith("history"):
            history += 1
    lines.append(
        f"  estimated vs observed: {grown} grown, {halved} halved, "
        f"{history} history-seeded of {len(capacities)} sites"
    )
    return "\n".join(lines)


def _operator_rows(operators: dict, indent: str = "  ") -> list[str]:
    """One line per operator site: kind, rows in/out, selectivity
    (rows_out / rows_in; broadcast exchanges exceed 1.0 by design)."""
    lines = []
    for site, ent in sorted(operators.items()):
        if not isinstance(ent, dict):
            continue
        rin = int(ent.get("rows_in", 0) or 0)
        rout = int(ent.get("rows_out", 0) or 0)
        sel = f"{rout / rin:.4f}" if rin > 0 else "-"
        lines.append(
            f"{indent}{site}: {ent.get('kind', '?')} "
            f"rows_in={rin:,} rows_out={rout:,} selectivity={sel}"
        )
    return lines


def render_operator_stats(operators: dict) -> str:
    """EXPLAIN ANALYZE section for in-program operator telemetry
    (exec/fragments.py ``op!`` counter channel): per-site row flow keyed
    by restart-stable names. Partial-agg selectivity here IS the
    per-exchange reduction ratio the mid-query-adaptivity roadmap item
    consumes from history."""
    lines = ["Operators (in-program row flow, by stable site):"]
    lines.extend(_operator_rows(operators))
    ratios = [
        int(e.get("rows_out", 0) or 0) / max(1, int(e.get("rows_in", 0) or 0))
        for e in operators.values()
        if isinstance(e, dict)
        and e.get("kind") == "partial-agg"
        and int(e.get("rows_in", 0) or 0) > 0
    ]
    if ratios:
        lines.append(
            f"  worst partial-agg reduction ratio: {max(ratios):.4f}"
        )
    return "\n".join(lines)


def render_distributed_plan(
    node: P.PlanNode,
    cluster_stats: dict,
    device_stats: Optional[dict] = None,
) -> str:
    """Trino-style distributed EXPLAIN ANALYZE
    (``PlanPrinter.textDistributedPlan`` analog): the logical plan
    followed by one section per stage, annotated with task counts, rows,
    wall, exchange bytes / padding ratio, and per-stage FLOPs / peak HBM —
    all merged by the coordinator from every worker's shipped task stats
    (``server/cluster.py::_finalize_query``)."""
    lines = ["Distributed plan:", render_plan_with_stats(node, None, 1), ""]
    lines.append("Stages (stats merged from worker tasks):")
    for st in cluster_stats.get("stages") or []:
        lines.append(
            f"Stage {st.get('stage')} "
            f"[tasks: {st.get('tasks', 0)}, attempts: {st.get('attempts', 0)},"
            f" wall: {st.get('elapsedMs', 0.0):.1f}ms]"
        )
        parts = []
        if st.get("rows") is not None:
            parts.append(f"output rows: {st['rows']:,}")
        if st.get("inputRows") is not None:
            parts.append(f"input rows: {st['inputRows']:,}")
        if st.get("outputBytes") is not None:
            parts.append(f"output bytes: {st['outputBytes']:,}")
        if parts:
            lines.append("    " + "  ".join(parts))
        te = st.get("taskElapsedMs")
        if te:
            lines.append(
                f"    task wall p50/p99/max: {te['p50']:.1f}/"
                f"{te['p99']:.1f}/{te['max']:.1f} ms"
            )
        ex = st.get("exchange") or {}
        exparts = [
            f"{k}={ex[k]}"
            for k in (
                "shuffle_rows", "shuffle_bytes", "padding_ratio",
                "hot_keys", "salted_rows", "overflow_retries",
            )
            if ex.get(k)
        ]
        if exparts:
            lines.append("    exchange: " + " ".join(exparts))
        stage_caps = ex.get("capacities")
        if isinstance(stage_caps, dict) and stage_caps:
            cparts = []
            for name, ent in sorted(
                stage_caps.items(),
                key=lambda kv: str(kv[1].get("site", kv[0])),
            ):
                if isinstance(ent, dict):
                    cparts.append(
                        f"{ent.get('site', name)}="
                        f"{ent.get('value')}({ent.get('provenance', '?')})"
                    )
            if cparts:
                lines.append("    capacities: " + " ".join(cparts))
        stage_ops = ex.get("operators")
        if isinstance(stage_ops, dict) and stage_ops:
            lines.append("    operators:")
            lines.extend(_operator_rows(stage_ops, indent="      "))
        dparts = []
        if st.get("flops") is not None:
            dparts.append(f"flops={st['flops']:.4g}")
        if st.get("peakHbmBytes") is not None:
            dparts.append(f"peak_hbm={int(st['peakHbmBytes']):,}B")
        if st.get("compileMs"):
            dparts.append(f"compile={st['compileMs']:.1f}ms")
        if dparts:
            lines.append("    device: " + " ".join(dparts))
    counters = []
    for key, lab in (
        ("task_retries", "task retries"),
        ("speculative_attempts", "speculative attempts"),
        ("speculative_wins", "speculative wins"),
    ):
        if cluster_stats.get(key):
            counters.append(f"{lab}: {cluster_stats[key]}")
    if counters:
        lines.append("    " + "  ".join(counters))
    if device_stats:
        lines.extend(["", render_device_stats(device_stats)])
    return "\n".join(lines)


def render_plan_with_stats(
    node: P.PlanNode, collector: Optional[StatsCollector], indent: int = 0
) -> str:
    """PlanPrinter.textDistributedPlan-with-stats analog: the logical plan
    annotated with wall time / rows / bytes per node."""
    pad = "  " * indent
    line = f"{pad}{P.node_label(node)}"
    if collector is not None:
        st = collector.by_node.get(id(node))
        if st is not None:
            line += (
                f"   [wall: {st.wall_seconds * 1000:.1f}ms, "
                f"rows: {st.output_rows:,}, bytes: {st.output_bytes:,}]"
            )
    out = [line]
    for s in node.sources:
        out.append(render_plan_with_stats(s, collector, indent + 1))
    return "\n".join(out)
