"""Coordinator-level caches (result tier of the repeat-path stack)."""

from trino_tpu.cache.result_cache import ResultCache

__all__ = ["ResultCache"]
