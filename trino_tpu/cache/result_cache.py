"""Semantic result cache + incremental aggregate maintenance.

The fourth tier of the repeat-path stack: PR 4 dedups compilation
(canonical-plan fingerprints), PR 12 dedups device data
(DeviceTableCache), PR 15 dedups tuning (query history) — this module
dedups the RESULT. Final result sets are keyed by ``(canonical
fingerprint, hoisted-param vector)`` and validated against per-catalog
data versions plus the access-control generation, under a byte-budget
LRU. A warm repeat returns host-resident rows in microseconds with zero
device dispatches.

Reference: Trino's fault-tolerant-execution result cache keys on plan
signature + table versions; the closest upstream analog is
``io.trino.cache`` (subsumed subplans against versioned connectors).

Incremental aggregate maintenance (the PAPERS.md "Partial Partial
Aggregates" idea applied across queries instead of across exchange
sites): when a cached entry's only staleness is an APPEND — detected via
part-level :meth:`Connector.data_versions`, where every old
``(part_id, token)`` pair survived and new ids arrived — and the plan is
aggregation-rooted with exactly-mergeable aggregates
(:func:`trino_tpu.planner.canonicalize.classify_maintainability`), the
cached plan is re-executed over ONLY the appended parts through a
:class:`DeltaPartsConnector` and the fresh partial-aggregate rows are
merged into the cached rows host-side. Everything else invalidates.

Concurrency discipline (lint/lockdep-clean by construction):

- ``_lock`` guards the entry map/byte budget and is only ever held for
  dict operations — never across connector IO, planning, or execution.
- maintenance serializes per entry under a separate mutex acquired
  WITHOUT ``_lock`` held (lock order is strictly maintenance -> cache);
  it runs on the caller's thread, which for the server is always a
  dispatch-pool worker (the QueryManager admission fast path probes with
  ``allow_maintenance=False``), never the event loop.
- entries are immutable; maintenance publishes a replacement atomically,
  so concurrent readers always observe a consistent snapshot — either
  the pre-append rows or the fully merged rows, never a half-merge.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Any, Optional

from trino_tpu.planner import plan as P


def session_signature(session) -> tuple:
    """The session facets that change what a SQL text means or traces
    into: name resolution context plus every codegen-relevant property
    (same list the plan fingerprint folds in, so the SQL-text memo can
    never alias two sessions onto one fingerprint)."""
    from trino_tpu.planner.canonicalize import _CODEGEN_PROPS

    props = []
    for name in _CODEGEN_PROPS + ("constant_hoisting", "program_cache"):
        try:
            props.append((name, repr(session.get(name))))
        except KeyError:
            continue
    return (session.catalog, session.schema, tuple(props))


def referenced_tables(root: P.PlanNode) -> list[tuple[str, str, str]]:
    """Every (catalog, schema, table) scanned by this plan, sorted."""
    out: list[tuple[str, str, str]] = []
    seen: set[tuple[str, str, str]] = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, P.TableScan):
            key = (node.catalog, node.schema, node.table)
            if key not in seen:
                seen.add(key)
                out.append(key)
        stack.extend(node.sources)
    return sorted(out)


def versions_snapshot(catalogs, tables) -> tuple:
    """Per-table ``(catalog, schema, table, coarse_token, parts|None)``.
    ``parts`` is the connector's part-level data_versions() enumeration
    when it has one (enables append detection); the coarse data_version()
    token otherwise (any change then invalidates)."""
    out = []
    for cat, schema, table in tables:
        conn = catalogs.get(cat)
        coarse = conn.data_version(schema, table)
        parts = conn.data_versions(schema, table)
        out.append(
            (cat, schema, table, coarse, None if parts is None else tuple(parts))
        )
    return tuple(out)


def _estimate_bytes(rows) -> int:
    """Deterministic host-memory estimate of a result set (drives the
    byte-budget LRU; CPython sizeof-ish constants, exactness irrelevant)."""
    n = 64
    for row in rows:
        n += 56
        for v in row:
            if v is None:
                n += 8
            elif isinstance(v, str):
                n += 49 + len(v)
            elif isinstance(v, (bytes, bytearray)):
                n += 33 + len(v)
            else:
                n += 32
    return n


def merge_aggregate_rows(cached_rows, delta_rows, cols) -> tuple:
    """Merge delta partial-aggregate rows into cached final rows.

    ``cols`` is the per-output-column kind vector from
    ``classify_maintainability``: ``key`` columns identify the group,
    ``sum``/``count``/``min``/``max`` merge by exact row-wise combine
    (None is the sum/min/max identity; count never yields None). Cached
    group order is preserved; new groups append in delta order — row
    order of a GROUP BY without ORDER BY is unspecified, and the cached
    entry serves one stable order.
    """
    key_idx = tuple(i for i, k in enumerate(cols) if k == "key")
    merged: "OrderedDict[tuple, list]" = OrderedDict()
    for row in cached_rows:
        merged[tuple(row[i] for i in key_idx)] = list(row)
    for row in delta_rows:
        k = tuple(row[i] for i in key_idx)
        cur = merged.get(k)
        if cur is None:
            merged[k] = list(row)
            continue
        for i, kind in enumerate(cols):
            if kind == "key":
                continue
            cur[i] = _merge_value(kind, cur[i], row[i])
    return tuple(tuple(r) for r in merged.values())


def _merge_value(kind: str, a, b):
    if kind == "count":
        return (a or 0) + (b or 0)
    if a is None:
        return b
    if b is None:
        return a
    if kind == "sum":
        return a + b
    if kind == "min":
        return a if a <= b else b
    if kind == "max":
        return a if a >= b else b
    raise ValueError(f"unmergeable aggregate kind: {kind}")


class DeltaPartsConnector:
    """Read-only view of one table restricted to named parts — the scan
    source for incremental maintenance.

    Explicit delegation only: inheriting (or ``__getattr__``-forwarding)
    the inner connector would leak full-table shortcuts — ``device_slab``
    staging, ``apply_aggregation_count``, limit pushdown — that silently
    read rows outside the delta. Every pushdown hook answers "no" so the
    executor actually scans exactly the delta splits."""

    supports_result_caching = False
    supports_distributed_writes = False

    def __init__(self, inner, schema: str, table: str, part_ids):
        self._inner = inner
        self._schema = schema
        self._table = table
        self._part_ids = list(part_ids)
        self.name = getattr(inner, "name", "connector")

    # --- metadata (pass-through) -----------------------------------------
    def list_schemas(self):
        return self._inner.list_schemas()

    def list_tables(self, schema):
        return self._inner.list_tables(schema)

    def get_table(self, schema, table):
        return self._inner.get_table(schema, table)

    # --- splits: the delta ------------------------------------------------
    def get_splits(self, schema, table, target_splits, constraint=None):
        if (schema, table) != (self._schema, self._table):
            return self._inner.get_splits(schema, table, target_splits, constraint)
        splits = self._inner.splits_for_parts(schema, table, self._part_ids)
        return self._inner.prune_splits(schema, table, splits, constraint)

    def get_splits_with_hints(
        self, schema, table, target_splits, constraint=None, limit=None, topn=None
    ):
        return self.get_splits(schema, table, target_splits, constraint)

    def prune_splits(self, schema, table, splits, constraint):
        return self._inner.prune_splits(schema, table, splits, constraint)

    def split_stats(self, schema, table, split):
        return self._inner.split_stats(schema, table, split)

    def read_split(self, schema, table, columns, split):
        return self._inner.read_split(schema, table, columns, split)

    def data_version(self, schema, table):
        return self._inner.data_version(schema, table)

    def data_versions(self, schema, table):
        return self._inner.data_versions(schema, table)

    # --- pushdowns: all declined (stats describe the FULL table) ----------
    def apply_limit(self, schema, table, count):
        return False

    def apply_topn(self, schema, table, keys, count):
        return False

    def apply_aggregation_count(self, schema, table):
        return None

    def estimate_rows(self, schema, table):
        return None

    def table_stats(self, schema, table):
        return None


@dataclasses.dataclass(frozen=True)
class ResultCacheEntry:
    """One cached result set. Immutable: maintenance builds a replacement
    and publishes it atomically under the cache lock."""

    fingerprint: str
    params_key: tuple
    sql: str
    rows: tuple  # tuple of row tuples (host-resident final values)
    column_names: tuple
    column_types: tuple
    tables: tuple  # ((catalog, schema, table), ...)
    versions: tuple  # versions_snapshot() taken BEFORE the execution
    acl_generation: int
    nbytes: int
    created: float
    # classify_maintainability() verdict + the baked optimized plan it
    # applies to (re-executed over delta splits); None = invalidate-only
    maintain: Optional[dict] = None
    plan: Any = None
    maintained_count: int = 0


class ResultCache:
    """Byte-budget LRU of final result sets + the SQL-text memo that
    makes the probe parse-free (sub-millisecond hits cannot afford
    parse+plan; the memo maps ``(sql, session signature)`` straight to
    the entry key and is populated at store time)."""

    MEMO_MAX = 4096

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, ResultCacheEntry]" = OrderedDict()
        self._entry_hits: dict[tuple, int] = {}
        self._memo: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._maint_locks: dict[tuple, threading.Lock] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.maintained = 0
        self.invalidations = 0

    # --- metrics ----------------------------------------------------------
    @staticmethod
    def _metric_inc(name: str, n: int = 1) -> None:
        try:
            from trino_tpu.obs.metrics import get_registry

            # closed vocabulary: callers pass literal suffixes only
            get_registry().counter(f"trino_tpu_result_cache_{name}").inc(n)  # lint: ignore[OBS002]
        except Exception:  # noqa: BLE001 — metrics must never fail a query
            pass

    def _metric_bytes(self) -> None:
        try:
            from trino_tpu.obs.metrics import get_registry

            get_registry().gauge("trino_tpu_result_cache_bytes").set(self._bytes)
        except Exception:  # noqa: BLE001
            pass

    # --- probe ------------------------------------------------------------
    def lookup(self, engine, sql: str, session, allow_maintenance: bool = True):
        """A StatementResult served from cache, or None.

        Pure hits are lock-brief and IO-free beyond the per-table version
        fetch. With ``allow_maintenance`` (engine probe on a worker
        thread) an append-stale maintainable entry is merged in place;
        without it (admission fast path) such entries simply miss and the
        admitted execution maintains or overwrites them.
        """
        memo_key = (sql, session_signature(session))
        with self._lock:
            memo = self._memo.get(memo_key)
            if memo is not None:
                self._memo.move_to_end(memo_key)
        if memo is None:
            return None  # unknown text/session: not counted as a miss
        fp, params_key, tables = memo
        from trino_tpu.security import AccessDeniedError

        try:
            for cat, schema, table in tables:
                engine.access_control.check_can_select(
                    session.user, cat, schema, table
                )
        except AccessDeniedError:
            return None  # the full path raises the user-visible error
        key = (fp, params_key)
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            self._miss()
            return None
        if entry.acl_generation != engine.access_control.generation:
            self._drop(key)
            self._miss()
            return None
        status, deltas = self._compare_versions(engine.catalogs, entry)
        if status == "same":
            res = self._serve(key)
            if res is None:
                self._miss()
            return res
        if status == "append" and entry.maintain is not None:
            if not allow_maintenance or not _maintenance_on(session):
                # leave the entry intact: a maintaining caller (or the
                # store after a full re-execution) will refresh it
                self._miss()
                return None
            res = self._maintain(engine, key, session)
            if res is None:
                self._miss()
            return res
        self._drop(key)
        self._miss()
        return None

    def _miss(self) -> None:
        with self._lock:
            self.misses += 1
        self._metric_inc("misses")

    def _serve(self, key, extra: Optional[dict] = None, ingest_stats=None):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            self._entry_hits[key] = self._entry_hits.get(key, 0) + 1
            entry_hits = self._entry_hits[key]
            self.hits += 1
        self._metric_inc("hits")
        from trino_tpu.engine import StatementResult

        stats = {
            "resultCacheHit": 1,
            "entryHits": entry_hits,
            "maintainedCount": entry.maintained_count,
        }
        if extra:
            stats.update(extra)
        return StatementResult(
            rows=list(entry.rows),
            column_names=list(entry.column_names),
            column_types=list(entry.column_types),
            ingest_stats=ingest_stats,
            result_cache_stats=stats,
        )

    def _drop(self, key) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return
            self._bytes -= entry.nbytes
            self._entry_hits.pop(key, None)
            self.invalidations += 1
        self._metric_inc("invalidations")
        self._metric_bytes()

    def _compare_versions(self, catalogs, entry: ResultCacheEntry):
        """("same"|"append"|"changed", {table -> (appended_ids, new_parts)})."""
        from trino_tpu.ingest import parts_delta

        status = "same"
        deltas: dict[tuple, tuple] = {}
        for cat, schema, table, coarse, parts in entry.versions:
            try:
                conn = catalogs.get(cat)
            except KeyError:
                return "changed", {}
            if parts is not None:
                new_parts = conn.data_versions(schema, table)
                if new_parts is None:
                    return "changed", {}
                new_parts = tuple(new_parts)
                if new_parts == parts:
                    continue
                verdict, appended = parts_delta(parts, new_parts)
                if verdict == "same":
                    continue
                if verdict != "append":
                    return "changed", {}
                status = "append"
                deltas[(cat, schema, table)] = (appended, new_parts)
            elif conn.data_version(schema, table) != coarse:
                return "changed", {}
        return status, deltas

    # --- store ------------------------------------------------------------
    def store(
        self,
        *,
        sql: str,
        session,
        fingerprint: str,
        params: list,
        tables,
        versions: tuple,
        acl_generation: int,
        res,
        maintain: Optional[dict],
        plan,
        max_bytes: Optional[int] = None,
    ) -> bool:
        """Insert/replace the entry for this execution (versions are the
        PRE-execution snapshot, so a write racing the execution leaves the
        entry conservatively stale, never wrong)."""
        if max_bytes is not None:
            self.max_bytes = int(max_bytes)
        params_key = tuple((v, repr(t)) for v, t in params)
        rows = tuple(tuple(r) for r in res.rows)
        nbytes = _estimate_bytes(rows)
        if nbytes > self.max_bytes:
            return False  # a single oversized result would evict everything
        entry = ResultCacheEntry(
            fingerprint=fingerprint,
            params_key=params_key,
            sql=sql,
            rows=rows,
            column_names=tuple(res.column_names),
            column_types=tuple(res.column_types),
            tables=tuple(tables),
            versions=versions,
            acl_generation=acl_generation,
            nbytes=nbytes,
            created=time.time(),
            maintain=maintain,
            plan=plan,
        )
        key = (fingerprint, params_key)
        memo_key = (sql, session_signature(session))
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                k, e = self._entries.popitem(last=False)
                self._bytes -= e.nbytes
                self._entry_hits.pop(k, None)
                self._maint_locks.pop(k, None)
                self.evictions += 1
                evicted += 1
            self._memo[memo_key] = (fingerprint, params_key, tuple(tables))
            self._memo.move_to_end(memo_key)
            while len(self._memo) > self.MEMO_MAX:
                self._memo.popitem(last=False)
        if evicted:
            self._metric_inc("evictions", evicted)
        self._metric_bytes()
        return True

    # --- incremental maintenance ------------------------------------------
    def _maint_lock(self, key) -> threading.Lock:
        with self._lock:
            return self._maint_locks.setdefault(key, threading.Lock())

    def _maintain(self, engine, key, session):
        """Merge an append delta into the cached entry and serve it.

        Runs on the calling worker thread; serialized per entry by a
        mutex acquired without the cache lock (order: maintenance ->
        cache, never the reverse). Any surprise — rewrite raced in,
        delta execution failed, a writer appended again mid-merge —
        drops the entry and falls back to full re-execution.
        """
        with self._maint_lock(key):
            with self._lock:
                entry = self._entries.get(key)
            if entry is None:
                return None
            # re-validate under the maintenance lock: another maintainer
            # may have merged while this caller waited
            status, deltas = self._compare_versions(engine.catalogs, entry)
            if status == "same":
                return self._serve(key)
            if status != "append" or entry.maintain is None or entry.plan is None:
                self._drop(key)
                return None
            table_key = tuple(entry.maintain["table"])
            if set(deltas) != {table_key}:
                self._drop(key)
                return None
            appended, new_parts = deltas[table_key]
            try:
                merged, ingest = self._execute_delta(engine, entry, session, appended)
            except Exception:  # noqa: BLE001 — fall back to re-execution
                self._drop(key)
                return None
            cat, schema, table = table_key
            conn = engine.catalogs.get(cat)
            # only publish when the table still reads exactly as the
            # snapshot the merge brought the rows up to (a writer racing
            # the delta scan otherwise makes the merge unanchored)
            check = conn.data_versions(schema, table)
            if check is None or tuple(check) != new_parts:
                self._drop(key)
                return None
            new_versions = tuple(
                v
                if (v[0], v[1], v[2]) != table_key
                else (cat, schema, table, conn.data_version(schema, table), new_parts)
                for v in entry.versions
            )
            replacement = dataclasses.replace(
                entry,
                rows=merged,
                versions=new_versions,
                nbytes=_estimate_bytes(merged),
                maintained_count=entry.maintained_count + 1,
            )
            evicted = 0
            with self._lock:
                old = self._entries.pop(key, None)
                if old is not None:
                    self._bytes -= old.nbytes
                self._entries[key] = replacement
                self._bytes += replacement.nbytes
                self.maintained += 1
                while self._bytes > self.max_bytes and len(self._entries) > 1:
                    k, e = self._entries.popitem(last=False)
                    self._bytes -= e.nbytes
                    self._entry_hits.pop(k, None)
                    self._maint_locks.pop(k, None)
                    self.evictions += 1
                    evicted += 1
            if evicted:
                self._metric_inc("evictions", evicted)
            self._metric_inc("maintained")
            self._metric_bytes()
            return self._serve(
                key,
                extra={
                    "incrementalMaintenance": 1,
                    "deltaSplits": int(ingest.get("splits_decoded", 0)),
                },
                ingest_stats=ingest or None,
            )

    def _execute_delta(self, engine, entry: ResultCacheEntry, session, appended):
        """Execute the entry's baked plan over ONLY the appended parts and
        merge; returns (merged_rows, delta ingest stats)."""
        from trino_tpu.config import Session
        from trino_tpu.connectors.api import CatalogManager
        from trino_tpu.exec.local import LocalExecutor

        cat, schema, table = entry.maintain["table"]
        inner = engine.catalogs.get(cat)
        delta_conn = DeltaPartsConnector(inner, schema, table, appended)
        catalogs = CatalogManager()
        for name in engine.catalogs.names():
            catalogs.register(
                name, delta_conn if name == cat else engine.catalogs.get(name)
            )
        props = dict(session.properties)
        props.pop("__txn", None)
        props["execution_mode"] = "local"
        msession = Session(
            user=session.user,
            catalog=session.catalog,
            schema=session.schema,
            properties=props,
        )
        executor = LocalExecutor(catalogs, msession)
        batch, _names = executor.execute(entry.plan)
        delta_rows = batch.to_pylist()
        ingest = executor.ingest_stats_snapshot() or {}
        merged = merge_aggregate_rows(entry.rows, delta_rows, entry.maintain["cols"])
        return merged, ingest

    # --- introspection (GET /v1/cache) ------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready state (brief lock only: safe to call from the event
        loop, same discipline as /v1/metrics)."""
        now = time.time()
        with self._lock:
            entries = [
                {
                    "fingerprint": e.fingerprint,
                    "query": e.sql.splitlines()[0][:120] if e.sql else "",
                    "rows": len(e.rows),
                    "nbytes": e.nbytes,
                    "hits": self._entry_hits.get(k, 0),
                    "maintainable": e.maintain is not None,
                    "maintainedCount": e.maintained_count,
                    "ageMs": int((now - e.created) * 1000),
                }
                for k, e in self._entries.items()
            ]
            return {
                "entries": entries,
                "totalBytes": self._bytes,
                "maxBytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "maintained": self.maintained,
                "invalidations": self.invalidations,
                "memoSize": len(self._memo),
            }


def _maintenance_on(session) -> bool:
    try:
        return bool(session.get("incremental_maintenance"))
    except KeyError:
        return False
