"""Transaction management.

Reference: ``core/trino-main/.../transaction/InMemoryTransactionManager.java``
— per-connector ``ConnectorTransactionHandle``s coordinated by a
transaction id; autocommit wraps single statements; explicit transactions
span statements and abort on access conflicts.

v1 scope matches the engine's connector surface: the memory connector is
the only writable store, so commit/rollback snapshot-and-restore its
table data; read-only connectors participate trivially (their handle is a
marker). Isolation is snapshot-at-begin for writes (READ COMMITTED-ish,
single-writer — the reference's default is also READ UNCOMMITTED-adjacent
per connector capability)."""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Optional

_txn_counter = itertools.count(1)


class TransactionError(Exception):
    pass


@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    create_time: float
    autocommit: bool
    snapshots: dict[str, Any] = dataclasses.field(default_factory=dict)
    state: str = "ACTIVE"  # ACTIVE | COMMITTED | ABORTED
    last_access: float = 0.0
    busy: int = 0  # statements currently executing in this transaction


class TransactionManager:
    """Registry + 2-phase-ish commit over snapshot-capable connectors."""

    def __init__(self, catalogs, idle_timeout: float = 300.0):
        self.catalogs = catalogs
        # reference expires idle transactions (transaction.idle-timeout);
        # without this a client that BEGINs and disconnects holds the
        # write lock forever
        self.idle_timeout = idle_timeout
        self._lock = threading.Lock()
        self._transactions: dict[str, TransactionInfo] = {}
        # single-writer enforcement: an explicit transaction holds this for
        # its whole lifetime; autocommit writes take it per statement. This
        # is what makes snapshot-at-begin rollback sound — no concurrent
        # committed write can be erased because none can start.
        # (threading.Lock may be released from a different thread than the
        # acquirer — required: HTTP requests hop threads.)
        self.write_lock = threading.Lock()

    def begin(self, autocommit: bool = False) -> str:
        self.expire_idle()
        if not self.write_lock.acquire(timeout=60):
            raise TransactionError("timed out waiting for the write lock")
        now = time.time()
        txn = TransactionInfo(
            f"txn_{next(_txn_counter)}", now, autocommit, last_access=now
        )
        with self._lock:
            self._transactions[txn.transaction_id] = txn
        # snapshot writable connectors (memory): rollback restores
        for name in self.catalogs.names():
            conn = self.catalogs.get(name)
            snap = getattr(conn, "snapshot_state", None)
            if snap is not None:
                txn.snapshots[name] = snap()
        return txn.transaction_id

    def get(self, txn_id: str) -> TransactionInfo:
        with self._lock:
            txn = self._transactions.get(txn_id)
        if txn is None:
            raise TransactionError(f"unknown transaction: {txn_id}")
        txn.last_access = time.time()
        return txn

    def expire_idle(self) -> None:
        """Roll back ACTIVE transactions idle beyond ``idle_timeout`` so an
        abandoned BEGIN eventually releases the write lock. Transactions
        with a statement mid-flight (busy > 0) are never expired."""
        now = time.time()
        for t in self.active_transactions():
            if t.busy == 0 and now - max(t.last_access, t.create_time) > self.idle_timeout:
                try:
                    self.rollback(t.transaction_id)
                except TransactionError:
                    pass  # raced with a concurrent commit/rollback

    def _transition(self, txn_id: str, new_state: str) -> TransactionInfo:
        """Atomically move an ACTIVE transaction to a terminal state. Exactly
        one caller wins (commit vs concurrent expire-rollback race); losers
        get TransactionError and must NOT touch snapshots or the lock."""
        with self._lock:
            txn = self._transactions.get(txn_id)
            if txn is None:
                raise TransactionError(f"unknown transaction: {txn_id}")
            if txn.state != "ACTIVE":
                raise TransactionError(f"transaction {txn_id} is {txn.state}")
            txn.state = new_state
        return txn

    def commit(self, txn_id: str) -> None:
        txn = self._transition(txn_id, "COMMITTED")
        txn.snapshots.clear()
        self._finish(txn_id)

    def rollback(self, txn_id: str) -> None:
        txn = self._transition(txn_id, "ABORTED")
        for name, snap in txn.snapshots.items():
            conn = self.catalogs.get(name)
            restore = getattr(conn, "restore_state", None)
            if restore is not None:
                restore(snap)
        txn.snapshots.clear()
        self._finish(txn_id)

    def _finish(self, txn_id: str) -> None:
        # only ever reached by the thread that won _transition, so the
        # write_lock is released exactly once per transaction
        with self._lock:
            self._transactions.pop(txn_id, None)  # no unbounded history
        try:
            self.write_lock.release()
        except RuntimeError:
            pass

    def active_transactions(self) -> list[TransactionInfo]:
        with self._lock:
            return [t for t in self._transactions.values() if t.state == "ACTIVE"]
