"""Transaction management.

Reference: ``core/trino-main/.../transaction/InMemoryTransactionManager.java``
— per-connector ``ConnectorTransactionHandle``s coordinated by a
transaction id; autocommit wraps single statements; explicit transactions
span statements and abort on access conflicts.

v1 scope matches the engine's connector surface: the memory connector is
the only writable store, so commit/rollback snapshot-and-restore its
table data; read-only connectors participate trivially (their handle is a
marker). Isolation is snapshot-at-begin for writes (READ COMMITTED-ish,
single-writer — the reference's default is also READ UNCOMMITTED-adjacent
per connector capability)."""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Optional

_txn_counter = itertools.count(1)


class TransactionError(Exception):
    pass


@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    create_time: float
    autocommit: bool
    snapshots: dict[str, Any] = dataclasses.field(default_factory=dict)
    state: str = "ACTIVE"  # ACTIVE | COMMITTED | ABORTED


class TransactionManager:
    """Registry + 2-phase-ish commit over snapshot-capable connectors."""

    def __init__(self, catalogs):
        self.catalogs = catalogs
        self._lock = threading.Lock()
        self._transactions: dict[str, TransactionInfo] = {}
        # single-writer enforcement: an explicit transaction holds this for
        # its whole lifetime; autocommit writes take it per statement. This
        # is what makes snapshot-at-begin rollback sound — no concurrent
        # committed write can be erased because none can start.
        # (threading.Lock may be released from a different thread than the
        # acquirer — required: HTTP requests hop threads.)
        self.write_lock = threading.Lock()

    def begin(self, autocommit: bool = False) -> str:
        if not self.write_lock.acquire(timeout=60):
            raise TransactionError("timed out waiting for the write lock")
        txn = TransactionInfo(
            f"txn_{next(_txn_counter)}", time.time(), autocommit
        )
        with self._lock:
            self._transactions[txn.transaction_id] = txn
        # snapshot writable connectors (memory): rollback restores
        for name in self.catalogs.names():
            conn = self.catalogs.get(name)
            snap = getattr(conn, "snapshot_state", None)
            if snap is not None:
                txn.snapshots[name] = snap()
        return txn.transaction_id

    def get(self, txn_id: str) -> TransactionInfo:
        with self._lock:
            txn = self._transactions.get(txn_id)
        if txn is None:
            raise TransactionError(f"unknown transaction: {txn_id}")
        return txn

    def commit(self, txn_id: str) -> None:
        txn = self.get(txn_id)
        if txn.state != "ACTIVE":
            raise TransactionError(f"transaction {txn_id} is {txn.state}")
        txn.state = "COMMITTED"
        txn.snapshots.clear()
        self._finish(txn_id)

    def rollback(self, txn_id: str) -> None:
        txn = self.get(txn_id)
        if txn.state != "ACTIVE":
            raise TransactionError(f"transaction {txn_id} is {txn.state}")
        for name, snap in txn.snapshots.items():
            conn = self.catalogs.get(name)
            restore = getattr(conn, "restore_state", None)
            if restore is not None:
                restore(snap)
        txn.state = "ABORTED"
        txn.snapshots.clear()
        self._finish(txn_id)

    def _finish(self, txn_id: str) -> None:
        with self._lock:
            self._transactions.pop(txn_id, None)  # no unbounded history
        try:
            self.write_lock.release()
        except RuntimeError:
            pass

    def active_transactions(self) -> list[TransactionInfo]:
        with self._lock:
            return [t for t in self._transactions.values() if t.state == "ACTIVE"]
