"""Pages wire format: Batch <-> bytes for exchange and spill.

Reference: ``execution/buffer/PagesSerde.java:41,64`` (per-block encodings
+ optional LZ4 compression) and the wire magic ``0xfea4f001``
(``server/PagesResponseWriter.java:50``). Encodings per column:

  PLAIN    raw little-endian storage bytes (floats)
  VARINT   delta+zigzag varints (keys, timestamps — usually near-sorted)
  RLE      run-length (low-cardinality / constant columns)
  BOOL     1-bit bitpack

Validity masks bitpack to 1 bit/row; varchar ships dictionary + codes.
The whole payload is LZ-compressed by the native codec (zlib fallback is
tagged in the header so mixed peers stay compatible).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary
from trino_tpu.native import (
    NATIVE_AVAILABLE,
    bitpack_decode,
    bitpack_encode,
    lz_compress,
    lz_decompress,
    rle_decode,
    rle_encode,
    varint_decode,
    varint_encode,
)

PAGES_MAGIC = 0xFEA4F001
# format version: bumped when the per-column layout changes (v2 added the
# wide-DECIMAL lane flag); readers reject other versions loudly instead of
# misparsing persisted part files
PAGES_VERSION = 3  # v3: typed dictionary values (ARRAY pools over the wire)
_CODEC_LZ = 0  # native/columnar.cpp tt_lz_*
_CODEC_ZLIB = 1

_ENC_PLAIN, _ENC_VARINT, _ENC_RLE, _ENC_BOOL = 0, 1, 2, 3


def _pack_bytes(b: bytes) -> bytes:
    return struct.pack("<q", len(b)) + b


# --- typed dictionary values (strings AND array pools) ----------------------
# ARRAY columns pool distinct array VALUES (python tuples of scalars/None)
# exactly like varchar pools strings; the wire must carry both
# (reference: ArrayBlock offsets+values — here pool + codes).


def _enc_value(v) -> bytes:
    if v is None:
        return b"\x00"
    if isinstance(v, bool):
        return b"\x01" + (b"\x01" if v else b"\x00")
    if isinstance(v, (int, np.integer)):
        return b"\x02" + struct.pack("<q", int(v))
    if isinstance(v, (float, np.floating)):
        return b"\x03" + struct.pack("<d", float(v))
    if isinstance(v, str):
        b = v.encode("utf-8", "surrogatepass")
        return b"\x04" + struct.pack("<i", len(b)) + b
    if isinstance(v, tuple):
        return b"\x05" + struct.pack("<i", len(v)) + b"".join(
            _enc_value(e) for e in v
        )
    raise ValueError(f"unsupported dictionary value type {type(v)!r}")


def _dec_value(buf: bytes, pos: int):
    tag = buf[pos]
    pos += 1
    if tag == 0:
        return None, pos
    if tag == 1:
        return buf[pos] == 1, pos + 1
    if tag == 2:
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if tag == 3:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag == 4:
        (ln,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        return buf[pos : pos + ln].decode("utf-8", "surrogatepass"), pos + ln
    if tag == 5:
        (ln,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        out = []
        for _ in range(ln):
            v, pos = _dec_value(buf, pos)
            out.append(v)
        return tuple(out), pos
    raise ValueError(f"corrupt dictionary value tag {tag}")


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def unpack(self, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.take(size))

    def take_bytes(self) -> bytes:
        (n,) = self.unpack("<q")
        return self.take(n)


def _encode_ints(data: np.ndarray) -> tuple[int, bytes]:
    """Pick RLE when runs dominate, else delta-varint."""
    as64 = data.astype(np.int64)
    n = len(as64)
    if n == 0:
        return _ENC_VARINT, b""
    runs = int(np.count_nonzero(np.diff(as64))) + 1
    if runs * 4 <= n:
        return _ENC_RLE, rle_encode(as64)
    return _ENC_VARINT, varint_encode(as64)


def serialize_batch(batch: Batch, compress: bool = True) -> bytes:
    """Batch -> wire bytes. Selection is applied (compact first)."""
    batch = batch.compact()
    n = batch.num_rows
    parts: list[bytes] = []
    for c in batch.columns:
        data, valid = c.to_numpy()
        ty = str(c.type)
        parts.append(_pack_bytes(ty.encode()))
        has_valid = 0 if bool(valid.all()) else 1
        has_dict = 1 if c.dictionary is not None else 0
        # wide DECIMAL columns ((n, 2) int64 hi/lo lanes) ship as two
        # consecutive lane encodings
        is_wide = 1 if data.ndim == 2 else 0
        parts.append(struct.pack("<bbb", has_valid, has_dict, is_wide))
        if has_valid:
            parts.append(_pack_bytes(bitpack_encode(valid.astype(np.uint64), 1)))
        if has_dict:
            blob = b"".join(_enc_value(v) for v in c.dictionary.values)
            parts.append(struct.pack("<q", len(c.dictionary.values)))
            parts.append(_pack_bytes(blob))
        lanes = [data[:, 0], data[:, 1]] if is_wide else [data]
        for lane in lanes:
            if lane.dtype == np.bool_:
                parts.append(struct.pack("<b", _ENC_BOOL))
                parts.append(_pack_bytes(bitpack_encode(lane.astype(np.uint64), 1)))
            elif lane.dtype.kind == "f":
                parts.append(struct.pack("<b", _ENC_PLAIN))
                parts.append(_pack_bytes(np.ascontiguousarray(lane).tobytes()))
            else:
                enc, payload = _encode_ints(lane)
                parts.append(struct.pack("<b", enc))
                parts.append(_pack_bytes(payload))
    body = b"".join(parts)
    codec = _CODEC_LZ if NATIVE_AVAILABLE else _CODEC_ZLIB
    compressed = lz_compress(body) if compress else body
    if not compress:
        codec = 0xFF  # uncompressed marker
    header = struct.pack(
        "<IBBqqQ", PAGES_MAGIC, PAGES_VERSION, codec, n, len(batch.columns), len(body)
    )
    return header + compressed


def deserialize_batch(data: bytes) -> Batch:
    r = _Reader(data)
    magic, version, codec, n, ncols, raw_len = r.unpack("<IBBqqQ")
    if magic != PAGES_MAGIC:
        raise ValueError(f"bad pages magic: {magic:#x}")
    if version != PAGES_VERSION:
        raise ValueError(
            f"pages format v{version} (expected v{PAGES_VERSION}) — "
            "table was written by an incompatible build"
        )
    payload = r.data[r.pos :]
    if codec == 0xFF:
        body = payload
    elif codec == _CODEC_LZ:
        if not NATIVE_AVAILABLE:
            raise ValueError("page compressed with native codec; lib unavailable")
        # ratio bound of the format: a 3-byte match token expands to <=131
        # bytes (~44x); a corrupt header can't force a huge allocation
        if raw_len > len(payload) * 64 + 1024:
            raise ValueError(f"implausible page raw length {raw_len}")
        body = lz_decompress(payload, raw_len)
    elif codec == _CODEC_ZLIB:
        import zlib

        body = zlib.decompress(payload)
    else:
        raise ValueError(f"unknown codec {codec}")
    br = _Reader(body)
    cols: list[Column] = []
    for _ in range(ncols):
        ty = T.parse_type(br.take_bytes().decode())
        has_valid, has_dict, is_wide = br.unpack("<bbb")
        valid: Optional[np.ndarray] = None
        if has_valid:
            valid = bitpack_decode(br.take_bytes(), n, 1).astype(np.bool_)
        dictionary = None
        if has_dict:
            (dict_len,) = br.unpack("<q")
            blob = br.take_bytes()
            values = []
            pos = 0
            for _ in range(dict_len):
                v, pos = _dec_value(blob, pos)
                values.append(v)
            dictionary = Dictionary(values)
        dtype = ty.storage_dtype
        lanes = []
        for _lane in range(2 if is_wide else 1):
            (enc,) = br.unpack("<b")
            payload = br.take_bytes()
            if enc == _ENC_BOOL:
                data_arr = bitpack_decode(payload, n, 1).astype(np.bool_)
            elif enc == _ENC_PLAIN:
                data_arr = np.frombuffer(payload, dtype=dtype).copy()
            elif enc == _ENC_RLE:
                data_arr = rle_decode(payload, n).astype(dtype)
            else:
                data_arr = varint_decode(payload, n).astype(dtype)
            lanes.append(data_arr.astype(dtype))
        data_out = np.stack(lanes, axis=1) if is_wide else lanes[0]
        cols.append(Column(ty, data_out, valid, dictionary))
    return Batch(cols, n)
