"""Dynamic filtering: runtime join pruning.

Reference: ``operator/DynamicFilterSourceOperator.java:55`` (build side
collects distinct key domains), ``server/DynamicFilterService.java:95,323``
(merge + push into probe scans), ``spi/connector/DynamicFilter.java``.

TPU-first twist: our executors materialize the build side before the probe
runs (stage-at-a-time, like a pjit program per fragment), so the dynamic
filter is *exact and synchronous* — no racing "filter arrived too late"
path. The build keys' domain is computed host-side from the materialized
build columns, then pushed into the probe subtree as (a) an intersected
scan ``constraint`` (prunes whole splits via min/max stats) and (b) a
row-level Filter (prunes probe rows before the join shuffle — the big win:
less data through ``all_to_all``).

Applies to INNER equi-joins only (outer joins preserve probe rows; SEMI
marks may feed arbitrary boolean contexts).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.predicate import Domain, Range, TupleDomain, ValueSet, to_row_expr
from trino_tpu.planner import plan as P

# discrete-set cap (above this, fall back to [min,max] range — reference:
# dynamic-filtering.small/large-max-distinct-values-per-driver)
MAX_DISCRETE_VALUES = 200


@dataclasses.dataclass
class DynamicFilterStats:
    """One collected filter, for EXPLAIN ANALYZE / observability
    (reference: DynamicFilterService.DynamicFilterDomainStats)."""

    symbol: str
    kind: str  # "discrete" | "range" | "none"
    distinct_values: int
    build_rows: int


def domain_from_build(
    data: np.ndarray, valid: Optional[np.ndarray], type_: T.SqlType
) -> Optional[Domain]:
    """Distinct-value / range domain of a materialized build key column.
    Returns None when the column type is not eligible (strings: probe and
    build dictionaries differ; skip in v1)."""
    if T.is_string(type_) or isinstance(type_, T.BooleanType):
        return None
    if valid is not None:
        data = data[valid]
    if data.size == 0:
        # empty build side: inner join produces nothing — probe prunes to zero
        return Domain.none(type_)
    uniq = np.unique(data)
    if uniq.size <= MAX_DISCRETE_VALUES:
        return Domain.of_values([v.item() for v in uniq], type_)
    return Domain(
        ValueSet.of_ranges([Range(uniq[0].item(), True, uniq[-1].item(), True)]),
        False,
        type_,
    )


def convert_domain(
    domain: Domain, from_type: T.SqlType, to_type: T.SqlType
) -> Optional[Domain]:
    """Convert a domain between storage representations across a coercing
    join criterion (e.g. DECIMAL(3,2) build vs BIGINT probe: storage 500
    vs 5). Returns None when no exact conversion exists (skip the filter)."""
    if from_type == to_type:
        return domain
    def scale_of(t: T.SqlType) -> Optional[int]:
        if isinstance(t, T.DecimalType):
            return t.scale
        if T.is_integer(t):
            return 0
        return None

    sf, st = scale_of(from_type), scale_of(to_type)
    if sf is None or st is None:
        # float/date/string cross-type: storage values are not portable
        if type(from_type) is type(to_type):
            return domain
        return None
    if sf == st:
        return domain
    if domain.values.is_all or domain.values.is_none():
        return Domain(domain.values, domain.null_allowed, to_type)
    out_ranges = []
    if st > sf:
        f = 10 ** (st - sf)
        for r in domain.values.ranges:
            out_ranges.append(
                Range(
                    None if r.low is None else r.low * f, r.low_inclusive,
                    None if r.high is None else r.high * f, r.high_inclusive,
                )
            )
    else:
        f = 10 ** (sf - st)
        for r in domain.values.ranges:
            if r.is_single_value:
                if r.low % f == 0:
                    out_ranges.append(Range.equal(r.low // f))
                continue  # value has fractional digits: matches no probe row
            lo = None if r.low is None else -(-r.low // f)  # ceil
            hi = None if r.high is None else r.high // f  # floor
            out_ranges.append(Range(lo, True, hi, True))
    return Domain(ValueSet.of_ranges(out_ranges), domain.null_allowed, to_type)


def push_probe_domain(
    node: P.PlanNode, symbol: P.Symbol, domain: Domain
) -> P.PlanNode:
    """Push ``symbol in domain`` as deep into the probe plan as is sound,
    intersecting scan constraints at the bottom (the runtime analog of
    PushPredicateIntoTableScan for dynamic filters)."""
    name = symbol.name

    if isinstance(node, P.TableScan):
        if name in {s.name for s in node.symbols}:
            sym_to_col = {s.name: c for s, c in zip(node.symbols, node.column_names)}
            extra = TupleDomain({sym_to_col[name]: domain})
            constraint = (
                extra if node.constraint is None else node.constraint.intersect(extra)
            )
            scan = P.TableScan(
                node.catalog, node.schema, node.table, node.symbols,
                node.column_names, node.pushed_predicate, constraint,
            )
            return _filter_above(scan, symbol, domain)
        return node

    if isinstance(node, P.Filter):
        return P.Filter(push_probe_domain(node.source, symbol, domain), node.predicate)

    if isinstance(node, P.Project):
        for s, e in node.assignments:
            if s.name == name:
                from trino_tpu.ir import Variable

                if isinstance(e, Variable):
                    inner = P.Symbol(e.name, e.type)
                    return P.Project(
                        push_probe_domain(node.source, inner, domain),
                        node.assignments,
                    )
                return _filter_above(node, symbol, domain)
        return node

    if isinstance(node, P.Join):
        left_names = {s.name for s in node.left.output_symbols}
        right_names = {s.name for s in node.right.output_symbols}
        # descend only into row-preserved sides (INNER both; LEFT left;
        # RIGHT right) — filtering a null-extended side below its join
        # would differ from filtering above
        if name in left_names and node.join_type in ("INNER", "LEFT", "SEMI", "ANTI", "CROSS"):
            return _replace_join_sides(
                node, push_probe_domain(node.left, symbol, domain), node.right
            )
        if name in right_names and node.join_type in ("INNER", "RIGHT", "CROSS"):
            return _replace_join_sides(
                node, node.left, push_probe_domain(node.right, symbol, domain)
            )
        if name in right_names and node.join_type in ("LEFT", "FULL"):
            # null-extended side: a NOT-NULL domain filter above the outer
            # join would drop the very rows the join exists to keep
            return node
        if name in left_names and node.join_type in ("RIGHT", "FULL"):
            return node
        return _filter_above(node, symbol, domain)

    if isinstance(node, P.Aggregate):
        if any(k.name == name for k in node.group_keys):
            return P.Aggregate(
                push_probe_domain(node.source, symbol, domain),
                node.group_keys, node.aggregates, node.step,
            )
        return node

    if isinstance(node, (P.Sort, P.Limit, P.TopN, P.Distinct, P.Window, P.SetOp)):
        # row-count-sensitive or multi-input: filter above, don't descend
        if name in {s.name for s in node.output_symbols}:
            return _filter_above(node, symbol, domain)
        return node

    if name in {s.name for s in node.output_symbols}:
        return _filter_above(node, symbol, domain)
    return node


def _filter_above(node: P.PlanNode, symbol: P.Symbol, domain: Domain) -> P.PlanNode:
    pred = to_row_expr(TupleDomain({symbol.name: domain}), {symbol.name: symbol.type})
    if pred is None:
        return node
    return P.Filter(node, pred)


def _replace_join_sides(node: P.Join, left: P.PlanNode, right: P.PlanNode) -> P.Join:
    return P.Join(
        node.join_type, left, right, node.criteria, node.filter,
        node.distribution, node.mark_symbol, node.null_aware,
        node.single_row,
    )


def collect_and_push(
    plan_node: P.PlanNode,
    probe_sym: P.Symbol,
    build_sym: P.Symbol,
    data: np.ndarray,
    valid: Optional[np.ndarray],
    build_rows: int,
    stats_out: Optional[list],
) -> P.PlanNode:
    """Shared per-criteria DF core used by the interpreter join and the
    fragment-level paths: build domain -> coerce to the probe type ->
    record stats -> push into the probe plan."""
    data = np.asarray(data)
    if data.ndim != 1:
        return plan_node  # wide-decimal (hi, lo) lanes: no host domain
    domain = domain_from_build(data, valid, build_sym.type)
    if domain is None or domain.is_all():
        return plan_node
    domain = convert_domain(domain, build_sym.type, probe_sym.type)
    if domain is None or domain.is_all():
        return plan_node
    if stats_out is not None:
        dv = domain.values.discrete_values()
        stats_out.append(
            DynamicFilterStats(
                probe_sym.name,
                "none" if domain.is_none() else (
                    "discrete" if dv is not None else "range"
                ),
                len(dv) if dv else 0,
                build_rows,
            )
        )
    return push_probe_domain(plan_node, probe_sym, domain)


def fragment_dynamic_filters(
    root: P.PlanNode,
    build_lookup,
    session,
    stats_out: Optional[list] = None,
) -> P.PlanNode:
    """Fragment-level dynamic filtering for fused/cluster execution.

    For every INNER equi-join in this fragment whose build side is a
    RemoteSource with a COMPLETED upstream result, compute the build
    keys' domains and push them into the probe subtree (scan constraints
    + row filters) before the fragment's inputs materialize. Sound for
    hash-partitioned builds too: probe rows of a task are co-partitioned
    with its build rows, so the task-local domain covers exactly the
    task-local probe rows.

    ``build_lookup(fragment_id)`` returns ``(get_column, n_rows)`` where
    ``get_column(name)`` lazily materializes ``(data, valid)`` host
    arrays for one build column (or None), or None when the upstream
    result is unavailable (e.g. sharded across hosts).

    Reference: ``server/DynamicFilterService.java:95,323`` — here the
    stage-at-a-time schedule makes the filter exact and synchronous.
    """
    if not session.get("enable_dynamic_filtering"):
        return root
    max_rows = int(session.get("dynamic_filtering_max_build_rows"))
    new_root = root
    for node in P.walk_plan(root):
        if (
            not isinstance(node, P.Join)
            or node.join_type != "INNER"
            or not node.criteria
            or not isinstance(node.right, P.RemoteSource)
        ):
            continue
        looked = build_lookup(node.right.fragment_id)
        if looked is None:
            continue
        get_column, n_rows = looked
        if n_rows > max_rows:
            continue
        for probe_sym, build_sym in node.criteria:
            pair = get_column(build_sym.name)
            if pair is None:
                continue
            data, valid = pair
            new_root = collect_and_push(
                new_root, probe_sym, build_sym, data, valid,
                int(n_rows), stats_out,
            )
    return new_root
