"""Spooled exchange: durable copies of task output that survive the
producing worker's death (reference: Trino's fault-tolerant execution
over a spooled exchange — the Tardigrade ``exchange/`` SPI)."""

from trino_tpu.exchange.spool import (  # noqa: F401
    DiskSpoolStore,
    MemorySpoolStore,
    SpoolStore,
    SpoolWriter,
    get_spool_store,
)
