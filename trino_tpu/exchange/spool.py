"""Spooled exchange store + worker-side async spool writer.

Reference: Trino's fault-tolerant execution exchange SPI
(``plugin/trino-exchange-filesystem`` / the Tardigrade design): workers
copy finished task output to a store that outlives them, so a consumer
whose producer died re-reads the spool instead of forcing the producer
(and transitively the whole query) to re-run.

Topology here: the **coordinator** hosts one :class:`SpoolStore` (RAM or
local disk — pluggable backends behind one registry) and serves it over
``/v1/spool/...`` (server/http.py). Workers never touch the spool medium
directly: a :class:`SpoolWriter` per task asynchronously POSTs completed
``OutputBuffer`` pages to the coordinator as they are enqueued, then
publishes a completion manifest (per-partition page counts) when the task
finishes. A spooled task is *readable* only once its manifest matches the
stored pages — a half-spooled stream from a crashed worker never serves.

The read side speaks the exact task-results wire shape
(``taskId/pages/token/complete/failed``), so the existing
``ExchangeClient`` (server/task.py) pulls a spool URI unchanged.

Capacity: ``spool_max_bytes`` bounds the store. Admission evicts
oldest-FINISHED-query data first (finish order, never a live query); when
eviction cannot make room the page is rejected and the task's spool stays
incomplete — recovery then falls back to lineage re-execution instead of
serving a truncated stream.
"""

from __future__ import annotations

import base64
import json
import os
import queue
import threading
import time
import urllib.request
from typing import Optional

from trino_tpu.obs.metrics import get_registry


class _TaskSpool:
    """Registry entry for one task's spooled output."""

    __slots__ = ("task_id", "query_id", "pages", "seqs", "bytes", "complete")

    def __init__(self, task_id: str, query_id: str):
        self.task_id = task_id
        self.query_id = query_id
        # partition -> ordered list of page handles (backend-defined)
        self.pages: dict[int, list] = {}
        # (partition, seq) already stored — re-POSTed pages dedupe
        self.seqs: set[tuple[int, int]] = set()
        self.bytes = 0
        self.complete = False


class SpoolStore:
    """Pluggable spool registry; backends implement page storage only.

    Thread-safe. Readable iff :meth:`complete` verified the producer's
    manifest against the stored page counts.
    """

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = int(max_bytes)
        self._tasks: dict[str, _TaskSpool] = {}
        # query_id -> finish ordinal (present = evictable, lowest first)
        self._finished_queries: dict[str, int] = {}
        self._finish_seq = 0
        self._total_bytes = 0
        self._evicted_bytes = 0
        self._rejected_pages = 0
        self.reaped_entries = 0  # startup debris sweep (disk backend)
        self._lock = threading.Lock()

    # --- backend hooks ----------------------------------------------------

    def _store_page(self, task_id: str, partition: int, seq: int,
                    page: bytes):
        raise NotImplementedError

    def _load_page(self, handle) -> bytes:
        raise NotImplementedError

    def _delete_pages(self, task_id: str, handles: list) -> None:
        raise NotImplementedError

    def _persist_manifest(self, task_id: str, query_id: str,
                          partitions: dict[int, int]) -> None:
        """Durable completion marker (disk backend): a spool directory
        without one is half-written debris after a coordinator crash."""

    # --- write path (worker POSTs relayed by server/http.py) --------------

    def put_page(self, query_id: str, task_id: str, partition: int,
                 seq: int, page: bytes) -> bool:
        """Store one page; False when the capacity cap rejects it (the
        task's spool then can never complete — lineage recovery applies).
        Idempotent per (task, partition, seq)."""
        with self._lock:
            entry = self._tasks.get(task_id)
            if entry is None:
                entry = self._tasks[task_id] = _TaskSpool(task_id, query_id)
                # a new task of a query revives it (QUERY retry re-runs
                # under the same id after the first attempt finished)
                self._finished_queries.pop(query_id, None)
            if (partition, seq) in entry.seqs:
                return True
            if not self._admit_locked(len(page), protect=query_id):
                self._rejected_pages += 1
                return False
            handle = self._store_page(task_id, partition, seq, page)
            entry.pages.setdefault(partition, []).append((seq, handle))
            entry.seqs.add((partition, seq))
            entry.bytes += len(page)
            self._total_bytes += len(page)
        reg = get_registry()
        reg.counter("trino_tpu_spooled_bytes_total").inc(len(page))
        reg.counter("trino_tpu_spooled_pages_total").inc()
        return True

    def _admit_locked(self, nbytes: int, protect: str) -> bool:
        """Make room under max_bytes, evicting oldest-finished-query data
        first; never evicts ``protect`` (the writing query) or any query
        not yet finished."""
        if nbytes > self.max_bytes:
            return False
        while self._total_bytes + nbytes > self.max_bytes:
            victim = min(
                (q for q in self._finished_queries if q != protect),
                key=lambda q: self._finished_queries[q],
                default=None,
            )
            if victim is None:
                return False
            self._delete_query_locked(victim)
        return True

    def complete(self, task_id: str, query_id: str,
                 partitions: dict[int, int]) -> bool:
        """Producer manifest: ``{partition: page_count}``. Marks the task
        readable iff every counted page is stored (a cap-rejected or lost
        page keeps it incomplete)."""
        with self._lock:
            entry = self._tasks.get(task_id)
            if entry is None:
                if not partitions:  # zero-output task: trivially complete
                    entry = self._tasks[task_id] = _TaskSpool(
                        task_id, query_id
                    )
                    entry.complete = True
                    self._persist_manifest(task_id, query_id, {})
                    return True
                return False
            for p, count in partitions.items():
                if len(entry.pages.get(int(p), [])) != int(count):
                    return False
            entry.complete = True
            self._persist_manifest(
                task_id, query_id, {int(p): int(c) for p, c in partitions.items()}
            )
            return True

    # --- read path (coordinator /v1/spool results route) ------------------

    def is_complete(self, task_id: str) -> bool:
        with self._lock:
            entry = self._tasks.get(task_id)
            return entry is not None and entry.complete

    def read(self, task_id: str, partition: int, token: int
             ) -> Optional[dict]:
        """Task-results wire dict for one token window, or None when the
        task is unknown/incomplete (the route 404s; a consumer pointed
        here by recovery only ever sees complete spools)."""
        with self._lock:
            entry = self._tasks.get(task_id)
            if entry is None or not entry.complete:
                return None
            handles = [
                h for _, h in sorted(entry.pages.get(partition, []))
            ][token:]
            pages = [self._load_page(h) for h in handles]
        return {
            "taskId": task_id,
            "pages": [base64.b64encode(p).decode() for p in pages],
            "token": token + len(pages),
            "complete": True,
            "failed": False,
            "error": None,
        }

    # --- lifecycle --------------------------------------------------------

    def delete_task(self, task_id: str) -> None:
        """Drop one task's spool (aborted writer, cancelled attempt)."""
        with self._lock:
            entry = self._tasks.pop(task_id, None)
            if entry is None:
                return
            self._drop_entry_locked(entry)

    def finish_query(self, query_id: str) -> None:
        """Mark a query's spool evictable (oldest-finished-first order)."""
        with self._lock:
            if query_id not in self._finished_queries:
                self._finish_seq += 1
                self._finished_queries[query_id] = self._finish_seq

    def delete_query(self, query_id: str) -> None:
        with self._lock:
            self._delete_query_locked(query_id)

    def query_bytes(self, query_id: str) -> int:
        with self._lock:
            return sum(
                e.bytes for e in self._tasks.values()
                if e.query_id == query_id
            )

    def _delete_query_locked(self, query_id: str) -> None:
        evicted = 0
        for tid in [
            tid for tid, e in self._tasks.items() if e.query_id == query_id
        ]:
            entry = self._tasks.pop(tid)
            evicted += entry.bytes
            self._drop_entry_locked(entry)
        self._finished_queries.pop(query_id, None)
        self._evicted_bytes += evicted

    def _drop_entry_locked(self, entry: _TaskSpool) -> None:
        self._total_bytes -= entry.bytes
        self._delete_pages(
            entry.task_id,
            [h for hs in entry.pages.values() for _, h in hs],
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "tasks": len(self._tasks),
                "completeTasks": sum(
                    1 for e in self._tasks.values() if e.complete
                ),
                "bytes": self._total_bytes,
                "maxBytes": self.max_bytes,
                "evictedBytes": self._evicted_bytes,
                "rejectedPages": self._rejected_pages,
                "finishedQueries": len(self._finished_queries),
                "reapedEntries": self.reaped_entries,
            }


class MemorySpoolStore(SpoolStore):
    """Host-RAM backend: page handles ARE the bytes."""

    def _store_page(self, task_id, partition, seq, page):
        return page

    def _load_page(self, handle):
        return handle

    def _delete_pages(self, task_id, handles):
        pass


class DiskSpoolStore(SpoolStore):
    """Local-disk backend: one directory per task under ``dir`` holding
    ``p{partition}.{seq}.page`` files plus a ``manifest.json`` written
    (tmp + rename) when the producer's completion manifest verifies. The
    live registry stays in memory; the on-disk manifest exists so a
    later process can tell a COMPLETE spool from half-written debris.

    Crash safety: a coordinator ``kill -9`` leaves ``*.tmp`` files and
    manifest-less task directories behind. ``_reap_debris`` sweeps both
    on startup (counted in ``reaped_entries`` / stats ``reapedEntries``)
    and re-registers manifest-complete directories as readable, already
    finish-marked (evictable) spools."""

    def __init__(self, directory: str, max_bytes: int = 256 << 20):
        super().__init__(max_bytes)
        self.dir = directory
        os.makedirs(self.dir, exist_ok=True)
        self._reap_debris()

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.dir, task_id.replace("/", "_"))

    def _path(self, task_id: str, partition: int, seq: int) -> str:
        return os.path.join(
            self._task_dir(task_id), f"p{partition}.{seq}.page"
        )

    def _store_page(self, task_id, partition, seq, page):
        path = self._path(task_id, partition, seq)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(page)
        os.replace(tmp, path)  # readers never see a partial file
        return path

    def _load_page(self, handle):
        with open(handle, "rb") as f:
            return f.read()

    def _delete_pages(self, task_id, handles):
        for path in handles:
            try:
                os.remove(path)
            except OSError:
                pass
        d = self._task_dir(task_id)
        try:
            os.remove(os.path.join(d, "manifest.json"))
        except OSError:
            pass
        try:
            os.rmdir(d)  # only if nothing is left in it
        except OSError:
            pass

    def _persist_manifest(self, task_id, query_id, partitions):
        d = self._task_dir(task_id)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump(
                {
                    "taskId": task_id,
                    "queryId": query_id,
                    "partitions": {str(p): c for p, c in partitions.items()},
                },
                f,
            )
        os.replace(tmp, os.path.join(d, "manifest.json"))

    def _reap_debris(self) -> None:
        """Startup sweep. Orphaned ``*.tmp`` files (anywhere) and task
        directories without a landed ``manifest.json`` are deleted;
        directories with one are rehydrated into the registry so their
        data stays readable — and reclaimable via normal eviction."""
        import shutil

        reaped = 0
        for name in sorted(os.listdir(self.dir)):
            path = os.path.join(self.dir, name)
            if not os.path.isdir(path):
                # loose file in the spool root: a torn tmp or a stray
                # page from an older layout — debris either way
                try:
                    os.remove(path)
                    reaped += 1
                except OSError:
                    pass
                continue
            manifest_path = os.path.join(path, "manifest.json")
            if not os.path.isfile(manifest_path):
                shutil.rmtree(path, ignore_errors=True)
                reaped += 1
                continue
            for fn in sorted(os.listdir(path)):
                if fn.endswith(".tmp"):
                    try:
                        os.remove(os.path.join(path, fn))
                        reaped += 1
                    except OSError:
                        pass
            if not self._rehydrate(path, manifest_path):
                shutil.rmtree(path, ignore_errors=True)
                reaped += 1
        self.reaped_entries = reaped

    def _rehydrate(self, task_dir: str, manifest_path: str) -> bool:
        """Re-register one manifest-complete spool directory; False when
        the stored pages don't match the manifest (treated as debris)."""
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
            task_id = manifest["taskId"]
            query_id = manifest["queryId"]
            partitions = {
                int(p): int(c)
                for p, c in (manifest.get("partitions") or {}).items()
            }
        except (OSError, ValueError, KeyError, TypeError):
            return False
        entry = _TaskSpool(task_id, query_id)
        for fn in os.listdir(task_dir):
            if not fn.endswith(".page"):
                continue
            try:
                stem = fn[:-len(".page")]
                p_str, seq_str = stem.lstrip("p").split(".", 1)
                partition, seq = int(p_str), int(seq_str)
            except ValueError:
                return False
            path = os.path.join(task_dir, fn)
            entry.pages.setdefault(partition, []).append((seq, path))
            entry.seqs.add((partition, seq))
            entry.bytes += os.path.getsize(path)
        for p, count in partitions.items():
            if len(entry.pages.get(p, [])) != count:
                return False
        entry.complete = True
        with self._lock:
            self._tasks[task_id] = entry
            self._total_bytes += entry.bytes
        # an inherited spool's query is long gone: evictable immediately
        self.finish_query(query_id)
        return True


def get_spool_store(engine, spool_dir: str = "",
                    max_bytes: Optional[int] = None) -> SpoolStore:
    """The coordinator's spool store, created on first use and pinned on
    the engine. The first spooling query's backend choice (RAM unless
    ``spool_dir`` is set) wins for the process — switching backends
    mid-flight would orphan live queries' spooled data; ``max_bytes`` is
    re-applied per query."""
    store = getattr(engine, "spool_store", None)
    if store is None:
        if spool_dir:
            store = DiskSpoolStore(
                spool_dir, max_bytes if max_bytes is not None else 256 << 20
            )
        else:
            store = MemorySpoolStore(
                max_bytes if max_bytes is not None else 256 << 20
            )
        engine.spool_store = store
    elif max_bytes is not None:
        store.max_bytes = int(max_bytes)
    return store


class SpoolWriter:
    """Worker-side async spooler for one task's output buffer.

    Pages enter via :meth:`offer` (called from ``OutputBuffer.enqueue``,
    off the producer's critical path — a daemon thread drains the queue
    and POSTs each page to the coordinator). :meth:`finish` blocks until
    the queue drains, then publishes the completion manifest; a worker
    dying before ``finish`` leaves the spool incomplete, which reads as
    "not recoverable from spool" — never as a truncated success.
    :meth:`abort` stops the drain and deletes the remote spool, unless
    the manifest already published (the coordinator owns complete spools;
    task cancel/reap must not yank data recovery may be serving).
    """

    def __init__(self, base_uri: str, task_id: str, query_id: str,
                 timeout: float = 10.0, http_retries: int = 3):
        self.uri = f"{base_uri.rstrip('/')}/v1/spool/{task_id}"
        self.task_id = task_id
        self.query_id = query_id
        self.timeout = float(timeout)
        self.http_retries = max(1, int(http_retries))
        self.failed = False  # a page POST was rejected or errored out
        self.completed = False  # manifest accepted by the coordinator
        self.spooled_bytes = 0
        self._counts: dict[int, int] = {}  # partition -> pages offered
        self._q: queue.Queue = queue.Queue()
        self._drained = threading.Event()
        self._aborted = False
        self._finish_lock = threading.Lock()
        self._finishing = False  # a finish() attempt is in flight
        self._finish_wave = threading.Event()  # set when that attempt ends
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    # --- producer side ----------------------------------------------------

    def offer(self, partition: int, page: bytes) -> None:
        if self._aborted or self.failed:
            return
        seq = self._counts.get(partition, 0)
        self._counts[partition] = seq + 1
        self._q.put((partition, seq, page))

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._drained.set()
                return
            partition, seq, page = item
            if self._aborted or self.failed:
                continue
            try:
                resp = self._request(
                    "POST",
                    f"{self.uri}?query={self.query_id}"
                    f"&partition={partition}&seq={seq}",
                    body=page,
                    content_type="application/octet-stream",
                )
                if not (resp or {}).get("accepted"):
                    self.failed = True  # cap-rejected: spool unusable
                else:
                    self.spooled_bytes += len(page)
            except Exception:  # noqa: BLE001 — spooling is best-effort
                self.failed = True

    def _request(self, method: str, uri: str, body: Optional[bytes] = None,
                 content_type: str = "application/json") -> Optional[dict]:
        from trino_tpu.ft.retry import is_retryable
        from trino_tpu.server import auth

        last: Optional[Exception] = None
        for attempt in range(1, self.http_retries + 1):
            try:
                req = urllib.request.Request(
                    uri, data=body, method=method, headers=auth.headers()
                )
                if body is not None:
                    req.add_header("Content-Type", content_type)
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    raw = r.read()
                    return json.loads(raw.decode()) if raw else None
            except Exception as e:  # noqa: BLE001
                last = e
                if not is_retryable(e) or attempt >= self.http_retries:
                    raise
                time.sleep(0.05 * attempt)
        raise last  # pragma: no cover

    # --- completion / teardown --------------------------------------------

    def finish(self, timeout: float = 60.0) -> bool:
        """Drain and publish the manifest. Idempotent; returns whether
        the coordinator verified the spool complete.

        The lock only claims the attempt — the drain wait and the manifest
        PUT run outside it, so concurrent finishers (task completion vs.
        worker drain) park on the attempt's wave event instead of
        serializing behind a mutex held across network I/O. A failed
        attempt clears ``_finishing`` so the next caller retries."""
        deadline = time.monotonic() + timeout
        while True:
            with self._finish_lock:
                if self.completed:
                    return True
                if self._aborted or self.failed:
                    return False
                if not self._finishing:
                    self._finishing = True
                    self._finish_wave.clear()
                    break
                wave = self._finish_wave
            # another caller owns the in-flight attempt: wait it out, then
            # re-check (it may have failed, in which case we retry)
            if not wave.wait(max(0.0, deadline - time.monotonic())):
                return False
        ok = False
        try:
            self._q.put_nowait(None)
            if (
                self._drained.wait(max(0.0, deadline - time.monotonic()))
                and not self.failed
            ):
                try:
                    resp = self._request(
                        "PUT",
                        f"{self.uri}/complete",
                        body=json.dumps(
                            {
                                "queryId": self.query_id,
                                "partitions": {
                                    str(p): c for p, c in self._counts.items()
                                },
                            }
                        ).encode(),
                    )
                    ok = bool((resp or {}).get("complete"))
                except Exception:  # noqa: BLE001
                    ok = False
        finally:
            with self._finish_lock:
                self.completed = ok or self.completed
                self._finishing = False
            self._finish_wave.set()
        return ok

    def abort(self) -> None:
        """Stop spooling and delete remote data — unless the manifest
        already published (complete spools belong to the coordinator's
        query lifecycle, not the producing task's)."""
        if self._aborted:
            return
        self._aborted = True
        self._q.put(None)
        if self.completed:
            return
        try:
            self._request("DELETE", self.uri)
        except Exception:  # noqa: BLE001 — best-effort
            pass
