"""Crash-safe query flight recorder: a bounded on-disk lifecycle journal.

Post-mortem debugging of chaos failures used to mean re-running them:
spans, queryStats, and the QueryManager's retained history all live in
coordinator memory, so a SIGKILL takes the evidence with it. The flight
recorder journals every query's lifecycle events (admission, state
transitions, retries, recovery, completion with queryStats /
operatorStats / error classification) to disk as they happen, in a
format a fresh process can replay.

Format — length-prefixed CRC-checked records in segment files:

    <u32 body_len> <u32 crc32(body)> <body: UTF-8 JSON>

appended to ``flight-{seq:08d}.seg`` under the journal directory. A
segment rolls at ``segment_bytes``; oldest segments are deleted once the
journal exceeds ``max_bytes``. A SIGKILL mid-write tears at most the
final record: replay reads each segment's intact prefix and stops at the
first short or CRC-failing record, so everything already framed survives
(the same torn-tail contract as the PR-14 DiskSpoolStore).

Writes are enqueued (``put_nowait`` — callers may be loop threads, which
must never block; the repo-wide LOOP001 discipline) and drained by one
daemon writer thread that frames, appends, and flushes. ``flush()``
barriers on durability for tests and the read endpoints.

Readers: :func:`replay_dir` (used by ``GET /v1/query/{id}/flight`` and
``scripts/flightdump.py``) needs only the directory — it works against a
journal whose writer process is long dead.
"""

from __future__ import annotations

import json
import os
import queue
import struct
import threading
import time
import zlib
from typing import Iterator, Optional

_HEADER = struct.Struct("<II")
# replay refuses absurd lengths (a torn/corrupt header would otherwise
# read garbage as a giant record); generous vs. any real event body
_MAX_RECORD = 8 << 20
_SEGMENT_PREFIX = "flight-"
_SEGMENT_SUFFIX = ".seg"


def _segment_seq(name: str) -> Optional[int]:
    if not (
        name.startswith(_SEGMENT_PREFIX) and name.endswith(_SEGMENT_SUFFIX)
    ):
        return None
    try:
        return int(name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)])
    except ValueError:
        return None


def _segments(directory: str) -> list[tuple[int, str]]:
    """(seq, path) pairs, oldest first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        seq = _segment_seq(name)
        if seq is not None:
            out.append((seq, os.path.join(directory, name)))
    return sorted(out)


def _read_segment(path: str) -> Iterator[dict]:
    """Decode one segment's intact prefix; stops (silently) at the first
    torn or CRC-failing record — the crash-safety contract."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return
    off = 0
    while off + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, off)
        if length == 0 or length > _MAX_RECORD:
            return  # corrupt header: treat the rest as torn tail
        body = data[off + _HEADER.size: off + _HEADER.size + length]
        if len(body) < length or zlib.crc32(body) & 0xFFFFFFFF != crc:
            return  # short write or bit rot: intact prefix ends here
        try:
            rec = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return
        if isinstance(rec, dict):
            yield rec
        off += _HEADER.size + length


def replay_dir(
    directory: str, query_id: Optional[str] = None
) -> list[dict]:
    """Replay the journal under ``directory`` (all segments, oldest
    first), optionally filtered to one query. Safe against torn tails and
    concurrent writers; needs no :class:`FlightRecorder` instance."""
    out: list[dict] = []
    for _, path in _segments(directory):
        for rec in _read_segment(path):
            if query_id is None or rec.get("queryId") == query_id:
                out.append(rec)
    return out


class FlightRecorder:
    """One journal writer per coordinator process (per directory)."""

    def __init__(
        self,
        directory: str,
        max_bytes: int = 16 << 20,
        segment_bytes: int = 1 << 20,
    ):
        self.directory = directory
        self.max_bytes = max(4096, int(max_bytes))
        self.segment_bytes = max(1024, int(segment_bytes))
        os.makedirs(directory, exist_ok=True)
        # never append to a pre-crash segment: its tail may be torn, and
        # records appended after a tear would be unreachable to replay
        existing = _segments(directory)
        self._seq = (existing[-1][0] + 1) if existing else 0
        self._file = None
        self._file_bytes = 0
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self.records = 0
        self.dropped = 0
        self.segments_deleted = 0
        self._writer = threading.Thread(
            target=self._drain, daemon=True, name="flight-writer"
        )
        self._writer.start()

    # --- write ------------------------------------------------------------

    def record(
        self, query_id: str, event: str, payload: Optional[dict] = None
    ) -> None:
        """Enqueue one event. Never blocks and never raises toward the
        query path (a full disk degrades to dropped counts, not failed
        queries); callers may be event-loop threads."""
        if self._closed:
            return
        rec = {"ts": time.time(), "queryId": query_id, "event": event}
        if payload:
            rec.update(payload)
        try:
            self._q.put_nowait(rec)
        except Exception:  # noqa: BLE001 — unbounded queue; belt+braces
            self.dropped += 1

    def flush(self, timeout: float = 5.0) -> bool:
        """Barrier: True once every event enqueued before this call is
        durable on disk (read endpoints and tests use it)."""
        if self._closed:
            return True
        done = threading.Event()
        self._q.put_nowait(("__flush__", done))
        return done.wait(timeout)

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        self._closed = True
        self._q.put_nowait(None)

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                break
            try:
                if isinstance(item, tuple) and item[0] == "__flush__":
                    self._sync()
                    item[1].set()
                else:
                    self._append(item)
            except Exception:  # noqa: BLE001 — journal loss, not query loss
                self.dropped += 1
        self._sync()
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass

    def _append(self, rec: dict) -> None:
        body = json.dumps(rec, default=str).encode("utf-8")
        frame = _HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body
        f = self._open_segment(len(frame))
        f.write(frame)
        f.flush()
        self._file_bytes += len(frame)
        self.records += 1

    def _sync(self) -> None:
        if self._file is not None:
            try:
                self._file.flush()
                os.fsync(self._file.fileno())
            except OSError:
                pass

    def _open_segment(self, need: int):
        if (
            self._file is not None
            and self._file_bytes + need > self.segment_bytes
        ):
            self._sync()
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        if self._file is None:
            path = os.path.join(
                self.directory,
                f"{_SEGMENT_PREFIX}{self._seq:08d}{_SEGMENT_SUFFIX}",
            )
            self._seq += 1
            self._file = open(path, "ab")
            self._file_bytes = 0
            self._enforce_budget()
        return self._file

    def _enforce_budget(self) -> None:
        """Delete oldest whole segments while the journal exceeds
        max_bytes (the current — newest — segment always survives)."""
        segs = _segments(self.directory)
        total = 0
        sizes = []
        for seq, path in segs:
            try:
                sz = os.path.getsize(path)
            except OSError:
                sz = 0
            sizes.append((seq, path, sz))
            total += sz
        for seq, path, sz in sizes[:-1]:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
                self.segments_deleted += 1
                total -= sz
            except OSError:
                pass

    # --- read -------------------------------------------------------------

    def replay(self, query_id: Optional[str] = None) -> list[dict]:
        return replay_dir(self.directory, query_id)

    def snapshot(self) -> dict:
        segs = _segments(self.directory)
        nbytes = 0
        for _, path in segs:
            try:
                nbytes += os.path.getsize(path)
            except OSError:
                pass
        return {
            "directory": self.directory,
            "segments": len(segs),
            "bytes": nbytes,
            "maxBytes": self.max_bytes,
            "segmentBytes": self.segment_bytes,
            "records": self.records,
            "dropped": self.dropped,
            "segmentsDeleted": self.segments_deleted,
        }


# One recorder per directory per process — lifecycle callers (QueryManager,
# ManagedQuery, HTTP endpoints) share the writer thread and its ordering.
_RECORDERS: dict[str, FlightRecorder] = {}
_RECORDERS_LOCK = threading.Lock()


def get_recorder(
    directory: str,
    max_bytes: int = 16 << 20,
    segment_bytes: int = 1 << 20,
) -> FlightRecorder:
    directory = os.path.abspath(directory)
    with _RECORDERS_LOCK:
        rec = _RECORDERS.get(directory)
        if rec is None or rec._closed:
            rec = _RECORDERS[directory] = FlightRecorder(
                directory, max_bytes=max_bytes, segment_bytes=segment_bytes
            )
        return rec


def replay_known(
    query_id: Optional[str] = None, directory: Optional[str] = None
) -> list[dict]:
    """Replay for the HTTP endpoint. With ``directory`` (the restarted-
    coordinator path: journal on disk, writer process dead) read that
    journal; otherwise flush and replay every recorder this process has
    opened. Blocks on flush — callers must not be loop threads."""
    if directory:
        rec = _RECORDERS.get(os.path.abspath(directory))
        if rec is not None and not rec._closed:
            rec.flush(2.0)
        return replay_dir(directory, query_id)
    with _RECORDERS_LOCK:
        recs = list(_RECORDERS.values())
    out: list[dict] = []
    for rec in recs:
        if not rec._closed:
            rec.flush(2.0)
        out.extend(rec.replay(query_id))
    return out


def for_session(session) -> Optional[FlightRecorder]:
    """The session's recorder per its ``flight_dir`` props ('' = off).
    Best-effort by contract: never raises toward the query path."""
    try:
        directory = str(session.get("flight_dir") or "").strip()
        if not directory:
            return None
        return get_recorder(
            directory,
            max_bytes=int(session.get("flight_max_bytes")),
            segment_bytes=int(session.get("flight_segment_bytes")),
        )
    except Exception:  # noqa: BLE001
        return None
