"""Persistent query history: per-fingerprint observed execution truth.

The observability stack measures everything but — before this module —
remembered nothing across queries: capacities were seeded from static
planner estimates, overflow retries and compile halvings recurred on
every cold variant, and HBM exhaustion was discovered at compile time.
:class:`QueryHistoryStore` closes that loop. It persists, keyed by the
program-cache fingerprint (``planner/canonicalize.py``), the observed
truth a finished query already collected: per-site final capacities with
provenance, padding ratio, overflow retries, compile halvings, flops,
peak HBM, elapsed wall, batch sizes — as EWMA / bounded-sample
aggregates.

Three consumers:

- **seed** — ``exec/fragments.py`` consults an entry's ``capacities``
  (restart-stable site names like ``agg@3#0``) ahead of the static
  planner-stats seeds, so a warm repeat of a query that overflowed or
  halved cold starts at the observed working shapes (provenance
  ``history``) and hits zero retries / zero halvings by construction.
- **admit** — ``server/querymanager.py`` gates admission on the entry's
  observed ``peak_hbm_bytes`` against live device headroom
  (``ingest.hbm_headroom_ok``) before any compile happens.
- **surface** — ``GET /v1/history``, ``system.runtime.history``, and
  ``scripts/prewarm_cache.py`` read :meth:`entries`.

Durability follows the repo-wide idiom: the whole store is one
schema-versioned JSON document written tmp + ``os.replace`` (atomic on
POSIX), entry- AND byte-bounded LRU, and corrupt-file tolerant — a
truncated or garbage file starts the store fresh and counts
``trino_tpu_history_corrupt_recovered_total``. An empty ``path`` keeps
the store purely in-memory (the tier-1 default: no cross-process state).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional

SCHEMA_VERSION = 1
# EWMA weight for scalar aggregates: recent runs dominate (matches the
# latency EWMA in the failure detector), but one outlier can't erase the
# regime
_ALPHA = 0.25
# bounded raw elapsed samples per entry — enough for p50/p90 without
# letting a hot fingerprint grow its record unboundedly
_SAMPLE_CAP = 32
_BATCH_CAP = 8


class HistoryHbmRejected(Exception):
    """Admission rejected a query whose fingerprint's OBSERVED peak HBM
    cannot fit the device — classified EXCEEDED_MEMORY_LIMIT /
    INSUFFICIENT_RESOURCES (errors.py), the same class the compile-time
    failure it preempts would have carried."""

    def __init__(self, fingerprint: str, peak_hbm_bytes: int, limit: int):
        self.fingerprint = fingerprint
        self.peak_hbm_bytes = int(peak_hbm_bytes)
        self.limit = int(limit)
        super().__init__(
            f"query rejected at admission: observed peak HBM "
            f"{self.peak_hbm_bytes} bytes for fingerprint {fingerprint} "
            f"exceeds the device limit {self.limit} bytes"
        )


def _ewma(old: Optional[float], new: float) -> float:
    if old is None:
        return float(new)
    return (1.0 - _ALPHA) * float(old) + _ALPHA * float(new)


def _percentile(xs: list, p: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    return float(ys[min(len(ys) - 1, int(p / 100.0 * len(ys)))])


class QueryHistoryStore:
    """Per-fingerprint observed-stats store with atomic persistence.

    Thread-safe; one instance is shared by every query of an engine that
    resolved the same ``history_dir``. Cross-process concurrent writers
    are safe by construction (tmp + rename never tears the file) and
    additive in the common case: each flush re-reads the file and adopts
    fingerprints it has not seen, so two engines recording disjoint
    workloads into one directory both survive.
    """

    def __init__(
        self,
        path: str = "",
        max_entries: int = 256,
        max_bytes: int = 1 << 20,
    ):
        self.path = path or ""
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(4096, int(max_bytes))
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        self._seq = 0
        self.corrupt_recovered = 0
        self.records = 0
        self.evictions = 0
        if self.path:
            with self._lock:
                self._entries = self._read_disk_locked()

    # --- persistence ------------------------------------------------------

    def _read_disk_locked(self) -> dict[str, dict]:
        """Load the on-disk document; corrupt/alien content starts fresh
        (counted) rather than failing the query that touched history."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            if (
                not isinstance(doc, dict)
                or doc.get("version") != SCHEMA_VERSION
                or not isinstance(doc.get("entries"), dict)
            ):
                raise ValueError("unrecognized history schema")
            entries = {
                str(fp): ent
                for fp, ent in doc["entries"].items()
                if isinstance(ent, dict)
            }
            for ent in entries.values():
                self._seq = max(self._seq, int(ent.get("seq", 0)))
            return entries
        except FileNotFoundError:
            return {}
        except Exception:  # noqa: BLE001 — truncated/garbage/foreign file
            self.corrupt_recovered += 1
            try:
                from trino_tpu.obs.metrics import get_registry

                get_registry().counter(
                    "trino_tpu_history_corrupt_recovered_total"
                ).inc()
            except Exception:  # noqa: BLE001
                pass
            return {}

    def _flush_locked(self) -> None:
        if not self.path:
            return
        doc = {"version": SCHEMA_VERSION, "entries": self._entries}
        tmp = f"{self.path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)  # atomic: readers never see a tear
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _adopt_disk_locked(self) -> None:
        """Concurrent-writer merge: before overwriting the file, adopt
        fingerprints another process flushed since we last read it."""
        if not self.path:
            return
        for fp, ent in self._read_disk_locked().items():
            ours = self._entries.get(fp)
            if ours is None or int(ent.get("count", 0)) > int(
                ours.get("count", 0)
            ):
                self._entries[fp] = ent

    # --- record -----------------------------------------------------------

    def record(self, fingerprint: str, observed: dict) -> None:
        """Fold one finished query's observed stats into the fingerprint's
        aggregate entry and flush. ``observed`` keys (all optional):
        elapsed_ms, rows, overflow_retries, compile_halvings,
        padding_ratio, shuffle_rows, flops, peak_hbm_bytes, batch_size,
        capacities ({stable_site: {value, provenance}}),
        operators ({stable_site: {kind, rows_in, rows_out}})."""
        from trino_tpu.server.eventloop import assert_not_loop_thread

        # record() flushes the JSON document to disk under _lock; callers
        # are query-finalize paths on dispatch workers, never the reactor
        assert_not_loop_thread("QueryHistoryStore.record")
        with self._lock:
            self._adopt_disk_locked()
            self._seq += 1
            ent = self._entries.get(fingerprint)
            if ent is None:
                ent = {"count": 0, "capacities": {}, "elapsed_samples": []}
                self._entries[fingerprint] = ent
            ent["count"] = int(ent.get("count", 0)) + 1
            ent["seq"] = self._seq
            ent["last_ts"] = time.time()
            el = observed.get("elapsed_ms")
            if el is not None:
                ent["elapsed_ms"] = _ewma(ent.get("elapsed_ms"), float(el))
                samples = list(ent.get("elapsed_samples") or [])
                samples.append(round(float(el), 3))
                ent["elapsed_samples"] = samples[-_SAMPLE_CAP:]
            for key in ("rows", "overflow_retries", "compile_halvings"):
                v = observed.get(key)
                if v is not None:
                    ent[key] = int(v)
                    mk = f"max_{key}"
                    ent[mk] = max(int(ent.get(mk, 0)), int(v))
            for key in ("padding_ratio", "shuffle_rows"):
                v = observed.get(key)
                if v is not None:
                    ent[key] = round(_ewma(ent.get(key), float(v)), 4)
            v = observed.get("flops")
            if isinstance(v, (int, float)):
                ent["flops"] = float(v)
            v = observed.get("peak_hbm_bytes")
            if isinstance(v, (int, float)) and v > 0:
                ent["peak_hbm_bytes"] = max(
                    int(ent.get("peak_hbm_bytes", 0)), int(v)
                )
            v = observed.get("batch_size")
            if v is not None:
                sizes = list(ent.get("batch_sizes") or [])
                sizes.append(int(v))
                ent["batch_sizes"] = sizes[-_BATCH_CAP:]
            for site, cap in (observed.get("capacities") or {}).items():
                try:
                    val = int(cap.get("value", 0))
                    prov = str(cap.get("provenance", ""))
                except (AttributeError, TypeError, ValueError):
                    continue
                if val <= 0:
                    continue
                old = ent["capacities"].get(site)
                if old is not None and "+halved" not in prov:
                    # growth is monotone truth (the ladder found this
                    # floor); a halved site's smaller value IS the truth —
                    # the bigger shape failed to compile/allocate
                    val = max(val, int(old.get("value", 0)))
                ent["capacities"][site] = {"value": val, "provenance": prov}
            for site, op in (observed.get("operators") or {}).items():
                # per-operator row flow as EWMAs; reduction_ratio on a
                # partial-agg/exchange site is the seed the mid-query
                # adaptive-execution roadmap item (a) consumes
                try:
                    rin = int(op.get("rows_in", 0) or 0)
                    rout = int(op.get("rows_out", 0) or 0)
                    kind = str(op.get("kind", ""))
                except (AttributeError, TypeError, ValueError):
                    continue
                ops = ent.setdefault("operators", {})
                old = ops.get(site) or {}
                rec = {
                    "kind": kind or old.get("kind", ""),
                    "rows_in": round(_ewma(old.get("rows_in"), float(rin)), 1),
                    "rows_out": round(
                        _ewma(old.get("rows_out"), float(rout)), 1
                    ),
                }
                if rin > 0:
                    # significant digits, not decimal places: a 3/60175
                    # partial-agg reduction must not round to 0.0
                    rec["reduction_ratio"] = float(
                        "%.4g" % _ewma(old.get("reduction_ratio"), rout / rin)
                    )
                elif "reduction_ratio" in old:
                    rec["reduction_ratio"] = old["reduction_ratio"]
                ops[site] = rec
            self.records += 1
            self._evict_locked()
            self._flush_locked()
        try:
            from trino_tpu.obs.metrics import get_registry

            get_registry().counter("trino_tpu_history_records_total").inc()
        except Exception:  # noqa: BLE001
            pass

    def _evict_locked(self) -> None:
        evicted = 0
        while len(self._entries) > self.max_entries:
            self._pop_lru_locked()
            evicted += 1
        # byte bound: the serialized document must fit max_bytes, so even
        # a store of few-but-huge entries stays bounded on disk
        while len(self._entries) > 1 and self._doc_bytes_locked() > self.max_bytes:
            self._pop_lru_locked()
            evicted += 1
        if evicted:
            self.evictions += evicted
            try:
                from trino_tpu.obs.metrics import get_registry

                get_registry().counter(
                    "trino_tpu_history_evictions_total"
                ).inc(evicted)
            except Exception:  # noqa: BLE001
                pass

    def _doc_bytes_locked(self) -> int:
        return len(
            json.dumps({"version": SCHEMA_VERSION, "entries": self._entries})
        )

    def _pop_lru_locked(self) -> None:
        lru = min(
            self._entries, key=lambda fp: int(self._entries[fp].get("seq", 0))
        )
        self._entries.pop(lru, None)

    # --- read -------------------------------------------------------------

    def get(self, fingerprint: str, touch: bool = True) -> Optional[dict]:
        """The fingerprint's aggregate entry (a private copy), bumping its
        LRU recency unless ``touch=False`` (admission peeks must not keep
        an entry alive that no query ever re-runs)."""
        with self._lock:
            ent = self._entries.get(fingerprint)
            if ent is None:
                return None
            if touch:
                self._seq += 1
                ent["seq"] = self._seq
            return json.loads(json.dumps(ent))

    def entries(self) -> list[tuple[str, dict]]:
        """(fingerprint, summary) pairs, most-recently-used first, with
        elapsed percentiles computed from the bounded sample window."""
        with self._lock:
            items = sorted(
                self._entries.items(),
                key=lambda kv: -int(kv[1].get("seq", 0)),
            )
            out = []
            for fp, ent in items:
                s = json.loads(json.dumps(ent))
                samples = s.pop("elapsed_samples", []) or []
                s["elapsed_p50_ms"] = round(_percentile(samples, 50), 3)
                s["elapsed_p90_ms"] = round(_percentile(samples, 90), 3)
                out.append((fp, s))
            return out

    def snapshot(self) -> dict:
        """Store-level stats + entries — the ``GET /v1/history`` body."""
        with self._lock:
            nbytes = self._doc_bytes_locked()
        rows = self.entries()
        return {
            "path": self.path,
            "entries": len(rows),
            "bytes": nbytes,
            "maxEntries": self.max_entries,
            "maxBytes": self.max_bytes,
            "records": self.records,
            "evictions": self.evictions,
            "corruptRecovered": self.corrupt_recovered,
            "fingerprints": [
                dict(fingerprint=fp, **ent) for fp, ent in rows
            ],
        }
