"""Device-level program profiling: XLA cost/memory accounting.

Reference: the coordinator's per-operator CPU accounting
(``operator/OperatorStats.java``) has no analog for a compiled-program
engine — the unit of execution is one XLA program per fragment, so the
profiling signal comes from XLA itself: ``Compiled.cost_analysis()``
(FLOPs, bytes accessed) and ``Compiled.memory_analysis()``
(argument/output/temp/peak HBM) on the AOT-compiled executable.

Both analyses are backend-dependent: CPU returns cost analysis but often
no (or partial) memory analysis, and some backends return ``None`` or
raise outright. Everything here degrades to absent fields — callers must
treat every key as optional.
"""

from __future__ import annotations

import math
from typing import Any, Optional

# Compiled.memory_analysis() attribute -> our snake_case stat key
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)


def _finite(v: Any) -> Optional[float]:
    """Numeric, finite and non-negative — XLA reports -1 for unknown."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    if not math.isfinite(f) or f < 0:
        return None
    return f


def capture_device_stats(compiled) -> Optional[dict]:
    """Extract cost/memory analysis from an AOT-compiled executable.

    Returns a dict of whatever the backend reports — a subset of
    ``flops``, ``bytes_accessed``, ``argument_bytes``, ``output_bytes``,
    ``temp_bytes``, ``generated_code_bytes``, ``peak_hbm_bytes`` — or
    ``None`` when the backend reports nothing at all.
    """
    out: dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent, optional
        ca = None
    # older jax returns a per-device list of dicts, newer a single dict
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = _finite(ca.get("flops"))
        if flops is not None:
            out["flops"] = flops
        ba = _finite(ca.get("bytes accessed"))
        if ba is not None:
            out["bytes_accessed"] = ba
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        ma = None
    if ma is not None:
        for attr, key in _MEMORY_FIELDS:
            v = _finite(getattr(ma, attr, None))
            if v is not None:
                out[key] = int(v)
        peak = _finite(getattr(ma, "peak_memory_in_bytes", None))
        if peak is None and all(
            k in out for k in ("argument_bytes", "output_bytes", "temp_bytes")
        ):
            # conservative upper bound when the backend has no peak
            # estimate: everything the program touches resident at once
            peak = float(
                out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
            )
        if peak is not None:
            out["peak_hbm_bytes"] = int(peak)
    return out or None


def rollup_device_stats(programs: dict[str, dict]) -> dict:
    """Query-level rollup over per-program stats: summed FLOPs/bytes
    weighted by execution count, peak HBM as the max across programs
    (programs run sequentially per query, so concurrent residency is
    bounded by the largest single program)."""
    total_flops = 0.0
    total_bytes = 0.0
    peak = 0
    have_flops = have_bytes = have_peak = False
    for st in programs.values():
        execs = max(1, int(st.get("executions", 1)))
        if "flops" in st:
            have_flops = True
            total_flops += st["flops"] * execs
        if "bytes_accessed" in st:
            have_bytes = True
            total_bytes += st["bytes_accessed"] * execs
        if "peak_hbm_bytes" in st:
            have_peak = True
            peak = max(peak, int(st["peak_hbm_bytes"]))
    out: dict[str, Any] = {"programs_profiled": len(programs)}
    if have_flops:
        out["total_flops"] = total_flops
    if have_bytes:
        out["total_bytes_accessed"] = total_bytes
    if have_peak:
        out["peak_hbm_bytes"] = peak
    return out


def merge_device_stats(target: dict, source: Optional[dict]) -> dict:
    """Merge one executor's ``device_stats_snapshot()['programs']`` (or a
    worker's shipped copy) into an accumulating per-program dict — used by
    the coordinator to combine device stats from many tasks. Cost fields
    describe the compiled program (identical across executions), so they
    overwrite; ``executions``/``compile_ms`` accumulate."""
    for label, st in (source or {}).items():
        if not isinstance(st, dict):
            continue
        ent = target.setdefault(label, {"executions": 0, "compile_ms": 0.0})
        ent["executions"] += int(st.get("executions", 1))
        ent["compile_ms"] = round(
            ent["compile_ms"] + float(st.get("compile_ms", 0.0)), 3
        )
        for k, v in st.items():
            if k not in ("executions", "compile_ms"):
                ent[k] = v
    return target
