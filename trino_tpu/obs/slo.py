"""SLO regression sentinel: history-baselined completion checks.

PR-15's :class:`QueryHistoryStore` keeps per-fingerprint elapsed
percentiles; until now nothing *acted* on them — a warm query that
silently got 4x slower (new data skew, a demoted join tier, a noisy
neighbor) looked healthy on every dashboard. The sentinel closes the
loop at query completion, on the dispatch thread that just recorded
history:

- **absolute SLO** — ``slo_elapsed_ms`` (session prop; 0 = off): any
  completion slower than the target counts
  ``trino_tpu_slo_violations_total``.
- **relative regression** — once a fingerprint's baseline holds at least
  ``slo_min_samples`` elapsed samples, a completion slower than
  ``slo_regression_multiplier`` x the baseline p50 fires a regression
  (severity ``minor``, or ``severe`` past ``slo_severe_multiplier``),
  counted by ``trino_tpu_query_regressions_total{severity}``. A
  subsequent in-bounds completion clears the fingerprint.

Verdicts are returned to the engine (surfaced as
``queryStats.regression``) and retained per fingerprint for
``GET /v1/slo``. Evaluation reads the PRE-run history entry, so the
baseline is never contaminated by the run being judged. Best-effort by
contract: the sentinel must never fail or slow the query that feeds it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional


def _percentile(xs: list, p: float) -> float:
    if not xs:
        return 0.0
    ys = sorted(xs)
    return float(ys[min(len(ys) - 1, int(p / 100.0 * len(ys)))])


class SloSentinel:
    """Thread-safe regression/violation tracker (one per process)."""

    def __init__(self, max_entries: int = 256):
        self._lock = threading.Lock()
        self._regressed: dict[str, dict] = {}
        self._max_entries = max(1, int(max_entries))
        self.violations = 0
        self.regressions = 0
        self.evaluations = 0

    # --- evaluate ---------------------------------------------------------

    def evaluate(
        self,
        session,
        fingerprint: Optional[str],
        elapsed_ms: float,
        history_entry: Optional[dict],
        query_id: Optional[str] = None,
    ) -> Optional[dict]:
        """Judge one completion. Returns the verdict dict attached to
        ``queryStats.regression`` (None = within baseline / cold / off)."""
        try:
            slo_ms = float(session.get("slo_elapsed_ms"))
            reg_mult = float(session.get("slo_regression_multiplier"))
            sev_mult = float(session.get("slo_severe_multiplier"))
            min_samples = int(session.get("slo_min_samples"))
        except (KeyError, TypeError, ValueError):
            return None
        elapsed_ms = float(elapsed_ms)
        verdict: dict[str, Any] = {}
        reg = self._registry()
        with self._lock:
            self.evaluations += 1
        if slo_ms > 0 and elapsed_ms > slo_ms:
            verdict["sloViolation"] = 1
            verdict["sloElapsedMs"] = slo_ms
            with self._lock:
                self.violations += 1
            if reg is not None:
                reg.counter("trino_tpu_slo_violations_total").inc()
        samples = list((history_entry or {}).get("elapsed_samples") or [])
        p50 = _percentile(samples, 50)
        if fingerprint and len(samples) >= min_samples and p50 > 0:
            magnitude = elapsed_ms / p50
            if magnitude >= reg_mult:
                severity = "severe" if magnitude >= sev_mult else "minor"
                verdict.update(
                    regressed=1,
                    severity=severity,
                    magnitude=round(magnitude, 3),
                    baselineP50Ms=round(p50, 3),
                    baselineP90Ms=round(_percentile(samples, 90), 3),
                    baselineSamples=len(samples),
                )
                with self._lock:
                    self.regressions += 1
                    self._regressed[fingerprint] = {
                        "fingerprint": fingerprint,
                        "queryId": query_id,
                        "elapsedMs": round(elapsed_ms, 3),
                        "baselineP50Ms": round(p50, 3),
                        "magnitude": round(magnitude, 3),
                        "severity": severity,
                        "ts": time.time(),
                    }
                    self._evict_locked()
                if reg is not None:
                    reg.counter(
                        "trino_tpu_query_regressions_total",
                        severity=severity,
                    ).inc()
            else:
                # recovered: an in-bounds completion clears the flag
                with self._lock:
                    self._regressed.pop(fingerprint, None)
        if not verdict:
            return None
        verdict["elapsedMs"] = round(elapsed_ms, 3)
        return verdict

    def _evict_locked(self) -> None:
        while len(self._regressed) > self._max_entries:
            oldest = min(
                self._regressed,
                key=lambda fp: self._regressed[fp].get("ts", 0.0),
            )
            self._regressed.pop(oldest, None)

    @staticmethod
    def _registry():
        try:
            from trino_tpu.obs.metrics import get_registry

            return get_registry()
        except Exception:  # noqa: BLE001
            return None

    # --- read -------------------------------------------------------------

    def snapshot(self) -> dict:
        """``GET /v1/slo`` body: currently-regressed fingerprints with
        magnitudes, newest first, plus process counters."""
        with self._lock:
            rows = sorted(
                self._regressed.values(),
                key=lambda r: -float(r.get("ts", 0.0)),
            )
            return {
                "regressed": [dict(r) for r in rows],
                "violations": self.violations,
                "regressions": self.regressions,
                "evaluations": self.evaluations,
            }

    def reset(self) -> None:
        with self._lock:
            self._regressed.clear()
            self.violations = self.regressions = self.evaluations = 0


_SENTINEL = SloSentinel()


def get_sentinel() -> SloSentinel:
    return _SENTINEL
