"""Lightweight structured span tracer.

Spans model the life of a query: the trace id IS the query id, the
root span is the query itself, and children cover planning,
optimization, fragmentation, per-stage scheduling, per-task attempts,
exchange transfers, program trace/compile, and device→host pulls.

Design constraints (per the hot-path rule in the issue):

- **No-op when dark.** ``Tracer.start_span`` returns a shared
  ``_NoopSpan`` singleton when no sink is registered — zero
  allocations, no clock reads, nothing to garbage-collect. Servers
  register an :class:`InMemorySpanSink`; a bare engine run traces
  nothing.
- **No deps.** Plain dataclass + ``itertools.count`` ids; durations
  come from ``time.monotonic()`` (epoch kept only for display).
- **Threads don't inherit context.** The ambient "current span" lives
  in a ``threading.local`` stack, so spans started on the same thread
  nest automatically, but work handed to another thread (query
  dispatch, exchange pulls) or another process (worker tasks over
  HTTP) must carry an explicit ``(trace_id, parent_span_id)`` pair —
  see :func:`format_trace_header` / :func:`parse_trace_header` for the
  ``X-Trino-Trace`` wire form.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

TRACE_HEADER = "X-Trino-Trace"

_ids = itertools.count(1)
# span ids must stay unique across the whole cluster: a timeline is the
# UNION of every node's span dump for one trace, and coordinator and
# worker processes each count from 1
_PROC = uuid.uuid4().hex[:6]


def _next_id(prefix: str) -> str:
    return f"{prefix}{_PROC}-{next(_ids)}"


@dataclass
class Span:
    """One timed unit of work inside a trace."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start_epoch: float
    attrs: Dict[str, Any] = field(default_factory=dict)
    duration_ms: Optional[float] = None
    status: str = "OK"
    _start_mono: float = 0.0
    _tracer: Optional["Tracer"] = None
    _done: bool = False

    def set(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def finish(self, status: str = "OK", **attrs: Any) -> None:
        """Close the span and hand it to the sinks. Idempotent."""
        if self._done:
            return
        self._done = True
        if self.duration_ms is None:
            self.duration_ms = (time.monotonic() - self._start_mono) * 1000.0
        self.status = status
        if attrs:
            self.attrs.update(attrs)
        if self._tracer is not None:
            self._tracer._record(self)

    def context(self) -> Tuple[str, str]:
        return (self.trace_id, self.span_id)

    def to_json(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "startMs": round(self.start_epoch * 1000.0, 1),
            "durationMs": round(self.duration_ms, 3)
            if self.duration_ms is not None
            else None,
            "status": self.status,
            "attrs": self.attrs,
        }

    # context-manager form: ``with tracer.span("plan"): ...``
    def __enter__(self) -> "Span":
        if self._tracer is not None:
            self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._tracer is not None:
            self._tracer._pop(self)
        if exc is not None and not self._done:
            self.finish(status="ERROR", error=f"{type(exc).__name__}: {exc}")
        else:
            self.finish()
        return False


class _NoopSpan:
    """Shared do-nothing span returned when no sink is registered."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def set(self, key: str, value: Any) -> None:
        pass

    def finish(self, status: str = "OK", **attrs: Any) -> None:
        pass

    def context(self) -> None:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Process-global span factory fanning finished spans out to sinks."""

    def __init__(self) -> None:
        self._sinks: List[Any] = []
        self._tls = threading.local()
        self._lock = threading.Lock()

    # -- sink management ------------------------------------------------
    @property
    def enabled(self) -> bool:
        return bool(self._sinks)

    def add_sink(self, sink: Any) -> None:
        with self._lock:
            if sink not in self._sinks:
                self._sinks.append(sink)

    def remove_sink(self, sink: Any) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)

    # -- ambient current-span stack (per thread) ------------------------
    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def context(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) of the ambient span, for cross-thread/HTTP
        handoff; None when dark or outside any span."""
        cur = self.current()
        return cur.context() if cur is not None else None

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()
        elif span in st:  # defensive: unbalanced exit
            st.remove(span)

    # -- span creation --------------------------------------------------
    def start_span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        """Create a live span. Parentage: explicit ``parent_id`` wins,
        else the ambient current span on this thread, else root."""
        if not self._sinks:
            return NOOP_SPAN
        if parent_id is None:
            cur = self.current()
            if cur is not None:
                parent_id = cur.span_id
                if trace_id is None:
                    trace_id = cur.trace_id
        if trace_id is None:
            trace_id = _next_id("t")
        return Span(
            trace_id=trace_id,
            span_id=_next_id("s"),
            parent_id=parent_id,
            name=name,
            start_epoch=time.time(),
            attrs=dict(attrs) if attrs else {},
            _start_mono=time.monotonic(),
            _tracer=self,
        )

    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        """``with tracer.span("optimize"): ...`` — starts, activates as
        the ambient span, and finishes on exit (ERROR on exception)."""
        return self.start_span(name, trace_id, parent_id, attrs)

    def activate(self, span):
        """Re-enter an existing span as the ambient span on THIS thread
        (e.g. the per-query dispatch thread adopting the root span that
        the HTTP handler thread created). Does not finish it on exit."""
        return _Activation(self, span)

    def record(
        self,
        name: str,
        duration_ms: float,
        attrs: Optional[Dict[str, Any]] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        status: str = "OK",
    ) -> None:
        """Emit an already-measured span retroactively (e.g. compile time
        known only after the fact). No-op when dark."""
        if not self._sinks:
            return
        if parent_id is None:
            cur = self.current()
            if cur is not None:
                parent_id = cur.span_id
                if trace_id is None:
                    trace_id = cur.trace_id
        if trace_id is None:
            trace_id = _next_id("t")
        span = Span(
            trace_id=trace_id,
            span_id=_next_id("s"),
            parent_id=parent_id,
            name=name,
            start_epoch=time.time() - duration_ms / 1000.0,
            attrs=dict(attrs) if attrs else {},
            duration_ms=duration_ms,
            _tracer=self,
        )
        span._done = True
        span.status = status
        self._record(span)

    def _record(self, span: Span) -> None:
        for sink in list(self._sinks):
            try:
                sink.record(span)
            except Exception:  # noqa: BLE001 — observability must not fail queries
                pass


class _Activation:
    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: Tracer, span) -> None:
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        if isinstance(self._span, Span):
            self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if isinstance(self._span, Span):
            self._tracer._pop(self._span)
        return False


class InMemorySpanSink:
    """Bounded per-trace span store backing ``/v1/query/{id}/timeline``."""

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 4096):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._traces: "OrderedDict[str, List[Dict[str, Any]]]" = OrderedDict()
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = self._traces[span.trace_id] = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            if len(spans) < self.max_spans_per_trace:
                spans.append(span.to_json())

    def spans_for(self, trace_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


# -- cross-process propagation (X-Trino-Trace header) -------------------

def format_trace_header(ctx: Optional[Tuple[str, str]]) -> Optional[str]:
    """``(trace_id, span_id)`` → ``"{trace_id};{span_id}"``."""
    if not ctx or not ctx[0]:
        return None
    return f"{ctx[0]};{ctx[1]}"


def parse_trace_header(value: Optional[str]) -> Optional[Tuple[str, str]]:
    if not value or ";" not in value:
        return None
    trace_id, _, span_id = value.partition(";")
    if not trace_id or not span_id:
        return None
    return (trace_id.strip(), span_id.strip())


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER
