"""Unified observability: span tracer + metrics registry + surfacing.

Reference: the stats chain ``operator/OperatorStats.java`` →
Driver → Task → Stage → ``execution/QueryStats.java``, surfaced over JMX
and event listeners. Our port keeps the same three altitudes with
TPU-era span names (program trace/lower/compile, device→host pulls,
exchange transfers) instead of per-operator CPU counters:

- :mod:`trino_tpu.obs.trace` — lightweight structured spans. Trace id =
  query id; spans parent across processes via the ``X-Trino-Trace``
  HTTP header. Emission is a no-op unless a sink is registered.
- :mod:`trino_tpu.obs.metrics` — process-global counters, gauges and
  fixed-bucket histograms (no external deps), rendered in Prometheus
  text format at ``GET /v1/metrics`` and embedded as JSON snapshots by
  ``bench.py`` / ``scripts/chaos_smoke.py``.
"""

from trino_tpu.obs.metrics import get_registry
from trino_tpu.obs.trace import InMemorySpanSink, get_tracer

__all__ = ["get_registry", "get_tracer", "InMemorySpanSink"]
