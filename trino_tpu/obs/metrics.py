"""Dependency-free metrics registry: counters, gauges, histograms.

Mirrors the role of the reference engine's JMX beans: a process-global
registry each server exposes at ``GET /v1/metrics`` in Prometheus text
exposition format (``?format=json`` returns :meth:`MetricsRegistry.snapshot`
for embedding in bench/chaos JSON lines).

Histograms use fixed upper-bound buckets (milliseconds by default) so
aggregation across scrapes is exact on counts and approximate on
quantiles (linear interpolation within the bucket) — the standard
Prometheus trade. Exact per-query quantiles (the speculative-execution
straggler signal) come instead from :func:`percentile` over the live
per-stage sibling elapsed lists the coordinator keeps while a stage
runs.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# upper bounds in ms; +inf is implicit
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0, 300000.0,
)


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Exact linear-interpolated percentile (q in [0, 100]) of a small
    sample, e.g. sibling task elapsed within one stage."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return float(vs[0])
    rank = (q / 100.0) * (len(vs) - 1)
    lo = int(math.floor(rank))
    hi = min(lo + 1, len(vs) - 1)
    frac = rank - lo
    return float(vs[lo] + (vs[hi] - vs[lo]) * frac)


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus-style)."""

    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: Sequence[float] = DEFAULT_MS_BUCKETS) -> None:
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # last = +inf
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            i = 0
            for i, ub in enumerate(self.buckets):  # noqa: B007
                if value <= ub:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """Approximate quantile (q in [0, 100]) by interpolating within
        the bucket containing the target rank."""
        with self._lock:
            if self.count == 0:
                return None
            target = (q / 100.0) * self.count
            cum = 0
            lo = 0.0
            for i, ub in enumerate(self.buckets):
                prev = cum
                cum += self.counts[i]
                if cum >= target:
                    if self.counts[i] == 0:
                        return ub
                    frac = (target - prev) / self.counts[i]
                    return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
                lo = ub
            return self.buckets[-1] if self.buckets else None


class MetricsRegistry:
    """Labelled metric families keyed by (name, sorted label items)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._types: Dict[str, str] = {}
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Any] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, Any], factory):
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            existing_kind = self._types.get(name)
            if existing_kind is None:
                self._types[name] = kind
            elif existing_kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing_kind}"
                )
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = factory()
            return m

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
        **labels: Any,
    ) -> Histogram:
        return self._get("histogram", name, labels, lambda: Histogram(buckets))

    def reset(self) -> None:
        with self._lock:
            self._types.clear()
            self._metrics.clear()

    # -- rendering ------------------------------------------------------
    @staticmethod
    def _escape_label_value(v: Any) -> str:
        """Prometheus text-format label escaping: backslash, double quote,
        and line feed must be escaped inside label values (exposition
        format 0.0.4) — fragment labels carry repr()'d program keys that
        can contain quotes."""
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @classmethod
    def _label_str(
        cls, labels: Tuple[Tuple[str, str], ...], extra: str = ""
    ) -> str:
        parts = [f'{k}="{cls._escape_label_value(v)}"' for k, v in labels]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        with self._lock:
            items = sorted(self._metrics.items())
            types = dict(self._types)
        lines: List[str] = []
        seen_type: set = set()
        for (name, labels), metric in items:
            kind = types.get(name, "counter")
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                cum = 0
                lo_labels = labels
                for i, ub in enumerate(metric.buckets):
                    cum += metric.counts[i]
                    ls = self._label_str(lo_labels, f'le="{ub:g}"')
                    lines.append(f"{name}_bucket{ls} {cum}")
                cum += metric.counts[-1]
                ls = self._label_str(lo_labels, 'le="+Inf"')
                lines.append(f"{name}_bucket{ls} {cum}")
                ls = self._label_str(lo_labels)
                lines.append(f"{name}_sum{ls} {metric.sum:g}")
                lines.append(f"{name}_count{ls} {metric.count}")
            else:
                ls = self._label_str(labels)
                lines.append(f"{name}{ls} {metric.value:g}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe dump for bench/chaos output: flat name{labels} keys."""
        with self._lock:
            items = sorted(self._metrics.items())
            types = dict(self._types)
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, labels), metric in items:
            kind = types.get(name, "counter")
            key = name + self._label_str(labels)
            if kind == "histogram":
                out["histograms"][key] = {
                    "count": metric.count,
                    "sum": round(metric.sum, 3),
                    "p50": metric.quantile(50),
                    "p99": metric.quantile(99),
                }
            elif kind == "gauge":
                out["gauges"][key] = metric.value
            else:
                out["counters"][key] = metric.value
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
