"""Retry policies, backoff, and retryable-error classification.

Reference: Trino's ``retry-policy`` session property (NONE / TASK /
QUERY, ``io.trino.execution.RetryPolicy``) plus the standard
exponential-backoff-with-jitter schedule its task retries use
(``faulttolerant/EventDrivenFaultTolerantQueryScheduler``). Jitter here
is *deterministic* (seeded by attempt index) so chaos runs replay with
identical timing decisions.
"""

from __future__ import annotations

import random
import socket
import urllib.error
from typing import Optional


class RetryPolicy:
    """String constants (mirrors ``io.trino.execution.RetryPolicy``)."""

    NONE = "NONE"
    TASK = "TASK"
    QUERY = "QUERY"

    @classmethod
    def of(cls, value) -> str:
        v = str(value or cls.NONE).upper()
        if v not in (cls.NONE, cls.TASK, cls.QUERY):
            raise ValueError(f"unknown retry_policy: {value!r}")
        return v

    @classmethod
    def from_session(cls, session) -> str:
        try:
            return cls.of(session.get("retry_policy"))
        except KeyError:
            return cls.NONE


class TaskFailure(Exception):
    """A remote task failed; carries the worker's retryable
    classification so the query-level error is typed correctly."""

    def __init__(self, task_id: str, node_id: str, error: Optional[str],
                 retryable: bool):
        self.task_id = task_id
        self.node_id = node_id
        self.error = error
        self.retryable = retryable
        super().__init__(
            f"task {task_id} failed on {node_id}"
            f" ({'retryable' if retryable else 'fatal'}): {error}"
        )


class TaskRetriesExhausted(TaskFailure):
    """Every allowed attempt of one task failed. Not task-retryable by
    construction (the budget is spent) but QUERY retry may still apply."""

    def __init__(self, task_id: str, node_id: str, error: Optional[str],
                 attempts: int):
        super().__init__(task_id, node_id, error, retryable=False)
        self.attempts = attempts
        self.args = (
            f"task {task_id} failed after {attempts} attempts"
            f" (last on {node_id}): {error}",
        )


class Backoff:
    """Exponential backoff with bounded, deterministic jitter.

    ``delay(attempt)`` for attempt=1,2,3... grows initial * 2^(attempt-1)
    up to ``max_delay``, scaled by a jitter factor in [0.5, 1.0] drawn
    from (seed, attempt) so replays sleep identically.
    """

    def __init__(
        self,
        initial_ms: float = 100.0,
        max_ms: float = 2000.0,
        seed: int = 0,
    ):
        self.initial_ms = max(0.0, float(initial_ms))
        self.max_ms = max(self.initial_ms, float(max_ms))
        self.seed = int(seed)

    @classmethod
    def from_session(cls, session) -> "Backoff":
        try:
            return cls(
                initial_ms=float(session.get("retry_initial_delay_ms")),
                max_ms=float(session.get("retry_max_delay_ms")),
                seed=int(session.get("fault_injection_seed")),
            )
        except KeyError:
            return cls()

    def delay(self, attempt: int) -> float:
        """Seconds to sleep before retry number ``attempt`` (1-based)."""
        if self.initial_ms <= 0:
            return 0.0
        base = min(self.max_ms, self.initial_ms * (2 ** max(0, attempt - 1)))
        jitter = 0.5 + 0.5 * random.Random(f"{self.seed}:backoff:{attempt}").random()
        return base * jitter / 1000.0


def is_retryable(exc: BaseException) -> bool:
    """Classify an exception for the retry policies.

    Retryable: injected faults, network/timeout errors (the request may
    succeed against a different worker or on a later attempt), and
    node-local memory exhaustion (another node may have headroom).
    Fatal: everything deterministic — SQL/semantic errors, capacity-
    retry exhaustion (same data ⇒ same growth path on any node), and
    exhausted task-retry budgets.
    """
    flagged = getattr(exc, "retryable", None)
    if flagged is not None:
        return bool(flagged)
    if isinstance(
        exc,
        (
            urllib.error.URLError,  # includes HTTPError; connection refused
            ConnectionError,
            TimeoutError,
            socket.timeout,
            OSError,
        ),
    ):
        return True
    try:
        from trino_tpu.memory import ExceededMemoryLimitError

        if isinstance(exc, ExceededMemoryLimitError):
            return True
    except ImportError:  # pragma: no cover
        pass
    return False
