"""Retry policies, backoff, and retryable-error classification.

Reference: Trino's ``retry-policy`` session property (NONE / TASK /
QUERY, ``io.trino.execution.RetryPolicy``) plus the standard
exponential-backoff-with-jitter schedule its task retries use
(``faulttolerant/EventDrivenFaultTolerantQueryScheduler``). Jitter here
is *deterministic* (seeded by attempt index) so chaos runs replay with
identical timing decisions.
"""

from __future__ import annotations

import random
import socket
import urllib.error
from typing import Optional


class RetryPolicy:
    """String constants (mirrors ``io.trino.execution.RetryPolicy``)."""

    NONE = "NONE"
    TASK = "TASK"
    QUERY = "QUERY"

    @classmethod
    def of(cls, value) -> str:
        v = str(value or cls.NONE).upper()
        if v not in (cls.NONE, cls.TASK, cls.QUERY):
            raise ValueError(f"unknown retry_policy: {value!r}")
        return v

    @classmethod
    def from_session(cls, session) -> str:
        try:
            return cls.of(session.get("retry_policy"))
        except KeyError:
            return cls.NONE


class TaskFailure(Exception):
    """A remote task failed; carries the worker's retryable
    classification so the query-level error is typed correctly."""

    def __init__(self, task_id: str, node_id: str, error: Optional[str],
                 retryable: bool):
        self.task_id = task_id
        self.node_id = node_id
        self.error = error
        self.retryable = retryable
        super().__init__(
            f"task {task_id} failed on {node_id}"
            f" ({'retryable' if retryable else 'fatal'}): {error}"
        )


class TaskRetriesExhausted(TaskFailure):
    """Every allowed attempt of one task failed. Not task-retryable by
    construction (the budget is spent) but QUERY retry may still apply."""

    def __init__(self, task_id: str, node_id: str, error: Optional[str],
                 attempts: int):
        super().__init__(task_id, node_id, error, retryable=False)
        self.attempts = attempts
        self.args = (
            f"task {task_id} failed after {attempts} attempts"
            f" (last on {node_id}): {error}",
        )


class SpeculationConfig:
    """Straggler-detection knobs for hedged task execution.

    A running attempt is flagged once ``elapsed > max(floor_ms,
    multiplier * p99(completed sibling elapsed))``, and only after
    ``min_completed`` siblings of the same stage have finished (the
    quorum keeps the very first finisher from branding everyone else a
    straggler). Only meaningful under ``retry_policy=TASK`` — hedging
    rides the same re-dispatch-over-retained-buffers machinery.
    """

    def __init__(
        self,
        enabled: bool = False,
        floor_ms: float = 500.0,
        multiplier: float = 2.0,
        max_fraction: float = 0.25,
        min_completed: int = 1,
    ):
        self.enabled = bool(enabled)
        self.floor_ms = max(0.0, float(floor_ms))
        self.multiplier = max(1.0, float(multiplier))
        self.max_fraction = max(0.0, float(max_fraction))
        self.min_completed = max(1, int(min_completed))

    @classmethod
    def from_session(cls, session) -> "SpeculationConfig":
        try:
            return cls(
                enabled=bool(session.get("speculation")),
                floor_ms=float(session.get("speculation_floor_ms")),
                multiplier=float(session.get("speculation_multiplier")),
                max_fraction=float(session.get("speculation_max_fraction")),
            )
        except (KeyError, TypeError, ValueError):
            return cls()

    def budget(self, total_tasks: int) -> int:
        """Max concurrent speculative attempts for a query with
        ``total_tasks`` planned tasks (at least 1 when enabled)."""
        if not self.enabled:
            return 0
        return max(1, int(self.max_fraction * max(0, total_tasks)))

    def threshold_ms(self, completed_elapsed_ms) -> Optional[float]:
        """Straggler threshold given completed siblings' elapsed times,
        or None while the quorum is unmet (never hedge blind)."""
        if not self.enabled or len(completed_elapsed_ms) < self.min_completed:
            return None
        from trino_tpu.obs.metrics import percentile

        p99 = percentile(completed_elapsed_ms, 99.0) or 0.0
        return max(self.floor_ms, self.multiplier * p99)


class SpoolConfig:
    """Spooled-exchange knobs — the recovery tier between TASK and QUERY.

    The recovery ladder: a failed *attempt* retries on another node
    (TASK); a straggler gets hedged (speculation); a *dead producer's*
    finished output is served from the spool or, if un-spooled, the
    producer alone is re-executed via lineage (this tier); only when all
    of that is exhausted does the whole statement re-run (QUERY). Spooling
    only engages under ``retry_policy=TASK`` — it extends the retained-
    buffer exchange that policy already materializes.
    """

    def __init__(self, enabled: bool = False, spool_dir: str = "",
                 max_bytes: int = 256 << 20):
        self.enabled = bool(enabled)
        self.spool_dir = str(spool_dir or "")
        self.max_bytes = max(0, int(max_bytes))

    @classmethod
    def from_session(cls, session) -> "SpoolConfig":
        try:
            return cls(
                enabled=bool(session.get("exchange_spooling")),
                spool_dir=str(session.get("spool_dir") or ""),
                max_bytes=int(session.get("spool_max_bytes")),
            )
        except (KeyError, TypeError, ValueError):
            return cls()


class Backoff:
    """Exponential backoff with bounded, deterministic jitter.

    ``delay(attempt)`` for attempt=1,2,3... grows initial * 2^(attempt-1)
    up to ``max_delay``, scaled by a jitter factor in [0.5, 1.0] drawn
    from (seed, attempt) so replays sleep identically.
    """

    def __init__(
        self,
        initial_ms: float = 100.0,
        max_ms: float = 2000.0,
        seed: int = 0,
    ):
        self.initial_ms = max(0.0, float(initial_ms))
        self.max_ms = max(self.initial_ms, float(max_ms))
        self.seed = int(seed)

    @classmethod
    def from_session(cls, session) -> "Backoff":
        try:
            return cls(
                initial_ms=float(session.get("retry_initial_delay_ms")),
                max_ms=float(session.get("retry_max_delay_ms")),
                seed=int(session.get("fault_injection_seed")),
            )
        except KeyError:
            return cls()

    def delay(self, attempt: int) -> float:
        """Seconds to sleep before retry number ``attempt`` (1-based)."""
        if self.initial_ms <= 0:
            return 0.0
        base = min(self.max_ms, self.initial_ms * (2 ** max(0, attempt - 1)))
        jitter = 0.5 + 0.5 * random.Random(f"{self.seed}:backoff:{attempt}").random()
        return base * jitter / 1000.0


def is_retryable(exc: BaseException) -> bool:
    """Classify an exception for the retry policies.

    Retryable: injected faults, network/timeout errors (the request may
    succeed against a different worker or on a later attempt), and
    node-local memory exhaustion (another node may have headroom).
    Fatal: everything deterministic — SQL/semantic errors, capacity-
    retry exhaustion (same data ⇒ same growth path on any node), and
    exhausted task-retry budgets.
    """
    flagged = getattr(exc, "retryable", None)
    if flagged is not None:
        return bool(flagged)
    if isinstance(
        exc,
        (
            urllib.error.URLError,  # includes HTTPError; connection refused
            ConnectionError,
            TimeoutError,
            socket.timeout,
            OSError,
        ),
    ):
        return True
    try:
        from trino_tpu.memory import ExceededMemoryLimitError

        if isinstance(exc, ExceededMemoryLimitError):
            return True
    except ImportError:  # pragma: no cover
        pass
    return False
