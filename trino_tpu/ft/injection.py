"""Deterministic, seed-driven fault injection.

Reference: ``io.trino.execution.FailureInjector`` (the hook Trino's
fault-tolerant-execution tests use to fail tasks at controlled points).
Here every injection *site* gets one pseudo-random draw derived purely
from ``(seed, site)`` — not from call order, wall clock, or process — so
a failing run replays exactly: the same seed and the same site string
always make the same decision, on the coordinator or on any worker.

Site strings deliberately exclude per-run identifiers (query counters,
host:port): a site is ``kind:fragment.partition[:attempt]``-shaped, so a
retried attempt (new attempt suffix) gets a fresh draw while a re-run of
the whole scenario reproduces the original faults bit-for-bit.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Optional

log = logging.getLogger("trino_tpu.ft")

# keep the replay log bounded: chaos runs can draw thousands of sites
MAX_EVENTS = 2048


class InjectedFault(Exception):
    """A fault planted by the injector. Always retryable by definition:
    it models a crash/drop of otherwise-healthy work."""

    retryable = True

    def __init__(self, site: str, draw: float, kind: str):
        self.site = site
        self.draw = draw
        self.kind = kind
        super().__init__(
            f"injected {kind} fault at {site} (draw={draw:.6f})"
        )


class FaultInjector:
    """Seed-keyed fault decisions for task crashes and HTTP chaos.

    ``maybe_*`` methods draw deterministically per site and either return
    (no fault) or raise :class:`InjectedFault` / sleep. Every *injected*
    fault is recorded in :attr:`events` with its site and draw so a
    failure can be replayed from the log alone.
    """

    def __init__(
        self,
        seed: int = 0,
        task_crash_p: float = 0.0,
        http_drop_p: float = 0.0,
        http_delay_ms: float = 0.0,
        salt: Any = 0,
        slow_workers: str = "",
        task_stall_ms: float = 0.0,
        task_slow_factor: float = 1.0,
        worker_exit_node: str = "",
        worker_exit_site: str = "",
        worker_exit_delay_ms: float = 0.0,
    ):
        self.seed = int(seed)
        self.salt = salt  # varies per query attempt under QUERY retry
        self.task_crash_p = float(task_crash_p)
        self.http_drop_p = float(http_drop_p)
        self.http_delay_ms = float(http_delay_ms)
        # worker-death fault: after a task at "task:{worker_exit_site}"
        # finishes on a matching node ("" = any), the worker process
        # os._exit()s — a deterministic stand-in for SIGKILL
        self.worker_exit_node = str(worker_exit_node or "")
        self.worker_exit_site = str(worker_exit_site or "")
        self.worker_exit_delay_ms = max(0.0, float(worker_exit_delay_ms))
        # delay faults: which nodes run slow ("" = all), and how — a fixed
        # pre-execute stall and/or a multiplicative execution slowdown
        self.slow_workers = frozenset(
            w.strip() for w in str(slow_workers or "").split(",") if w.strip()
        )
        self.task_stall_ms = float(task_stall_ms)
        self.task_slow_factor = max(1.0, float(task_slow_factor))
        self.events: list[dict] = []
        self.dropped_events = 0
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    # --- construction -----------------------------------------------------

    @classmethod
    def from_session(cls, session) -> Optional["FaultInjector"]:
        """Injector for a session, or None when no fault is configured
        (the common case: zero overhead on the happy path)."""
        try:
            crash_p = float(session.get("fault_task_crash_p"))
            drop_p = float(session.get("fault_http_drop_p"))
            delay_ms = float(session.get("fault_http_delay_ms"))
            stall_ms = float(session.get("fault_task_stall_ms"))
            slow_factor = float(session.get("fault_task_slow_factor"))
            exit_site = str(session.get("fault_worker_exit_site") or "")
            if (
                crash_p <= 0
                and drop_p <= 0
                and delay_ms <= 0
                and stall_ms <= 0
                and slow_factor <= 1.0
                and not exit_site
            ):
                return None
            return cls(
                seed=int(session.get("fault_injection_seed")),
                task_crash_p=crash_p,
                http_drop_p=drop_p,
                http_delay_ms=delay_ms,
                salt=session.properties.get("fault_attempt_salt", 0),
                slow_workers=str(session.get("fault_slow_workers")),
                task_stall_ms=stall_ms,
                task_slow_factor=slow_factor,
                worker_exit_node=str(
                    session.get("fault_worker_exit_node") or ""
                ),
                worker_exit_site=exit_site,
                worker_exit_delay_ms=float(
                    session.get("fault_worker_exit_delay_ms")
                ),
            )
        except (KeyError, TypeError, ValueError):
            return None

    # --- draws ------------------------------------------------------------

    def draw(self, site: str) -> float:
        """The deterministic uniform draw for a site: a function of
        (seed, salt, site) only. ``random.Random`` seeds strings via
        SHA-512 (version-2 seeding), so the value is stable across
        processes and interpreter restarts."""
        return random.Random(f"{self.seed}/{self.salt}:{site}").random()

    def _record(self, site: str, kind: str, draw: float) -> None:
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            if len(self.events) < MAX_EVENTS:
                self.events.append(
                    {"site": site, "kind": kind, "draw": round(draw, 6)}
                )
            else:
                self.dropped_events += 1
        log.warning("fault injected: kind=%s site=%s draw=%.6f", kind, site, draw)

    @property
    def total_injected(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def maybe_crash_task(self, site: str) -> None:
        """Task-loop injection point: raises with ``task_crash_p``."""
        if self.task_crash_p <= 0:
            return
        d = self.draw(site)
        if d < self.task_crash_p:
            self._record(site, "task-crash", d)
            raise InjectedFault(site, d, "task-crash")

    def maybe_drop_http(self, site: str) -> None:
        """HTTP injection point: raises (the request never leaves) with
        ``http_drop_p``, modelling a dropped connection."""
        if self.http_drop_p <= 0:
            return
        d = self.draw(site)
        if d < self.http_drop_p:
            self._record(site, "http-drop", d)
            raise InjectedFault(site, d, "http-drop")

    def delay_http(self, site: str) -> None:
        """Slow-network injection: constant deterministic delay before a
        request (chaos tests shrink the configurable timeouts to turn
        this into timeout coverage)."""
        if self.http_delay_ms <= 0:
            return
        self._record(site, "http-delay", self.http_delay_ms / 1000.0)
        time.sleep(self.http_delay_ms / 1000.0)

    # --- delay faults (straggler manufacturing) ---------------------------

    def is_slow_node(self, node_id: Optional[str]) -> bool:
        """Does a delay fault target this node? An empty ``slow_workers``
        list means every node is slow (single-node chaos convenience)."""
        if self.task_stall_ms <= 0 and self.task_slow_factor <= 1.0:
            return False
        if not self.slow_workers:
            return True
        return node_id is not None and node_id in self.slow_workers

    def stall_task(self, site: str, node_id: Optional[str]) -> None:
        """Fixed pre-execute stall on targeted nodes. Recorded per site so
        chaos runs replay the same wall-clock shape."""
        if self.task_stall_ms <= 0 or not self.is_slow_node(node_id):
            return
        self._record(site, "task-stall", self.task_stall_ms / 1000.0)
        time.sleep(self.task_stall_ms / 1000.0)

    def slow_task(self, site: str, node_id: Optional[str],
                  execute_s: float) -> None:
        """Multiplicative slowdown: the worker measured ``execute_s`` of
        real execution; sleep the remainder so the attempt takes
        ``task_slow_factor`` times as long end to end. Applied *before*
        the result is emitted, so a speculative cancel still aborts the
        output buffer of a genuinely-10x-slow attempt."""
        if self.task_slow_factor <= 1.0 or not self.is_slow_node(node_id):
            return
        extra_s = max(0.0, execute_s) * (self.task_slow_factor - 1.0)
        if extra_s <= 0:
            return
        self._record(site, "task-slow", round(extra_s, 6))
        time.sleep(extra_s)

    def http_site(self, op: str, target: str, attempt: int) -> str:
        """Canonical HTTP site string. ``target`` must already be free of
        per-run identifiers (ports, query counters)."""
        return f"http:{op}:{target}:t{attempt}"

    # --- worker-death faults ----------------------------------------------

    def should_exit_worker(self, site: str, node_id: Optional[str]) -> bool:
        if not self.worker_exit_site:
            return False
        if site != f"task:{self.worker_exit_site}":
            return False
        if self.worker_exit_node and node_id != self.worker_exit_node:
            return False
        return True

    def maybe_exit_worker(self, site: str, node_id: Optional[str]) -> None:
        """Kill this worker process (``os._exit`` — no cleanup, no spool
        flush beyond what already happened) when ``site`` matches the
        configured fault point. Called after a task's terminal-state
        bookkeeping, so the death lands exactly once the fault-site task
        FINISHED; ``worker_exit_delay_ms`` lets the coordinator observe
        that state before the node vanishes. Fires at most once per
        process."""
        if not self.should_exit_worker(site, node_id):
            return
        if _worker_exit_fired.is_set():
            return
        _worker_exit_fired.set()
        self._record(site, "worker-exit", self.worker_exit_delay_ms / 1000.0)
        delay_s = self.worker_exit_delay_ms / 1000.0

        def _die():
            if delay_s > 0:
                time.sleep(delay_s)
            import os

            os._exit(137)  # SIGKILL-grade: skip atexit, flushes, finally

        threading.Thread(target=_die, daemon=True).start()


# process-wide: one injected death per worker process, even across tasks
_worker_exit_fired = threading.Event()


def injection_properties(
    seed: int,
    task_crash_p: float = 0.0,
    http_drop_p: float = 0.0,
    http_delay_ms: float = 0.0,
    slow_workers: str = "",
    task_stall_ms: float = 0.0,
    task_slow_factor: float = 1.0,
    worker_exit_node: str = "",
    worker_exit_site: str = "",
    worker_exit_delay_ms: float = 0.0,
) -> dict:
    """Session-property dict enabling injection (test/CLI convenience)."""
    return {
        "fault_injection_seed": seed,
        "fault_task_crash_p": task_crash_p,
        "fault_http_drop_p": http_drop_p,
        "fault_http_delay_ms": http_delay_ms,
        "fault_slow_workers": slow_workers,
        "fault_task_stall_ms": task_stall_ms,
        "fault_task_slow_factor": task_slow_factor,
        "fault_worker_exit_node": worker_exit_node,
        "fault_worker_exit_site": worker_exit_site,
        "fault_worker_exit_delay_ms": worker_exit_delay_ms,
    }


def task_site(task_id: str) -> str:
    """Injection site for a worker task, stripped of the per-run query
    counter: ``cq7.3.0r1`` -> ``task:3.0r1`` (fragment.partition+attempt),
    so draws replay across runs and differ across retry attempts."""
    parts = task_id.split(".")
    return "task:" + ".".join(parts[-2:]) if len(parts) >= 2 else f"task:{task_id}"
