"""Fault-tolerant execution: deterministic fault injection + retry policies.

Reference: Trino's later fault-tolerant execution ("Tardigrade",
``core/trino-main/.../execution/scheduler/faulttolerant/``) — retry
policies NONE / TASK / QUERY over materialized (spooled) exchanges — and
the chaos-style ``FailureInjector`` used by its test harness
(``io.trino.execution.FailureInjector``). v356 itself has no mid-query
retry; this subsystem is the cluster-level robustness layer the ROADMAP's
preemptible-slice north star requires.
"""

from trino_tpu.ft.injection import (
    FaultInjector,
    InjectedFault,
    injection_properties,
)
from trino_tpu.ft.retry import (
    Backoff,
    RetryPolicy,
    TaskFailure,
    TaskRetriesExhausted,
    is_retryable,
)

__all__ = [
    "Backoff",
    "FaultInjector",
    "InjectedFault",
    "RetryPolicy",
    "TaskFailure",
    "TaskRetriesExhausted",
    "injection_properties",
    "is_retryable",
]
