"""Parquet reader/writer built from scratch (no external libraries).

Reference: ``lib/trino-parquet`` — a from-scratch reader with row-group
pruning and dictionary/RLE decoding (``parquet/reader/ParquetReader.java:65``,
``nextBatch:161``, ``parquet/predicate/``). Matching that design here:

- Thrift *compact protocol* decoding/encoding for the footer and page
  headers (the only wire metadata format Parquet uses).
- Hot byte work in C++ (native/columnar.cpp): snappy codec, RLE/bit-packed
  hybrid runs (definition levels + dictionary indices); NumPy handles
  PLAIN fixed-width ranges zero-copy.
- Row-group ``Statistics`` surface as (min, max, has_null) for TupleDomain
  pruning — the same shape the file connector's stripe stats use.

Supported surface (flat schemas): BOOLEAN, INT32, INT64, FLOAT, DOUBLE,
BYTE_ARRAY (UTF8 -> dictionary varchar), DATE, TIMESTAMP micros, DECIMAL
over INT32/INT64; PLAIN + RLE/PLAIN dictionary encodings; UNCOMPRESSED,
SNAPPY and GZIP codecs; optional (nullable) and required fields.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Any, BinaryIO, Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary
from trino_tpu.native import (
    parquet_rle_decode,
    parquet_rle_encode,
    snappy_compress,
    snappy_decompress,
)

MAGIC = b"PAR1"

# parquet physical types
BOOLEAN, INT32, INT64, INT96, FLOAT, DOUBLE, BYTE_ARRAY, FLBA = range(8)
# encodings
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE, ENC_RLE_DICT = 0, 2, 3, 8
# codecs
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
# converted types
CT_UTF8, CT_DECIMAL, CT_DATE = 0, 5, 6
CT_TIMESTAMP_MILLIS, CT_TIMESTAMP_MICROS = 9, 10
# page types
PAGE_DATA, PAGE_DICT = 0, 2


# === thrift compact protocol ================================================


class _TReader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not (b & 0x80):
                return out
            shift += 7

    def zigzag(self) -> int:
        u = self.varint()
        return (u >> 1) ^ -(u & 1)

    def read_binary(self) -> bytes:
        n = self.varint()
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def skip(self, ttype: int) -> None:
        if ttype in (1, 2):
            return
        if ttype == 3:
            self.pos += 1
        elif ttype in (4, 5, 6):
            self.varint()
        elif ttype == 7:
            self.pos += 8
        elif ttype == 8:
            self.read_binary()
        elif ttype in (9, 10):
            size, etype = self.list_header()
            for _ in range(size):
                self.skip(etype)
        elif ttype == 12:
            self.skip_struct()
        else:
            raise ValueError(f"cannot skip thrift type {ttype}")

    def skip_struct(self) -> None:
        for _fid, ftype in self.fields():
            self.skip(ftype)

    def fields(self):
        """Yield (field_id, type) until STOP; caller must consume values."""
        fid = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            if b == 0:
                return
            delta = b >> 4
            ftype = b & 0x0F
            fid = fid + delta if delta else self.zigzag()
            yield fid, ftype

    def list_header(self) -> tuple[int, int]:
        b = self.data[self.pos]
        self.pos += 1
        size = b >> 4
        etype = b & 0x0F
        if size == 15:
            size = self.varint()
        return size, etype


class _TWriter:
    def __init__(self):
        self.out = bytearray()
        self._last_fid = [0]

    def varint(self, v: int) -> None:
        while v >= 0x80:
            self.out.append((v & 0x7F) | 0x80)
            v >>= 7
        self.out.append(v)

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, fid: int, ftype: int) -> None:
        delta = fid - self._last_fid[-1]
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ftype)
        else:
            self.out.append(ftype)
            self.zigzag(fid)
        self._last_fid[-1] = fid

    def i32(self, fid: int, v: int) -> None:
        self.field(fid, 5)
        self.zigzag(v)

    def i64(self, fid: int, v: int) -> None:
        self.field(fid, 6)
        self.zigzag(v)

    def binary(self, fid: int, v: bytes) -> None:
        self.field(fid, 8)
        self.varint(len(v))
        self.out += v

    def begin_struct(self, fid: Optional[int] = None) -> None:
        if fid is not None:
            self.field(fid, 12)
        self._last_fid.append(0)

    def end_struct(self) -> None:
        self.out.append(0)
        self._last_fid.pop()

    def list_begin(self, fid: int, etype: int, size: int) -> None:
        self.field(fid, 9)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append((15 << 4) | etype)
            self.varint(size)


# === metadata model =========================================================


@dataclasses.dataclass
class ParquetColumn:
    name: str
    physical: int
    converted: Optional[int] = None
    optional: bool = True
    scale: int = 0
    precision: int = 0

    def sql_type(self) -> T.SqlType:
        if self.converted == CT_DECIMAL:
            return T.decimal(self.precision or 18, self.scale)
        if self.converted == CT_DATE:
            return T.DATE
        if self.converted in (CT_TIMESTAMP_MILLIS, CT_TIMESTAMP_MICROS):
            return T.TIMESTAMP
        if self.physical == BOOLEAN:
            return T.BOOLEAN
        if self.physical == INT32:
            return T.INTEGER
        if self.physical == INT64:
            return T.BIGINT
        if self.physical == FLOAT:
            return T.REAL
        if self.physical == DOUBLE:
            return T.DOUBLE
        if self.physical == BYTE_ARRAY:
            return T.VARCHAR
        raise ValueError(f"unsupported parquet type {self.physical}")


@dataclasses.dataclass
class ColumnChunkMeta:
    column: ParquetColumn
    codec: int
    num_values: int
    data_page_offset: int
    dictionary_page_offset: Optional[int]
    total_compressed_size: int
    stats_min: Optional[bytes] = None
    stats_max: Optional[bytes] = None
    null_count: Optional[int] = None


@dataclasses.dataclass
class RowGroupMeta:
    num_rows: int
    columns: list[ColumnChunkMeta]


@dataclasses.dataclass
class FileMeta:
    num_rows: int
    schema: list[ParquetColumn]
    row_groups: list[RowGroupMeta]


def _parse_schema_element(r: _TReader) -> dict:
    out: dict[str, Any] = {}
    for fid, ftype in r.fields():
        if fid == 1:
            out["type"] = r.zigzag()
        elif fid == 2:
            out["type_length"] = r.zigzag()
        elif fid == 3:
            out["repetition"] = r.zigzag()
        elif fid == 4:
            out["name"] = r.read_binary().decode()
        elif fid == 5:
            out["num_children"] = r.zigzag()
        elif fid == 6:
            out["converted"] = r.zigzag()
        elif fid == 7:
            out["scale"] = r.zigzag()
        elif fid == 8:
            out["precision"] = r.zigzag()
        else:
            r.skip(ftype)
    return out


def _parse_statistics(r: _TReader) -> dict:
    out: dict[str, Any] = {}
    for fid, ftype in r.fields():
        if fid == 1:
            out.setdefault("max", r.read_binary())
        elif fid == 2:
            out.setdefault("min", r.read_binary())
        elif fid == 3:
            out["null_count"] = r.zigzag()
        elif fid == 5:
            out["max"] = r.read_binary()
        elif fid == 6:
            out["min"] = r.read_binary()
        else:
            r.skip(ftype)
    return out


def _parse_column_meta(r: _TReader, schema_by_name: dict) -> ColumnChunkMeta:
    vals: dict[str, Any] = {}
    for fid, ftype in r.fields():
        if fid == 3:
            size, _ = r.list_header()
            parts = [r.read_binary().decode() for _ in range(size)]
            vals["path"] = parts[-1] if parts else ""
        elif fid == 4:
            vals["codec"] = r.zigzag()
        elif fid == 5:
            vals["num_values"] = r.zigzag()
        elif fid == 9:
            vals["data_page_offset"] = r.zigzag()
        elif fid == 11:
            vals["dictionary_page_offset"] = r.zigzag()
        elif fid == 7:
            vals["total_compressed_size"] = r.zigzag()
        elif fid == 12:
            vals["stats"] = _parse_statistics(r)
        elif fid == 2:
            size, etype = r.list_header()
            for _ in range(size):
                r.skip(etype)
        else:
            r.skip(ftype)
    col = schema_by_name[vals["path"]]
    stats = vals.get("stats", {})
    return ColumnChunkMeta(
        column=col,
        codec=vals.get("codec", 0),
        num_values=vals.get("num_values", 0),
        data_page_offset=vals.get("data_page_offset", 0),
        dictionary_page_offset=vals.get("dictionary_page_offset"),
        total_compressed_size=vals.get("total_compressed_size", 0),
        stats_min=stats.get("min"),
        stats_max=stats.get("max"),
        null_count=stats.get("null_count"),
    )


def read_footer(data: bytes) -> FileMeta:
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError("not a parquet file (bad magic)")
    (meta_len,) = struct.unpack("<I", data[-8:-4])
    r = _TReader(data, len(data) - 8 - meta_len)
    num_rows = 0
    schema: list[ParquetColumn] = []
    row_groups: list[RowGroupMeta] = []
    for fid, ftype in r.fields():
        if fid == 2:  # schema
            size, _ = r.list_header()
            raw = [_parse_schema_element(r) for _ in range(size)]
            for el in raw[1:]:  # raw[0] is the root group
                if "type" not in el:
                    raise ValueError("nested parquet schemas not supported")
                schema.append(
                    ParquetColumn(
                        name=el["name"].lower(),
                        physical=el["type"],
                        converted=el.get("converted"),
                        optional=el.get("repetition", 1) == 1,
                        scale=el.get("scale", 0),
                        precision=el.get("precision", 0),
                    )
                )
        elif fid == 3:
            num_rows = r.zigzag()
        elif fid == 4:  # row groups
            by_name = {c.name: c for c in schema}
            size, _ = r.list_header()
            for _ in range(size):
                rg_rows = 0
                rg_cols: list[ColumnChunkMeta] = []
                for rfid, rftype in r.fields():
                    if rfid == 1:
                        csize, _ = r.list_header()
                        for _ in range(csize):
                            for cfid, cftype in r.fields():
                                if cfid == 3:
                                    rg_cols.append(_parse_column_meta(r, by_name))
                                else:
                                    r.skip(cftype)
                    elif rfid == 3:
                        rg_rows = r.zigzag()
                    else:
                        r.skip(rftype)
                row_groups.append(RowGroupMeta(rg_rows, rg_cols))
        else:
            r.skip(ftype)
    return FileMeta(num_rows, schema, row_groups)


# === page decode ============================================================


def _decompress(codec: int, data: bytes, uncompressed: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_SNAPPY:
        return snappy_decompress(data, uncompressed)
    if codec == CODEC_GZIP:
        return zlib.decompress(data, 31)
    raise ValueError(f"unsupported parquet codec {codec}")


def _parse_page_header(r: _TReader) -> dict:
    out: dict[str, Any] = {"stats": None}
    for fid, ftype in r.fields():
        if fid == 1:
            out["type"] = r.zigzag()
        elif fid == 2:
            out["uncompressed"] = r.zigzag()
        elif fid == 3:
            out["compressed"] = r.zigzag()
        elif fid == 5:  # DataPageHeader
            dp: dict[str, Any] = {}
            for dfid, dftype in r.fields():
                if dfid == 1:
                    dp["num_values"] = r.zigzag()
                elif dfid == 2:
                    dp["encoding"] = r.zigzag()
                else:
                    r.skip(dftype)
            out["data"] = dp
        elif fid == 7:  # DictionaryPageHeader
            dh: dict[str, Any] = {}
            for dfid, dftype in r.fields():
                if dfid == 1:
                    dh["num_values"] = r.zigzag()
                elif dfid == 2:
                    dh["encoding"] = r.zigzag()
                else:
                    r.skip(dftype)
            out["dict"] = dh
        else:
            r.skip(ftype)
    return out


def _plain_values(col: ParquetColumn, body: bytes, n: int):
    if col.physical == INT32:
        return np.frombuffer(body, dtype="<i4", count=n)
    if col.physical == INT64:
        return np.frombuffer(body, dtype="<i8", count=n)
    if col.physical == FLOAT:
        return np.frombuffer(body, dtype="<f4", count=n)
    if col.physical == DOUBLE:
        return np.frombuffer(body, dtype="<f8", count=n)
    if col.physical == BOOLEAN:
        bits = np.unpackbits(
            np.frombuffer(body, dtype=np.uint8), bitorder="little"
        )
        return bits[:n].astype(np.bool_)
    if col.physical == BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", body, pos)
            pos += 4
            out.append(body[pos : pos + ln].decode("utf-8", "surrogatepass"))
            pos += ln
        return out
    raise ValueError(f"unsupported PLAIN physical type {col.physical}")


def read_column_chunk(data: bytes, chunk: ColumnChunkMeta):
    """Decode one column chunk -> (values ndarray-or-strlist, valid ndarray)."""
    col = chunk.column
    start = (
        chunk.dictionary_page_offset
        if chunk.dictionary_page_offset is not None
        else chunk.data_page_offset
    )
    r = _TReader(data, start)
    dictionary = None
    values_parts: list = []
    valid_parts: list[np.ndarray] = []
    remaining = chunk.num_values
    while remaining > 0:
        header = _parse_page_header(r)
        body = data[r.pos : r.pos + header["compressed"]]
        r.pos += header["compressed"]
        body = _decompress(chunk.codec, body, header["uncompressed"])
        if header["type"] == PAGE_DICT:
            dh = header["dict"]
            dictionary = _plain_values(col, body, dh["num_values"])
            continue
        if header["type"] != PAGE_DATA:
            raise ValueError(f"unsupported page type {header['type']}")
        dp = header["data"]
        n = dp["num_values"]
        pos = 0
        if col.optional:
            (dl_len,) = struct.unpack_from("<I", body, pos)
            pos += 4
            def_levels = parquet_rle_decode(body[pos : pos + dl_len], 1, n)
            pos += dl_len
            valid = def_levels.astype(np.bool_)
        else:
            valid = np.ones(n, dtype=np.bool_)
        n_present = int(valid.sum())
        enc = dp["encoding"]
        if enc in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            if dictionary is None:
                raise ValueError("dictionary-encoded page without dictionary")
            bit_width = body[pos]
            pos += 1
            idx = parquet_rle_decode(body[pos:], bit_width, n_present)
            if isinstance(dictionary, list):
                present = [dictionary[i] for i in idx]
            else:
                present = dictionary[idx]
        elif enc == ENC_PLAIN:
            present = _plain_values(col, body[pos:], n_present)
        else:
            raise ValueError(f"unsupported data encoding {enc}")
        # scatter present values into n slots
        if isinstance(present, list):
            vals: list = [""] * n
            j = 0
            for i in range(n):
                if valid[i]:
                    vals[i] = present[j]
                    j += 1
            values_parts.append(vals)
        else:
            full = np.zeros(n, dtype=present.dtype)
            full[valid] = present
            values_parts.append(full)
        valid_parts.append(valid)
        remaining -= n
    valid = np.concatenate(valid_parts) if valid_parts else np.zeros(0, bool)
    if values_parts and isinstance(values_parts[0], list):
        values: Any = [v for part in values_parts for v in part]
    else:
        values = (
            np.concatenate(values_parts)
            if values_parts
            else np.zeros(0, dtype=np.int64)
        )
    return values, valid


def _to_column(col: ParquetColumn, values, valid: np.ndarray) -> Column:
    t = col.sql_type()
    v = None if valid.all() else valid
    if isinstance(values, list):  # strings
        d, codes = Dictionary.from_strings(values)
        codes = np.where(valid, codes, -1).astype(np.int32)
        return Column(t, codes, v, d)
    if isinstance(t, T.TimestampType) and col.converted == CT_TIMESTAMP_MILLIS:
        values = values.astype(np.int64) * 1000
    data = values.astype(t.storage_dtype)
    return Column(t, data, v)


def read_batch(
    data: bytes, meta: FileMeta, row_group: int, columns: list[str]
) -> Batch:
    rg = meta.row_groups[row_group]
    by_name = {c.column.name: c for c in rg.columns}
    cols = []
    for name in columns:
        chunk = by_name[name.lower()]
        values, valid = read_column_chunk(data, chunk)
        cols.append(_to_column(chunk.column, values, valid))
    return Batch(cols, rg.num_rows)


def _decode_stat(col: ParquetColumn, raw: Optional[bytes]):
    """Statistics min/max raw bytes -> engine storage scalar."""
    if raw is None:
        return None
    t = col.sql_type()
    if col.physical == INT32:
        v = struct.unpack("<i", raw)[0]
    elif col.physical == INT64:
        v = struct.unpack("<q", raw)[0]
    elif col.physical == FLOAT:
        v = struct.unpack("<f", raw)[0]
    elif col.physical == DOUBLE:
        v = struct.unpack("<d", raw)[0]
    elif col.physical == BYTE_ARRAY:
        return raw.decode("utf-8", "surrogatepass")
    elif col.physical == BOOLEAN:
        v = bool(raw[0])
    else:
        return None
    if isinstance(t, T.TimestampType) and col.converted == CT_TIMESTAMP_MILLIS:
        v = v * 1000
    return v


def row_group_stats(meta: FileMeta, row_group: int) -> dict:
    """Per-column (min, max, has_null) — TupleDomain pruning input
    (reference: TupleDomainParquetPredicate over row-group statistics)."""
    out = {}
    rg = meta.row_groups[row_group]
    for chunk in rg.columns:
        mn = _decode_stat(chunk.column, chunk.stats_min)
        mx = _decode_stat(chunk.column, chunk.stats_max)
        if mn is None and mx is None:
            continue
        has_null = bool(chunk.null_count) if chunk.null_count is not None else True
        out[chunk.column.name] = (mn, mx, has_null)
    return out


# === writer =================================================================


def _sql_to_parquet(t: T.SqlType) -> tuple[int, Optional[int], int, int]:
    """(physical, converted, scale, precision)."""
    if isinstance(t, T.BooleanType):
        return BOOLEAN, None, 0, 0
    if isinstance(t, T.IntegerLikeType):
        return (INT32, None, 0, 0) if t.bits <= 32 else (INT64, None, 0, 0)
    if isinstance(t, T.RealType):
        return FLOAT, None, 0, 0
    if isinstance(t, T.DoubleType):
        return DOUBLE, None, 0, 0
    if isinstance(t, T.DecimalType):
        return INT64, CT_DECIMAL, t.scale, t.precision
    if isinstance(t, T.DateType):
        return INT32, CT_DATE, 0, 0
    if isinstance(t, T.TimestampType):
        return INT64, CT_TIMESTAMP_MICROS, 0, 0
    if T.is_string(t):
        return BYTE_ARRAY, CT_UTF8, 0, 0
    raise ValueError(f"cannot write {t} to parquet")


def _encode_plain(col: Column, valid: np.ndarray) -> tuple[bytes, Any, Any]:
    """(body, min_raw, max_raw) for present values in PLAIN encoding."""
    t = col.type
    if T.is_string(t):
        data = np.asarray(col.data)
        parts = []
        present_vals = []
        for i in np.nonzero(valid)[0]:
            s = (col.dictionary.decode(int(data[i])) or "").encode(
                "utf-8", "surrogatepass"
            )
            parts.append(struct.pack("<I", len(s)) + s)
            present_vals.append(s)
        mn = min(present_vals) if present_vals else None
        mx = max(present_vals) if present_vals else None
        return b"".join(parts), mn, mx
    data = np.asarray(col.data)[valid]
    if isinstance(t, T.BooleanType):
        body = np.packbits(data.astype(np.uint8), bitorder="little").tobytes()
        mn = struct.pack("<B", int(data.min())) if data.size else None
        mx = struct.pack("<B", int(data.max())) if data.size else None
        return body, mn, mx
    phys, _, _, _ = _sql_to_parquet(t)
    np_t = {INT32: "<i4", INT64: "<i8", FLOAT: "<f4", DOUBLE: "<f8"}[phys]
    arr = data.astype(np_t)
    body = arr.tobytes()
    if arr.size:
        mn = arr.min().tobytes()
        mx = arr.max().tobytes()
    else:
        mn = mx = None
    return body, mn, mx


def write_parquet(
    f: BinaryIO,
    names: list[str],
    batches: list[Batch],
    codec: int = CODEC_SNAPPY,
) -> None:
    """One row group per batch, PLAIN pages, v1 data pages + statistics."""
    f.write(MAGIC)
    offset = 4
    col_types = [c.type for c in batches[0].columns] if batches else []
    rg_metas = []
    for batch in batches:
        batch = batch.compact()
        chunk_metas = []
        for name, col in zip(names, batch.columns):
            _, valid_np = col.to_numpy()
            valid = valid_np
            body, mn, mx = _encode_plain(col, valid)
            n = batch.num_rows
            # optional def levels (4-byte length + RLE runs)
            dl = parquet_rle_encode(valid.astype(np.int32), 1)
            page_body = struct.pack("<I", len(dl)) + dl + body
            compressed = (
                snappy_compress(page_body)
                if codec == CODEC_SNAPPY
                else page_body
            )
            hw = _TWriter()
            hw.begin_struct()
            hw.i32(1, PAGE_DATA)
            hw.i32(2, len(page_body))
            hw.i32(3, len(compressed))
            hw.begin_struct(5)  # DataPageHeader
            hw.i32(1, n)
            hw.i32(2, ENC_PLAIN)
            hw.i32(3, ENC_RLE)
            hw.i32(4, ENC_RLE)
            hw.end_struct()
            hw.end_struct()
            header_bytes = bytes(hw.out)
            page_offset = offset
            f.write(header_bytes)
            f.write(compressed)
            offset += len(header_bytes) + len(compressed)
            null_count = int((~valid).sum())
            chunk_metas.append(
                (name, col.type, n, page_offset,
                 len(header_bytes) + len(compressed), mn, mx, null_count)
            )
        rg_metas.append((batch.num_rows, chunk_metas))

    # footer
    w = _TWriter()
    w.begin_struct()
    w.i32(1, 1)  # version
    # schema: root + leaves
    w.list_begin(2, 12, 1 + len(names))
    w.begin_struct()  # root
    w.binary(4, b"schema")
    w.i32(5, len(names))
    w.end_struct()
    for name, t in zip(names, col_types):
        phys, conv, scale, precision = _sql_to_parquet(t)
        w.begin_struct()
        w.i32(1, phys)
        w.i32(3, 1)  # OPTIONAL
        w.binary(4, name.encode())
        if conv is not None:
            w.i32(6, conv)
        if conv == CT_DECIMAL:
            w.i32(7, scale)
            w.i32(8, precision)
        w.end_struct()
    total_rows = sum(nr for nr, _ in rg_metas)
    w.i64(3, total_rows)
    w.list_begin(4, 12, len(rg_metas))
    for nr, chunk_metas in rg_metas:
        w.begin_struct()  # RowGroup
        w.list_begin(1, 12, len(chunk_metas))
        total_bytes = 0
        for name, t, n, page_offset, nbytes, mn, mx, null_count in chunk_metas:
            total_bytes += nbytes
            phys, conv, scale, precision = _sql_to_parquet(t)
            w.begin_struct()  # ColumnChunk
            w.i64(2, page_offset)  # file_offset
            w.begin_struct(3)  # ColumnMetaData
            w.i32(1, phys)
            w.list_begin(2, 5, 1)
            w.zigzag(ENC_PLAIN)
            w.list_begin(3, 8, 1)
            w.varint(len(name.encode()))
            w.out += name.encode()
            w.i32(4, codec)
            w.i64(5, n)
            w.i64(6, nbytes)
            w.i64(7, nbytes)
            w.i64(9, page_offset)
            w.begin_struct(12)  # Statistics
            w.i64(3, null_count)
            if mx is not None:
                w.binary(5, mx)
            if mn is not None:
                w.binary(6, mn)
            w.end_struct()
            w.end_struct()
            w.end_struct()
        w.i64(2, total_bytes)
        w.i64(3, nr)
        w.end_struct()
    w.binary(6, b"trino-tpu parquet writer")
    w.end_struct()
    meta_bytes = bytes(w.out)
    f.write(meta_bytes)
    f.write(struct.pack("<I", len(meta_bytes)))
    f.write(MAGIC)
