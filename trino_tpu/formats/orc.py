"""From-scratch ORC reader + writer feeding device-ready numpy columns.

Reference: ``lib/trino-orc`` (``orc/OrcReader.java:66,251`` tail/footer
parsing, ``OrcRecordReader.java:376`` stripe iteration,
``TupleDomainOrcPredicate.java:74`` stats pruning) — reimplemented from
the public ORC v1 specification, not translated: the hot decoders
(RLEv1/RLEv2, bit-unpack, byte-RLE) vectorize into numpy and the column
assembly produces the engine's null-mask/dictionary columnar layout
directly.

Format essentials (ORC spec):
- file tail: ...stripes | metadata | footer | postscript | ps_length(1B)
- protobuf messages throughout (hand-rolled tag/varint parser below)
- every compressed region is framed in chunks with a 3-byte header
  ``(length << 1) | is_original`` (little-endian)
- integers use RLEv1 (runs + literals of varints) or RLEv2 (SHORT_REPEAT
  / DIRECT / PATCHED_BASE / DELTA sub-encodings, bit-packed)
- nulls ride PRESENT streams (bit-per-value, byte-RLE framed)
- strings are DIRECT (bytes + lengths) or DICTIONARY (codes + dict)

Verified against pyarrow in both directions (tests/test_orc.py):
pyarrow-written files through our reader AND our writer's files through
pyarrow's reader — none/zlib/snappy compression, all engine scalar
types (wide DECIMAL(38) included), null patterns, multi-stripe files,
and stripe-stats pruning.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary

# --- minimal protobuf ------------------------------------------------------


def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _proto(buf: bytes) -> dict[int, list]:
    """Parse one protobuf message into {field: [values]}; length-delimited
    values stay bytes, varints stay ints."""
    out: dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _varint(buf, pos)
        elif wire == 1:
            v = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def _first(msg: dict, field: int, default=None):
    vals = msg.get(field)
    return vals[0] if vals else default


def _uints(msg: dict, field: int) -> list[int]:
    """Repeated uint field: entries may be plain varints or PACKED bytes."""
    out: list[int] = []
    for v in msg.get(field, []):
        if isinstance(v, int):
            out.append(v)
        else:
            pos = 0
            while pos < len(v):
                u, pos = _varint(v, pos)
                out.append(u)
    return out


def _zigzag_int(u: int) -> int:
    """Zigzag-decode one unsigned Python int (exact for any u < 2**64)."""
    return (u >> 1) ^ -(u & 1)


def _zigzag_u64(u: np.ndarray) -> np.ndarray:
    """Zigzag-decode a uint64 array into int64 (bit-exact: the xor runs in
    unsigned space; going through int64 first would overflow for values
    >= 2**63 and arithmetic-shift already-negative lanes)."""
    one = np.uint64(1)
    return ((u >> one) ^ (np.uint64(0) - (u & one))).view(np.int64)


# --- compression framing ---------------------------------------------------

COMPRESSION_NONE = 0
COMPRESSION_ZLIB = 1
COMPRESSION_SNAPPY = 2
COMPRESSION_ZSTD = 5


def _decompress(data: bytes, kind: int) -> bytes:
    if kind == COMPRESSION_NONE:
        return data
    out = bytearray()
    pos = 0
    while pos + 3 <= len(data):
        header = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
        pos += 3
        length = header >> 1
        original = header & 1
        chunk = data[pos : pos + length]
        pos += length
        if original:
            out.extend(chunk)
        elif kind == COMPRESSION_ZLIB:
            out.extend(zlib.decompress(chunk, -15))  # raw deflate
        elif kind == COMPRESSION_SNAPPY:
            from trino_tpu.native import snappy_decompress

            # snappy block: leading varint = uncompressed length
            ulen, p = _varint(chunk, 0)
            out.extend(snappy_decompress(chunk, ulen))
        elif kind == COMPRESSION_ZSTD:
            raise ValueError("zstd-compressed ORC is not supported")
        else:
            raise ValueError(f"unknown ORC compression kind {kind}")
    return bytes(out)


# --- integer decoders ------------------------------------------------------


def _read_varints(buf: bytes, count: int, pos: int = 0):
    out = np.empty(count, dtype=np.uint64)
    for i in range(count):
        v, pos = _varint(buf, pos)
        out[i] = v & 0xFFFFFFFFFFFFFFFF
    return out, pos


def _rle_v1(buf: bytes, count: int, signed: bool) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_rle1(buf, count, signed)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    filled = 0
    while filled < count:
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:  # run
            run = ctrl + 3
            delta = buf[pos]
            delta = delta - 256 if delta >= 128 else delta
            pos += 1
            base, pos = _varint(buf, pos)
            base = _zigzag_int(base) if signed else base
            out[filled : filled + run] = base + delta * np.arange(run)
            filled += run
        else:  # literals
            lit = 256 - ctrl
            vals, pos = _read_varints(buf, lit, pos)
            v = _zigzag_u64(vals) if signed else vals.astype(np.int64)
            out[filled : filled + lit] = v
            filled += lit
    return out


_RLE2_WIDTHS = [
    1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64,
    72, 80, 88, 96, 104, 112, 120, 128,
]  # 5-bit code -> bits (codes 0..4 are 1,2,4,8,16? spec: deprecated map)


def _fbw(code: int) -> int:
    """Decode the 5-bit "fixed bit width" code (ORC spec table)."""
    if code <= 23:
        return code + 1
    return {24: 26, 25: 28, 26: 30, 27: 32, 28: 40, 29: 48, 30: 56, 31: 64}[code]


_FIXED_WIDTHS = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]


def _closest_fixed_bits(n: int) -> int:
    """Round up to the nearest encodable width (patch entries pack at
    closestFixedBits(gapWidth + patchWidth))."""
    for w in _FIXED_WIDTHS:
        if w >= n:
            return w
    return 64


def _unpack_bits(buf: bytes, count: int, width: int, pos: int):
    """Big-endian bit-unpack `count` values of `width` bits."""
    nbits = count * width
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=pos)
    bits = np.unpackbits(raw)[: count * width].reshape(count, width)
    weights = (1 << np.arange(width - 1, -1, -1, dtype=np.uint64))
    vals = (bits.astype(np.uint64) * weights).sum(axis=1)
    return vals, pos + nbytes


def _rle_v2(buf: bytes, count: int, signed: bool) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_rle2(buf, count, signed)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    filled = 0
    while filled < count:
        first = buf[pos]
        enc = first >> 6
        if enc == 0:  # SHORT_REPEAT
            width = ((first >> 3) & 0x7) + 1
            repeat = (first & 0x7) + 3
            pos += 1
            val = int.from_bytes(buf[pos : pos + width], "big")
            pos += width
            if signed:
                val = _zigzag_int(val)
            out[filled : filled + repeat] = val
            filled += repeat
        elif enc == 1:  # DIRECT
            width = _fbw((first >> 1) & 0x1F)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_bits(buf, length, width, pos)
            v = _zigzag_u64(vals.astype(np.uint64)) if signed else vals.astype(np.int64)
            out[filled : filled + length] = v
            filled += length
        elif enc == 3:  # DELTA
            width_code = (first >> 1) & 0x1F
            width = 0 if width_code == 0 else _fbw(width_code)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            base, pos = _varint(buf, pos)
            base = _zigzag_int(base) if signed else base
            delta0, pos = _varint(buf, pos)
            delta0 = _zigzag_int(delta0)
            seq = np.empty(length, dtype=np.int64)
            seq[0] = base
            if length > 1:
                if width == 0:
                    deltas = np.full(length - 1, delta0, dtype=np.int64)
                else:
                    rest, pos = _unpack_bits(buf, length - 2, width, pos)
                    deltas = np.empty(length - 1, dtype=np.int64)
                    deltas[0] = delta0
                    sign = 1 if delta0 >= 0 else -1
                    deltas[1:] = sign * rest.astype(np.int64)
                seq[1:] = base + np.cumsum(deltas)
            out[filled : filled + length] = seq
            filled += length
        else:  # PATCHED_BASE
            width = _fbw((first >> 1) & 0x1F)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            third, fourth = buf[pos + 2], buf[pos + 3]
            base_width = ((third >> 5) & 0x7) + 1
            patch_width = _fbw(third & 0x1F)
            patch_gap_width = ((fourth >> 5) & 0x7) + 1
            patch_count = fourth & 0x1F
            pos += 4
            base_raw = int.from_bytes(buf[pos : pos + base_width], "big")
            pos += base_width
            msb = 1 << (base_width * 8 - 1)
            base = -(base_raw & ~msb) if base_raw & msb else base_raw
            vals, pos = _unpack_bits(buf, length, width, pos)
            patch_bits = _closest_fixed_bits(patch_width + patch_gap_width)
            patches, pos = _unpack_bits(buf, patch_count, patch_bits, pos)
            vals = vals.astype(np.int64)
            idx = 0
            for p in patches:
                gap = int(p) >> patch_width
                patch = int(p) & ((1 << patch_width) - 1)
                idx += gap
                vals[idx] |= patch << width
            out[filled : filled + length] = base + vals
            filled += length
    return out


def _byte_rle(buf: bytes, count: int) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_byte_rle(buf, count)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.uint8)
    pos = 0
    filled = 0
    while filled < count:
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:
            run = ctrl + 3
            out[filled : filled + run] = buf[pos]
            pos += 1
            filled += run
        else:
            lit = 256 - ctrl
            out[filled : filled + lit] = np.frombuffer(
                buf, dtype=np.uint8, count=lit, offset=pos
            )
            pos += lit
            filled += lit
    return out


def _bool_rle(buf: bytes, count: int) -> np.ndarray:
    nbytes = (count + 7) // 8
    b = _byte_rle(buf, nbytes)
    return np.unpackbits(b)[:count].astype(bool)


def _decimal_varints_wide(
    buf: bytes, count: int, target_scale: int, scales: np.ndarray
) -> np.ndarray:
    """Decimal DATA for precision > 18: unbounded zigzag varints decoded in
    Python ints, rescaled to the declared scale, split into (hi, lo)
    two's-complement int64 lanes (the engine's wide storage)."""
    from trino_tpu.ops.decimal128 import int_to_pair

    out = np.empty((count, 2), dtype=np.int64)
    pos = 0
    for i in range(count):
        u, pos = _varint(buf, pos)
        v = (u >> 1) ^ -(u & 1)
        diff = target_scale - int(scales[i])
        if diff > 0:
            v *= 10**diff
        elif diff < 0:
            v //= 10**-diff
        hi, lo = int_to_pair(v)
        out[i, 0] = hi
        out[i, 1] = lo
    return out


def _decimal_varints(buf: bytes, count: int) -> np.ndarray:
    """Decimal DATA: unbounded zigzag varints (values beyond int64 raise —
    wide decimal ORC columns arrive via the (hi, lo) path)."""
    from trino_tpu import native

    fast = native.orc_decimal64(buf, count)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    for i in range(count):
        v, pos = _varint(buf, pos)
        out[i] = _zigzag_int(v & 0xFFFFFFFFFFFFFFFF)
    return out


# --- schema ---------------------------------------------------------------

KIND_BOOLEAN = 0
KIND_BYTE = 1
KIND_SHORT = 2
KIND_INT = 3
KIND_LONG = 4
KIND_FLOAT = 5
KIND_DOUBLE = 6
KIND_STRING = 7
KIND_BINARY = 8
KIND_TIMESTAMP = 9
KIND_LIST = 10
KIND_MAP = 11
KIND_STRUCT = 12
KIND_UNION = 13
KIND_DECIMAL = 14
KIND_DATE = 15
KIND_VARCHAR = 16
KIND_CHAR = 17

STREAM_PRESENT = 0
STREAM_DATA = 1
STREAM_LENGTH = 2
STREAM_DICTIONARY_DATA = 3
STREAM_SECONDARY = 5
STREAM_ROW_INDEX = 6

ENC_DIRECT = 0
ENC_DICTIONARY = 1
ENC_DIRECT_V2 = 2
ENC_DICTIONARY_V2 = 3


@dataclasses.dataclass
class OrcType:
    kind: int
    subtypes: list[int]
    field_names: list[str]
    precision: int = 0
    scale: int = 0

    def sql_type(self):
        if self.kind in (KIND_BOOLEAN,):
            return T.BOOLEAN
        if self.kind in (KIND_BYTE, KIND_SHORT, KIND_INT, KIND_LONG):
            return T.BIGINT
        if self.kind in (KIND_FLOAT, KIND_DOUBLE):
            return T.DOUBLE
        if self.kind in (KIND_STRING, KIND_VARCHAR, KIND_CHAR):
            return T.VARCHAR
        if self.kind == KIND_DATE:
            return T.DATE
        if self.kind == KIND_DECIMAL:
            return T.decimal(self.precision or 38, self.scale)
        raise ValueError(f"unsupported ORC type kind {self.kind}")


@dataclasses.dataclass
class StripeInfo:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    num_rows: int


@dataclasses.dataclass
class ColumnStats:
    num_values: Optional[int]
    has_null: bool
    min_value: Optional[object]
    max_value: Optional[object]


class OrcFile:
    """Parsed tail + stripe directory of one ORC file."""

    MAGIC = b"ORC"

    def __init__(self, data: bytes):
        self.data = data
        if len(data) < 16:
            raise ValueError("not an ORC file (too short)")
        ps_len = data[-1]
        ps = _proto(data[-1 - ps_len : -1])
        self.compression = _first(ps, 2, 0)
        footer_len = _first(ps, 1, 0)
        meta_len = _first(ps, 5, 0)
        magic = _first(ps, 8000, b"")
        if magic != self.MAGIC and not data.startswith(self.MAGIC):
            raise ValueError("not an ORC file (missing magic)")
        tail = len(data) - 1 - ps_len
        footer = _proto(_decompress(data[tail - footer_len : tail], self.compression))
        meta_buf = data[tail - footer_len - meta_len : tail - footer_len]
        self.metadata = (
            _proto(_decompress(meta_buf, self.compression)) if meta_len else {}
        )
        self.num_rows = _first(footer, 6, 0)
        self.types: list[OrcType] = []
        for tbytes in footer.get(4, []):
            m = _proto(tbytes)
            self.types.append(
                OrcType(
                    kind=_first(m, 1, 0),
                    subtypes=_uints(m, 2),
                    field_names=[v.decode() for v in m.get(3, [])],
                    precision=_first(m, 5, 0),
                    scale=_first(m, 6, 0),
                )
            )
        self.stripes: list[StripeInfo] = []
        for sbytes in footer.get(3, []):
            m = _proto(sbytes)
            self.stripes.append(
                StripeInfo(
                    offset=_first(m, 1, 0),
                    index_length=_first(m, 2, 0),
                    data_length=_first(m, 3, 0),
                    footer_length=_first(m, 4, 0),
                    num_rows=_first(m, 5, 0),
                )
            )
        # column order: root struct's children
        root = self.types[0]
        if root.kind != KIND_STRUCT:
            raise ValueError("ORC root type must be a struct")
        self.column_names = root.field_names
        self.column_type_ids = root.subtypes

    # --- statistics ------------------------------------------------------

    def stripe_stats(self, stripe_index: int) -> dict[int, ColumnStats]:
        """Per-type-id stats for one stripe (Metadata.stripeStats)."""
        entries = self.metadata.get(1, [])
        if stripe_index >= len(entries):
            return {}
        per_col = _proto(entries[stripe_index]).get(1, [])
        out = {}
        for type_id, cbytes in enumerate(per_col):
            out[type_id] = _parse_col_stats(
                cbytes, self.types[type_id] if type_id < len(self.types) else None
            )
        return out

    # --- stripe reading --------------------------------------------------

    def read_stripe(
        self, stripe: StripeInfo, want: Optional[set[str]] = None
    ) -> dict[str, Column]:
        sf_off = stripe.offset + stripe.index_length + stripe.data_length
        sfooter = _proto(
            _decompress(
                self.data[sf_off : sf_off + stripe.footer_length],
                self.compression,
            )
        )
        streams = []
        pos = stripe.offset
        for sbytes in sfooter.get(1, []):
            m = _proto(sbytes)
            kind = _first(m, 1, 0)
            column = _first(m, 2, 0)
            length = _first(m, 3, 0)
            streams.append((kind, column, pos, length))
            pos += length
        encodings = [
            (_first(_proto(e), 1, 0), _first(_proto(e), 2, 0))
            for e in sfooter.get(2, [])
        ]

        def stream(col_id: int, kind: int) -> Optional[bytes]:
            for k, c, off, ln in streams:
                if c == col_id and k == kind:
                    return _decompress(
                        self.data[off : off + ln], self.compression
                    )
            return None

        out: dict[str, Column] = {}
        for name, type_id in zip(self.column_names, self.column_type_ids):
            if want is not None and name not in want:
                continue
            out[name] = self._read_column(
                type_id, stripe.num_rows, stream, encodings
            )
        return out

    def _read_column(self, type_id, num_rows, stream, encodings) -> Column:
        t = self.types[type_id]
        enc = encodings[type_id][0] if type_id < len(encodings) else ENC_DIRECT
        present = stream(type_id, STREAM_PRESENT)
        if present is not None:
            valid = _bool_rle(present, num_rows)
            n_present = int(valid.sum())
        else:
            valid = None
            n_present = num_rows

        def expand(vals: np.ndarray, fill=0) -> np.ndarray:
            if valid is None:
                return vals
            out = np.full(num_rows, fill, dtype=vals.dtype)
            out[valid] = vals
            return out

        data = stream(type_id, STREAM_DATA)
        v2 = enc in (ENC_DIRECT_V2, ENC_DICTIONARY_V2)
        rle = _rle_v2 if v2 else _rle_v1

        if t.kind in (KIND_SHORT, KIND_INT, KIND_LONG):
            vals = rle(data, n_present, signed=True)
            return Column(T.BIGINT, expand(vals), valid)
        if t.kind == KIND_DATE:
            vals = rle(data, n_present, signed=True)
            return Column(T.DATE, expand(vals).astype(np.int32), valid)
        if t.kind == KIND_BYTE:
            vals = _byte_rle(data, n_present).astype(np.int8).astype(np.int64)
            return Column(T.BIGINT, expand(vals), valid)
        if t.kind == KIND_BOOLEAN:
            vals = _bool_rle(data, n_present)
            return Column(T.BOOLEAN, expand(vals), valid)
        if t.kind in (KIND_FLOAT, KIND_DOUBLE):
            width = 4 if t.kind == KIND_FLOAT else 8
            dt = np.float32 if t.kind == KIND_FLOAT else np.float64
            vals = np.frombuffer(data, dtype=np.dtype(dt).newbyteorder("<"),
                                 count=n_present).astype(np.float64)
            return Column(T.DOUBLE, expand(vals), valid)
        if t.kind == KIND_DECIMAL:
            secondary = stream(type_id, STREAM_SECONDARY)
            scales = rle(secondary, n_present, signed=True)
            target = t.scale
            if (t.precision or 38) > 18:
                # wide path: unbounded varints -> (hi, lo) int64 lanes
                pairs = _decimal_varints_wide(data, n_present, target, scales)
                if valid is None:
                    return Column(t.sql_type(), pairs, None)
                out_pairs = np.zeros((num_rows, 2), dtype=np.int64)
                out_pairs[valid] = pairs
                return Column(t.sql_type(), out_pairs, valid)
            vals = _decimal_varints(data, n_present)
            diff = target - scales
            # normalize to declared scale (writers emit per-value scales)
            vals = np.where(
                diff >= 0,
                vals * (10 ** np.clip(diff, 0, None)),
                vals // (10 ** np.clip(-diff, 0, None)),
            )
            return Column(t.sql_type(), expand(vals), valid)
        if t.kind in (KIND_STRING, KIND_VARCHAR, KIND_CHAR):
            if enc in (ENC_DICTIONARY, ENC_DICTIONARY_V2):
                codes = rle(data, n_present, signed=False)
                dict_data = stream(type_id, STREAM_DICTIONARY_DATA) or b""
                lengths = rle(
                    stream(type_id, STREAM_LENGTH), encodings[type_id][1],
                    signed=False,
                )
                offs = np.concatenate([[0], np.cumsum(lengths)])
                values = [
                    dict_data[offs[i] : offs[i + 1]].decode("utf-8")
                    for i in range(len(lengths))
                ]
                d = Dictionary(values)
                out_codes = expand(codes.astype(np.int32), fill=-1)
                return Column(T.VARCHAR, out_codes, valid, d)
            lengths = rle(stream(type_id, STREAM_LENGTH), n_present, signed=False)
            offs = np.concatenate([[0], np.cumsum(lengths)])
            strings = [
                data[offs[i] : offs[i + 1]].decode("utf-8")
                for i in range(n_present)
            ]
            d, codes = Dictionary.from_strings(strings)
            return Column(T.VARCHAR, expand(codes, fill=-1), valid, d)
        raise ValueError(f"unsupported ORC column kind {t.kind}")


def _parse_col_stats(cbytes: bytes, t: Optional[OrcType]) -> ColumnStats:
    m = _proto(cbytes)
    num = _first(m, 1)
    has_null = bool(_first(m, 10, 0))
    mn = mx = None
    if 2 in m:  # integers
        s = _proto(m[2][0])
        mn = _signed_varint(_first(s, 1))
        mx = _signed_varint(_first(s, 2))
    elif 7 in m:  # date
        s = _proto(m[7][0])
        mn = _signed_varint(_first(s, 1))
        mx = _signed_varint(_first(s, 2))
    elif 4 in m:  # string
        s = _proto(m[4][0])
        mn = _first(s, 1)
        mx = _first(s, 2)
        mn = mn.decode() if mn is not None else None
        mx = mx.decode() if mx is not None else None
    elif 3 in m:  # double
        s = _proto(m[3][0])
        mn = _f64(_first(s, 1))
        mx = _f64(_first(s, 2))
    elif 6 in m:  # decimal (strings)
        s = _proto(m[6][0])
        mn = _first(s, 1)
        mx = _first(s, 2)
        mn = mn.decode() if mn is not None else None
        mx = mx.decode() if mx is not None else None
    return ColumnStats(num, has_null, mn, mx)


def _signed_varint(v):
    """sint64 fields arrive zigzag-encoded by protobuf."""
    if v is None:
        return None
    return _zigzag_int(v & 0xFFFFFFFFFFFFFFFF)


def _f64(v):
    if v is None:
        return None
    return float(np.frombuffer(v, dtype="<f8")[0])


def read_orc(path: str, columns: Optional[list[str]] = None) -> Batch:
    """Read a whole ORC file into one Batch (column subset optional)."""
    from trino_tpu.columnar import concat_batches

    with open(path, "rb") as f:
        data = f.read()
    f = OrcFile(data)
    want = set(columns) if columns is not None else None
    names = [
        n for n in f.column_names if want is None or n in want
    ]
    batches = []
    for stripe in f.stripes:
        cols = f.read_stripe(stripe, want)
        batches.append(
            Batch([cols[n] for n in names], stripe.num_rows)
        )
    if not batches:
        return Batch([], 0)
    return concat_batches(batches) if len(batches) > 1 else batches[0]

# ===========================================================================
# Writer
# ===========================================================================
#
# Mirrors the reader above from the other side of the ORC v1 spec
# (reference: ``lib/trino-orc/src/main/java/io/trino/orc/OrcWriter.java``,
# stream layout ``OrcWriter.java`` bufferStripeData / writeStripe — rebuilt
# from the public specification, not translated). One stripe per input
# batch; integer/date/decimal-scale streams use RLEv2 (SHORT_REPEAT for
# short constant runs, DELTA for long ones, DIRECT for everything else),
# strings use sorted DICTIONARY_V2, decimals use unbounded zigzag varints
# (wide (hi, lo) columns included), nulls ride byte-RLE PRESENT bitmaps.
# File- and stripe-level column statistics are emitted so our own
# stripe-stats pruning works on files we wrote.


class _PW:
    """Protobuf writer (mirror of _proto above)."""

    def __init__(self):
        self.out = bytearray()

    def varint(self, v: int):
        """64-bit varint (field tags, lengths, counts)."""
        v &= 0xFFFFFFFFFFFFFFFF
        self.varint_unbounded(v)

    def varint_unbounded(self, v: int):
        """Unbounded varint (ORC decimal unscaled values exceed 64 bits)."""
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def uint(self, field: int, v: int):
        self.varint((field << 3) | 0)
        self.varint(v)

    def sint(self, field: int, v: int):
        self.uint(field, (v << 1) ^ (v >> 63) if v >= -(1 << 63) else (v << 1) ^ -1)

    def f64(self, field: int, x: float):
        import struct as _s

        self.varint((field << 3) | 1)
        self.out += _s.pack("<d", x)

    def bytes_(self, field: int, b: bytes):
        self.varint((field << 3) | 2)
        self.varint(len(b))
        self.out += b

    def msg(self, field: int, pw: "_PW"):
        self.bytes_(field, bytes(pw.out))


def _zigzag_encode_np(v: np.ndarray) -> np.ndarray:
    """int64 -> zigzag uint64 (unsigned space, exact)."""
    u = v.astype(np.int64).view(np.uint64)
    one = np.uint64(1)
    return (u << one) ^ (np.uint64(0) - (u >> np.uint64(63)))


def _varints_bytes(u: np.ndarray) -> bytes:
    """Encode a uint64 array as concatenated LEB128 varints (vectorized)."""
    from trino_tpu import native

    fast = native.orc_varint_encode(u)
    if fast is not None:
        return fast
    out = bytearray()
    for x in u.tolist():
        x &= 0xFFFFFFFFFFFFFFFF
        while True:
            b = x & 0x7F
            x >>= 7
            if x:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
    return bytes(out)


_WIDTH_CODES = {w: (w - 1) for w in range(1, 25)}
_WIDTH_CODES.update({26: 24, 28: 25, 30: 26, 32: 27, 40: 28, 48: 29, 56: 30, 64: 31})


def _pack_bits_be(u: np.ndarray, width: int) -> bytes:
    """Big-endian bitpack of uint64 values at `width` bits each."""
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    bits = ((u[:, None] >> shifts) & np.uint64(1)).astype(np.uint8).reshape(-1)
    return np.packbits(bits).tobytes()


def _emit_direct(out: bytearray, u: np.ndarray):
    """One DIRECT chunk (<=512 zigzagged/unsigned values)."""
    maxv = int(u.max()) if u.size else 0
    width = _closest_fixed_bits(max(maxv.bit_length(), 1))
    code = _WIDTH_CODES[width]
    ln = len(u) - 1
    out.append(0x40 | (code << 1) | (ln >> 8))
    out.append(ln & 0xFF)
    out += _pack_bits_be(u, width)


def _emit_constant_run(out: bytearray, value: int, run: int, signed: bool):
    """Constant run as SHORT_REPEAT (3..10) or DELTA with delta 0 (<=512)."""
    uval = ((value << 1) ^ (value >> 63)) & 0xFFFFFFFFFFFFFFFF if signed else value
    while run > 0:
        if 3 <= run <= 10:
            width = max((uval.bit_length() + 7) // 8, 1)
            out.append(((width - 1) << 3) | (run - 3))
            out += uval.to_bytes(width, "big")
            return
        take = min(run, 512)
        if take < 3:  # trailing 1-2 values: emit as DIRECT
            _emit_direct(out, np.full(take, uval, dtype=np.uint64))
            return
        ln = take - 1
        out.append(0xC0 | (ln >> 8))  # DELTA, width code 0
        out.append(ln & 0xFF)
        pw = _PW()
        if signed:
            pw.varint(((value << 1) ^ (value >> 63)) & 0xFFFFFFFFFFFFFFFF)
        else:
            pw.varint(value)
        pw.varint(0)  # delta0 = 0 (zigzag of 0)
        out += pw.out
        run -= take


def _rle_v2_encode(vals: np.ndarray, signed: bool) -> bytes:
    """RLEv2 encode int64 values (greedy runs + DIRECT literals)."""
    v = np.asarray(vals, dtype=np.int64)
    n = len(v)
    if n == 0:
        return b""
    from trino_tpu import native

    fast = native.orc_rle2_encode(v, signed)
    if fast is not None:
        return fast
    u = _zigzag_encode_np(v) if signed else v.view(np.uint64)
    # maximal equal-value runs
    starts = np.concatenate([[0], np.flatnonzero(np.diff(v) != 0) + 1])
    lens = np.diff(np.concatenate([starts, [n]]))
    big = np.flatnonzero(lens >= 6)  # runs worth a run-encoding
    out = bytearray()
    pos = 0
    for ri in big:
        s, ln = int(starts[ri]), int(lens[ri])
        for c0 in range(pos, s, 512):  # flush literals before the run
            _emit_direct(out, u[c0 : min(c0 + 512, s)])
        _emit_constant_run(out, int(v[s]), ln, signed)
        pos = s + ln
    for c0 in range(pos, n, 512):
        _emit_direct(out, u[c0 : min(c0 + 512, n)])
    return bytes(out)


def _byte_rle_encode(b: np.ndarray) -> bytes:
    """Byte-RLE encode (runs of 3..130, literals of 1..128)."""
    b = np.asarray(b, dtype=np.uint8)
    n = len(b)
    if n == 0:
        return b""
    from trino_tpu import native

    fast = native.orc_byte_rle_encode(b)
    if fast is not None:
        return fast
    starts = np.concatenate([[0], np.flatnonzero(np.diff(b) != 0) + 1])
    lens = np.diff(np.concatenate([starts, [n]]))
    out = bytearray()
    lit_start = 0

    def flush_literals(upto: int):
        nonlocal lit_start
        p = lit_start
        while p < upto:
            take = min(128, upto - p)
            out.append(256 - take)
            out.extend(b[p : p + take].tobytes())
            p += take
        lit_start = upto

    for s, ln in zip(starts.tolist(), lens.tolist()):
        if ln >= 3:
            flush_literals(s)
            rem = ln
            while rem > 0:
                take = min(rem, 130)
                if rem - take in (1, 2):
                    take -= 3 - (rem - take)  # leave >=3 (or 0) for next pass
                out.append(take - 3)
                out.append(int(b[s]))
                rem -= take
            lit_start = s + ln
    flush_literals(n)
    return bytes(out)


def _bool_rle_encode(mask: np.ndarray) -> bytes:
    packed = np.packbits(np.asarray(mask, dtype=np.uint8))  # big-endian bits
    return _byte_rle_encode(packed)


def _compress_stream(data: bytes, kind: int, block: int = 262144) -> bytes:
    if kind == COMPRESSION_NONE:
        return data
    out = bytearray()
    for p in range(0, len(data), block) or [0]:
        chunk = data[p : p + block]
        if kind == COMPRESSION_ZLIB:
            comp = zlib.compress(chunk, 6)[2:-4]  # raw deflate
        elif kind == COMPRESSION_SNAPPY:
            from trino_tpu.native import snappy_compress

            pw = _PW()
            pw.varint(len(chunk))
            comp = bytes(pw.out) + snappy_compress(chunk)
        else:
            raise ValueError(f"unsupported ORC write compression {kind}")
        if len(comp) >= len(chunk):
            header = (len(chunk) << 1) | 1
            out += header.to_bytes(3, "little")
            out += chunk
        else:
            header = len(comp) << 1
            out += header.to_bytes(3, "little")
            out += comp
    if not data:
        return b""
    return bytes(out)


def _orc_kind(t: T.SqlType) -> int:
    if isinstance(t, T.BooleanType):
        return KIND_BOOLEAN
    if isinstance(t, T.IntegerLikeType):
        return KIND_INT if t.bits == 32 else KIND_LONG
    if isinstance(t, T.RealType):
        return KIND_FLOAT
    if isinstance(t, T.DoubleType):
        return KIND_DOUBLE
    if T.is_string(t):
        return KIND_STRING
    if isinstance(t, T.DateType):
        return KIND_DATE
    if isinstance(t, T.DecimalType):
        return KIND_DECIMAL
    raise ValueError(f"cannot write type {t} to ORC")


class _ColStats:
    """Accumulates numberOfValues/hasNull/min/max for one column."""

    def __init__(self, t: T.SqlType):
        self.t = t
        self.n = 0
        self.has_null = False
        self.mn = None
        self.mx = None

    def update(self, mn, mx, count, has_null):
        self.n += count
        self.has_null |= has_null
        if mn is not None and (self.mn is None or mn < self.mn):
            self.mn = mn
        if mx is not None and (self.mx is None or mx > self.mx):
            self.mx = mx

    def proto(self) -> "_PW":
        pw = _PW()
        pw.uint(1, self.n)
        t, mn, mx = self.t, self.mn, self.mx
        if mn is not None:
            sub = _PW()
            if isinstance(t, T.IntegerLikeType):
                sub.sint(1, int(mn))
                sub.sint(2, int(mx))
                pw.msg(2, sub)
            elif isinstance(t, (T.DoubleType, T.RealType)):
                sub.f64(1, float(mn))
                sub.f64(2, float(mx))
                pw.msg(3, sub)
            elif T.is_string(t):
                sub.bytes_(1, mn.encode("utf-8"))
                sub.bytes_(2, mx.encode("utf-8"))
                pw.msg(4, sub)
            elif isinstance(t, T.DecimalType):
                from decimal import Decimal

                q = Decimal(1).scaleb(-t.scale)
                sub.bytes_(1, str(Decimal(int(mn)).scaleb(-t.scale).quantize(q)).encode())
                sub.bytes_(2, str(Decimal(int(mx)).scaleb(-t.scale).quantize(q)).encode())
                pw.msg(6, sub)
            elif isinstance(t, T.DateType):
                sub.sint(1, int(mn))
                sub.sint(2, int(mx))
                pw.msg(7, sub)
        if self.has_null:
            pw.uint(10, 1)
        return pw


def _encode_column(
    col: Column, kind: int, compression: int
) -> tuple[list[tuple[int, bytes]], tuple[int, int], tuple, int]:
    """Encode one column -> ([(stream_kind, bytes)], (encoding, dict_size),
    (min, max, count, has_null), n_present)."""
    t = col.type
    data, valid = col.to_numpy()
    all_valid = bool(valid.all())
    streams: list[tuple[int, bytes]] = []
    if not all_valid:
        streams.append((STREAM_PRESENT, _bool_rle_encode(valid)))
    enc = (ENC_DIRECT_V2, 0)
    mn = mx = None
    has_null = not all_valid

    if T.is_string(t):
        # gather present strings, sort a dictionary, remap codes
        codes = data[valid]
        d = col.dictionary
        present = [d.decode(int(c)) or "" for c in codes]
        uniq = sorted(set(present))
        index = {s: i for i, s in enumerate(uniq)}
        remapped = np.asarray([index[s] for s in present], dtype=np.int64)
        dict_bytes = b"".join(s.encode("utf-8") for s in uniq)
        lengths = np.asarray([len(s.encode("utf-8")) for s in uniq], dtype=np.int64)
        streams.append((STREAM_DATA, _rle_v2_encode(remapped, signed=False)))
        streams.append((STREAM_DICTIONARY_DATA, dict_bytes))
        streams.append((STREAM_LENGTH, _rle_v2_encode(lengths, signed=False)))
        enc = (ENC_DICTIONARY_V2, len(uniq))
        if present:
            mn, mx = min(present), max(present)
    elif isinstance(t, T.BooleanType):
        streams.append((STREAM_DATA, _bool_rle_encode(data[valid].astype(bool))))
        pv = data[valid]
        if pv.size:
            mn, mx = bool(pv.min()), bool(pv.max())
        enc = (ENC_DIRECT, 0)
    elif isinstance(t, (T.DoubleType, T.RealType)):
        pv = data[valid]
        if isinstance(t, T.RealType):
            streams.append((STREAM_DATA, pv.astype("<f4").tobytes()))
        else:
            streams.append((STREAM_DATA, pv.astype("<f8").tobytes()))
        finite = pv[~np.isnan(pv)] if pv.dtype.kind == "f" else pv
        if finite.size:
            mn, mx = float(finite.min()), float(finite.max())
        enc = (ENC_DIRECT, 0)
    elif isinstance(t, T.DecimalType):
        if data.ndim == 2:  # wide (hi, lo)
            from trino_tpu.ops.decimal128 import pair_to_int

            ints = [pair_to_int(int(h), int(l)) for h, l in data[valid]]
            pw = _PW()
            for x in ints:
                pw.varint_unbounded((x << 1) ^ (x >> 127))  # zigzag, >64-bit
            dec_bytes = bytes(pw.out)
        else:
            pv = data[valid].astype(np.int64)
            ints = [int(x) for x in pv]
            dec_bytes = _varints_bytes(_zigzag_encode_np(pv))
        streams.append((STREAM_DATA, dec_bytes))
        scales = np.full(len(ints), t.scale, dtype=np.int64)
        streams.append((STREAM_SECONDARY, _rle_v2_encode(scales, signed=True)))
        if ints:
            mn, mx = min(ints), max(ints)
    elif isinstance(t, T.DateType) or isinstance(t, T.IntegerLikeType):
        pv = data[valid].astype(np.int64)
        streams.append((STREAM_DATA, _rle_v2_encode(pv, signed=True)))
        if pv.size:
            mn, mx = int(pv.min()), int(pv.max())
    else:
        raise ValueError(f"cannot write type {t} to ORC")

    n_present = int(valid.sum())
    streams = [(k, _compress_stream(b, compression)) for k, b in streams]
    return streams, enc, (mn, mx, n_present, has_null), n_present


def write_orc(
    f,
    names: list[str],
    batches: list["Batch"],
    compression: int = COMPRESSION_ZLIB,
) -> None:
    """Write batches as an ORC file: one stripe per batch.

    The inverse of :class:`OrcFile`; stream layout per the ORC v1 spec,
    verified against pyarrow's reader (tests/test_orc.py)."""
    f.write(b"ORC")
    offset = 3
    col_types = [c.type for c in batches[0].columns] if batches else []
    kinds = [_orc_kind(t) for t in col_types]
    file_stats = [_ColStats(t) for t in col_types]
    root_stats_rows = 0
    stripe_infos: list[tuple[int, int, int, int, int]] = []
    stripe_stat_msgs: list[_PW] = []

    for batch in batches:
        batch = batch.compact()
        nrows = batch.num_rows
        root_stats_rows += nrows
        all_streams: list[tuple[int, int, bytes]] = []  # (kind, column_id, data)
        encodings: list[tuple[int, int]] = [(ENC_DIRECT, 0)]  # root
        per_col_stats: list[_ColStats] = []
        for ci, (col, kind) in enumerate(zip(batch.columns, kinds)):
            streams, enc, stat, _np_ = _encode_column(col, kind, compression)
            for sk, sb in streams:
                all_streams.append((sk, ci + 1, sb))
            encodings.append(enc)
            cs = _ColStats(col.type)
            cs.update(stat[0], stat[1], stat[2], stat[3])
            per_col_stats.append(cs)
            file_stats[ci].update(stat[0], stat[1], stat[2], stat[3])
        data_len = sum(len(sb) for _, _, sb in all_streams)
        # stripe footer
        sf = _PW()
        for sk, cid, sb in all_streams:
            sub = _PW()
            sub.uint(1, sk)
            sub.uint(2, cid)
            sub.uint(3, len(sb))
            sf.msg(1, sub)
        for ek, dsz in encodings:
            sub = _PW()
            sub.uint(1, ek)
            if dsz:
                sub.uint(2, dsz)
            sf.msg(2, sub)
        sf_bytes = _compress_stream(bytes(sf.out), compression)
        stripe_offset = offset
        for _, _, sb in all_streams:
            f.write(sb)
        f.write(sf_bytes)
        offset += data_len + len(sf_bytes)
        stripe_infos.append((stripe_offset, 0, data_len, len(sf_bytes), nrows))
        # stripe statistics entry (root column 0 + data columns)
        ss = _PW()
        root = _PW()
        root.uint(1, nrows)
        ss.msg(1, root)
        for cs in per_col_stats:
            ss.msg(1, cs.proto())
        stripe_stat_msgs.append(ss)

    # metadata (stripe stats)
    meta = _PW()
    for ss in stripe_stat_msgs:
        meta.msg(1, ss)
    meta_bytes = _compress_stream(bytes(meta.out), compression)

    # footer
    ft = _PW()
    ft.uint(1, 3)  # headerLength ("ORC")
    ft.uint(2, offset)  # contentLength
    for so, il, dl, fl, nr in stripe_infos:
        sub = _PW()
        sub.uint(1, so)
        sub.uint(2, il)
        sub.uint(3, dl)
        sub.uint(4, fl)
        sub.uint(5, nr)
        ft.msg(3, sub)
    root_t = _PW()
    root_t.uint(1, KIND_STRUCT)
    for i in range(len(names)):
        root_t.uint(2, i + 1)
    for nme in names:
        root_t.bytes_(3, nme.encode("utf-8"))
    ft.msg(4, root_t)
    for t, kind in zip(col_types, kinds):
        sub = _PW()
        sub.uint(1, kind)
        if isinstance(t, T.DecimalType):
            sub.uint(5, t.precision)
            sub.uint(6, t.scale)
        ft.msg(4, sub)
    ft.uint(6, root_stats_rows)
    # file-level column statistics (field 7): root then columns
    root_cs = _PW()
    root_cs.uint(1, root_stats_rows)
    ft.msg(7, root_cs)
    for cs in file_stats:
        ft.msg(7, cs.proto())
    ft.uint(8, 0)  # rowIndexStride = 0 (no row indexes)
    footer_bytes = _compress_stream(bytes(ft.out), compression)

    f.write(meta_bytes)
    f.write(footer_bytes)

    ps = _PW()
    ps.uint(1, len(footer_bytes))
    ps.uint(2, compression)
    if compression != COMPRESSION_NONE:
        ps.uint(3, 262144)
    ps.uint(4, 0)
    ps.uint(4, 12)
    ps.uint(5, len(meta_bytes))
    ps.uint(6, 1)  # writerVersion
    ps.bytes_(8000, b"ORC")
    ps_bytes = bytes(ps.out)
    f.write(ps_bytes)
    f.write(bytes([len(ps_bytes)]))
