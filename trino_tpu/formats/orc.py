"""From-scratch ORC reader feeding device-ready numpy columns.

Reference: ``lib/trino-orc`` (``orc/OrcReader.java:66,251`` tail/footer
parsing, ``OrcRecordReader.java:376`` stripe iteration,
``TupleDomainOrcPredicate.java:74`` stats pruning) — reimplemented from
the public ORC v1 specification, not translated: the hot decoders
(RLEv1/RLEv2, bit-unpack, byte-RLE) vectorize into numpy and the column
assembly produces the engine's null-mask/dictionary columnar layout
directly.

Format essentials (ORC spec):
- file tail: ...stripes | metadata | footer | postscript | ps_length(1B)
- protobuf messages throughout (hand-rolled tag/varint parser below)
- every compressed region is framed in chunks with a 3-byte header
  ``(length << 1) | is_original`` (little-endian)
- integers use RLEv1 (runs + literals of varints) or RLEv2 (SHORT_REPEAT
  / DIRECT / PATCHED_BASE / DELTA sub-encodings, bit-packed)
- nulls ride PRESENT streams (bit-per-value, byte-RLE framed)
- strings are DIRECT (bytes + lengths) or DICTIONARY (codes + dict)

Verified against pyarrow's ORC writer in both directions
(tests/test_orc.py): none/zlib/snappy compression, all engine scalar
types, null patterns, multi-stripe files, and stripe-stats pruning.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Optional

import numpy as np

from trino_tpu import types as T
from trino_tpu.columnar import Batch, Column, Dictionary

# --- minimal protobuf ------------------------------------------------------


def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _proto(buf: bytes) -> dict[int, list]:
    """Parse one protobuf message into {field: [values]}; length-delimited
    values stay bytes, varints stay ints."""
    out: dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _varint(buf, pos)
        elif wire == 1:
            v = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def _first(msg: dict, field: int, default=None):
    vals = msg.get(field)
    return vals[0] if vals else default


def _uints(msg: dict, field: int) -> list[int]:
    """Repeated uint field: entries may be plain varints or PACKED bytes."""
    out: list[int] = []
    for v in msg.get(field, []):
        if isinstance(v, int):
            out.append(v)
        else:
            pos = 0
            while pos < len(v):
                u, pos = _varint(v, pos)
                out.append(u)
    return out


def _zigzag_int(u: int) -> int:
    """Zigzag-decode one unsigned Python int (exact for any u < 2**64)."""
    return (u >> 1) ^ -(u & 1)


def _zigzag_u64(u: np.ndarray) -> np.ndarray:
    """Zigzag-decode a uint64 array into int64 (bit-exact: the xor runs in
    unsigned space; going through int64 first would overflow for values
    >= 2**63 and arithmetic-shift already-negative lanes)."""
    one = np.uint64(1)
    return ((u >> one) ^ (np.uint64(0) - (u & one))).view(np.int64)


# --- compression framing ---------------------------------------------------

COMPRESSION_NONE = 0
COMPRESSION_ZLIB = 1
COMPRESSION_SNAPPY = 2
COMPRESSION_ZSTD = 5


def _decompress(data: bytes, kind: int) -> bytes:
    if kind == COMPRESSION_NONE:
        return data
    out = bytearray()
    pos = 0
    while pos + 3 <= len(data):
        header = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
        pos += 3
        length = header >> 1
        original = header & 1
        chunk = data[pos : pos + length]
        pos += length
        if original:
            out.extend(chunk)
        elif kind == COMPRESSION_ZLIB:
            out.extend(zlib.decompress(chunk, -15))  # raw deflate
        elif kind == COMPRESSION_SNAPPY:
            from trino_tpu.native import snappy_decompress

            # snappy block: leading varint = uncompressed length
            ulen, p = _varint(chunk, 0)
            out.extend(snappy_decompress(chunk, ulen))
        elif kind == COMPRESSION_ZSTD:
            raise ValueError("zstd-compressed ORC is not supported")
        else:
            raise ValueError(f"unknown ORC compression kind {kind}")
    return bytes(out)


# --- integer decoders ------------------------------------------------------


def _read_varints(buf: bytes, count: int, pos: int = 0):
    out = np.empty(count, dtype=np.uint64)
    for i in range(count):
        v, pos = _varint(buf, pos)
        out[i] = v & 0xFFFFFFFFFFFFFFFF
    return out, pos


def _rle_v1(buf: bytes, count: int, signed: bool) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_rle1(buf, count, signed)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    filled = 0
    while filled < count:
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:  # run
            run = ctrl + 3
            delta = buf[pos]
            delta = delta - 256 if delta >= 128 else delta
            pos += 1
            base, pos = _varint(buf, pos)
            base = _zigzag_int(base) if signed else base
            out[filled : filled + run] = base + delta * np.arange(run)
            filled += run
        else:  # literals
            lit = 256 - ctrl
            vals, pos = _read_varints(buf, lit, pos)
            v = _zigzag_u64(vals) if signed else vals.astype(np.int64)
            out[filled : filled + lit] = v
            filled += lit
    return out


_RLE2_WIDTHS = [
    1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64,
    72, 80, 88, 96, 104, 112, 120, 128,
]  # 5-bit code -> bits (codes 0..4 are 1,2,4,8,16? spec: deprecated map)


def _fbw(code: int) -> int:
    """Decode the 5-bit "fixed bit width" code (ORC spec table)."""
    if code <= 23:
        return code + 1
    return {24: 26, 25: 28, 26: 30, 27: 32, 28: 40, 29: 48, 30: 56, 31: 64}[code]


_FIXED_WIDTHS = list(range(1, 25)) + [26, 28, 30, 32, 40, 48, 56, 64]


def _closest_fixed_bits(n: int) -> int:
    """Round up to the nearest encodable width (patch entries pack at
    closestFixedBits(gapWidth + patchWidth))."""
    for w in _FIXED_WIDTHS:
        if w >= n:
            return w
    return 64


def _unpack_bits(buf: bytes, count: int, width: int, pos: int):
    """Big-endian bit-unpack `count` values of `width` bits."""
    nbits = count * width
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(buf, dtype=np.uint8, count=nbytes, offset=pos)
    bits = np.unpackbits(raw)[: count * width].reshape(count, width)
    weights = (1 << np.arange(width - 1, -1, -1, dtype=np.uint64))
    vals = (bits.astype(np.uint64) * weights).sum(axis=1)
    return vals, pos + nbytes


def _rle_v2(buf: bytes, count: int, signed: bool) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_rle2(buf, count, signed)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    filled = 0
    while filled < count:
        first = buf[pos]
        enc = first >> 6
        if enc == 0:  # SHORT_REPEAT
            width = ((first >> 3) & 0x7) + 1
            repeat = (first & 0x7) + 3
            pos += 1
            val = int.from_bytes(buf[pos : pos + width], "big")
            pos += width
            if signed:
                val = _zigzag_int(val)
            out[filled : filled + repeat] = val
            filled += repeat
        elif enc == 1:  # DIRECT
            width = _fbw((first >> 1) & 0x1F)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_bits(buf, length, width, pos)
            v = _zigzag_u64(vals.astype(np.uint64)) if signed else vals.astype(np.int64)
            out[filled : filled + length] = v
            filled += length
        elif enc == 3:  # DELTA
            width_code = (first >> 1) & 0x1F
            width = 0 if width_code == 0 else _fbw(width_code)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            base, pos = _varint(buf, pos)
            base = _zigzag_int(base) if signed else base
            delta0, pos = _varint(buf, pos)
            delta0 = _zigzag_int(delta0)
            seq = np.empty(length, dtype=np.int64)
            seq[0] = base
            if length > 1:
                if width == 0:
                    deltas = np.full(length - 1, delta0, dtype=np.int64)
                else:
                    rest, pos = _unpack_bits(buf, length - 2, width, pos)
                    deltas = np.empty(length - 1, dtype=np.int64)
                    deltas[0] = delta0
                    sign = 1 if delta0 >= 0 else -1
                    deltas[1:] = sign * rest.astype(np.int64)
                seq[1:] = base + np.cumsum(deltas)
            out[filled : filled + length] = seq
            filled += length
        else:  # PATCHED_BASE
            width = _fbw((first >> 1) & 0x1F)
            length = ((first & 1) << 8 | buf[pos + 1]) + 1
            third, fourth = buf[pos + 2], buf[pos + 3]
            base_width = ((third >> 5) & 0x7) + 1
            patch_width = _fbw(third & 0x1F)
            patch_gap_width = ((fourth >> 5) & 0x7) + 1
            patch_count = fourth & 0x1F
            pos += 4
            base_raw = int.from_bytes(buf[pos : pos + base_width], "big")
            pos += base_width
            msb = 1 << (base_width * 8 - 1)
            base = -(base_raw & ~msb) if base_raw & msb else base_raw
            vals, pos = _unpack_bits(buf, length, width, pos)
            patch_bits = _closest_fixed_bits(patch_width + patch_gap_width)
            patches, pos = _unpack_bits(buf, patch_count, patch_bits, pos)
            vals = vals.astype(np.int64)
            idx = 0
            for p in patches:
                gap = int(p) >> patch_width
                patch = int(p) & ((1 << patch_width) - 1)
                idx += gap
                vals[idx] |= patch << width
            out[filled : filled + length] = base + vals
            filled += length
    return out


def _byte_rle(buf: bytes, count: int) -> np.ndarray:
    from trino_tpu import native

    fast = native.orc_byte_rle(buf, count)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.uint8)
    pos = 0
    filled = 0
    while filled < count:
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:
            run = ctrl + 3
            out[filled : filled + run] = buf[pos]
            pos += 1
            filled += run
        else:
            lit = 256 - ctrl
            out[filled : filled + lit] = np.frombuffer(
                buf, dtype=np.uint8, count=lit, offset=pos
            )
            pos += lit
            filled += lit
    return out


def _bool_rle(buf: bytes, count: int) -> np.ndarray:
    nbytes = (count + 7) // 8
    b = _byte_rle(buf, nbytes)
    return np.unpackbits(b)[:count].astype(bool)


def _decimal_varints(buf: bytes, count: int) -> np.ndarray:
    """Decimal DATA: unbounded zigzag varints (values beyond int64 raise —
    wide decimal ORC columns arrive via the (hi, lo) path)."""
    from trino_tpu import native

    fast = native.orc_decimal64(buf, count)
    if fast is not None:
        return fast
    out = np.empty(count, dtype=np.int64)
    pos = 0
    for i in range(count):
        v, pos = _varint(buf, pos)
        out[i] = _zigzag_int(v & 0xFFFFFFFFFFFFFFFF)
    return out


# --- schema ---------------------------------------------------------------

KIND_BOOLEAN = 0
KIND_BYTE = 1
KIND_SHORT = 2
KIND_INT = 3
KIND_LONG = 4
KIND_FLOAT = 5
KIND_DOUBLE = 6
KIND_STRING = 7
KIND_BINARY = 8
KIND_TIMESTAMP = 9
KIND_LIST = 10
KIND_MAP = 11
KIND_STRUCT = 12
KIND_UNION = 13
KIND_DECIMAL = 14
KIND_DATE = 15
KIND_VARCHAR = 16
KIND_CHAR = 17

STREAM_PRESENT = 0
STREAM_DATA = 1
STREAM_LENGTH = 2
STREAM_DICTIONARY_DATA = 3
STREAM_SECONDARY = 5
STREAM_ROW_INDEX = 6

ENC_DIRECT = 0
ENC_DICTIONARY = 1
ENC_DIRECT_V2 = 2
ENC_DICTIONARY_V2 = 3


@dataclasses.dataclass
class OrcType:
    kind: int
    subtypes: list[int]
    field_names: list[str]
    precision: int = 0
    scale: int = 0

    def sql_type(self):
        if self.kind in (KIND_BOOLEAN,):
            return T.BOOLEAN
        if self.kind in (KIND_BYTE, KIND_SHORT, KIND_INT, KIND_LONG):
            return T.BIGINT
        if self.kind in (KIND_FLOAT, KIND_DOUBLE):
            return T.DOUBLE
        if self.kind in (KIND_STRING, KIND_VARCHAR, KIND_CHAR):
            return T.VARCHAR
        if self.kind == KIND_DATE:
            return T.DATE
        if self.kind == KIND_DECIMAL:
            return T.decimal(self.precision or 38, self.scale)
        raise ValueError(f"unsupported ORC type kind {self.kind}")


@dataclasses.dataclass
class StripeInfo:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    num_rows: int


@dataclasses.dataclass
class ColumnStats:
    num_values: Optional[int]
    has_null: bool
    min_value: Optional[object]
    max_value: Optional[object]


class OrcFile:
    """Parsed tail + stripe directory of one ORC file."""

    MAGIC = b"ORC"

    def __init__(self, data: bytes):
        self.data = data
        if len(data) < 16:
            raise ValueError("not an ORC file (too short)")
        ps_len = data[-1]
        ps = _proto(data[-1 - ps_len : -1])
        self.compression = _first(ps, 2, 0)
        footer_len = _first(ps, 1, 0)
        meta_len = _first(ps, 5, 0)
        magic = _first(ps, 8000, b"")
        if magic != self.MAGIC and not data.startswith(self.MAGIC):
            raise ValueError("not an ORC file (missing magic)")
        tail = len(data) - 1 - ps_len
        footer = _proto(_decompress(data[tail - footer_len : tail], self.compression))
        meta_buf = data[tail - footer_len - meta_len : tail - footer_len]
        self.metadata = (
            _proto(_decompress(meta_buf, self.compression)) if meta_len else {}
        )
        self.num_rows = _first(footer, 6, 0)
        self.types: list[OrcType] = []
        for tbytes in footer.get(4, []):
            m = _proto(tbytes)
            self.types.append(
                OrcType(
                    kind=_first(m, 1, 0),
                    subtypes=_uints(m, 2),
                    field_names=[v.decode() for v in m.get(3, [])],
                    precision=_first(m, 5, 0),
                    scale=_first(m, 6, 0),
                )
            )
        self.stripes: list[StripeInfo] = []
        for sbytes in footer.get(3, []):
            m = _proto(sbytes)
            self.stripes.append(
                StripeInfo(
                    offset=_first(m, 1, 0),
                    index_length=_first(m, 2, 0),
                    data_length=_first(m, 3, 0),
                    footer_length=_first(m, 4, 0),
                    num_rows=_first(m, 5, 0),
                )
            )
        # column order: root struct's children
        root = self.types[0]
        if root.kind != KIND_STRUCT:
            raise ValueError("ORC root type must be a struct")
        self.column_names = root.field_names
        self.column_type_ids = root.subtypes

    # --- statistics ------------------------------------------------------

    def stripe_stats(self, stripe_index: int) -> dict[int, ColumnStats]:
        """Per-type-id stats for one stripe (Metadata.stripeStats)."""
        entries = self.metadata.get(1, [])
        if stripe_index >= len(entries):
            return {}
        per_col = _proto(entries[stripe_index]).get(1, [])
        out = {}
        for type_id, cbytes in enumerate(per_col):
            out[type_id] = _parse_col_stats(
                cbytes, self.types[type_id] if type_id < len(self.types) else None
            )
        return out

    # --- stripe reading --------------------------------------------------

    def read_stripe(
        self, stripe: StripeInfo, want: Optional[set[str]] = None
    ) -> dict[str, Column]:
        sf_off = stripe.offset + stripe.index_length + stripe.data_length
        sfooter = _proto(
            _decompress(
                self.data[sf_off : sf_off + stripe.footer_length],
                self.compression,
            )
        )
        streams = []
        pos = stripe.offset
        for sbytes in sfooter.get(1, []):
            m = _proto(sbytes)
            kind = _first(m, 1, 0)
            column = _first(m, 2, 0)
            length = _first(m, 3, 0)
            streams.append((kind, column, pos, length))
            pos += length
        encodings = [
            (_first(_proto(e), 1, 0), _first(_proto(e), 2, 0))
            for e in sfooter.get(2, [])
        ]

        def stream(col_id: int, kind: int) -> Optional[bytes]:
            for k, c, off, ln in streams:
                if c == col_id and k == kind:
                    return _decompress(
                        self.data[off : off + ln], self.compression
                    )
            return None

        out: dict[str, Column] = {}
        for name, type_id in zip(self.column_names, self.column_type_ids):
            if want is not None and name not in want:
                continue
            out[name] = self._read_column(
                type_id, stripe.num_rows, stream, encodings
            )
        return out

    def _read_column(self, type_id, num_rows, stream, encodings) -> Column:
        t = self.types[type_id]
        enc = encodings[type_id][0] if type_id < len(encodings) else ENC_DIRECT
        present = stream(type_id, STREAM_PRESENT)
        if present is not None:
            valid = _bool_rle(present, num_rows)
            n_present = int(valid.sum())
        else:
            valid = None
            n_present = num_rows

        def expand(vals: np.ndarray, fill=0) -> np.ndarray:
            if valid is None:
                return vals
            out = np.full(num_rows, fill, dtype=vals.dtype)
            out[valid] = vals
            return out

        data = stream(type_id, STREAM_DATA)
        v2 = enc in (ENC_DIRECT_V2, ENC_DICTIONARY_V2)
        rle = _rle_v2 if v2 else _rle_v1

        if t.kind in (KIND_SHORT, KIND_INT, KIND_LONG):
            vals = rle(data, n_present, signed=True)
            return Column(T.BIGINT, expand(vals), valid)
        if t.kind == KIND_DATE:
            vals = rle(data, n_present, signed=True)
            return Column(T.DATE, expand(vals).astype(np.int32), valid)
        if t.kind == KIND_BYTE:
            vals = _byte_rle(data, n_present).astype(np.int8).astype(np.int64)
            return Column(T.BIGINT, expand(vals), valid)
        if t.kind == KIND_BOOLEAN:
            vals = _bool_rle(data, n_present)
            return Column(T.BOOLEAN, expand(vals), valid)
        if t.kind in (KIND_FLOAT, KIND_DOUBLE):
            width = 4 if t.kind == KIND_FLOAT else 8
            dt = np.float32 if t.kind == KIND_FLOAT else np.float64
            vals = np.frombuffer(data, dtype=np.dtype(dt).newbyteorder("<"),
                                 count=n_present).astype(np.float64)
            return Column(T.DOUBLE, expand(vals), valid)
        if t.kind == KIND_DECIMAL:
            vals = _decimal_varints(data, n_present)
            secondary = stream(type_id, STREAM_SECONDARY)
            scales = rle(secondary, n_present, signed=True)
            target = t.scale
            diff = target - scales
            # normalize to declared scale (writers emit per-value scales)
            vals = np.where(
                diff >= 0,
                vals * (10 ** np.clip(diff, 0, None)),
                vals // (10 ** np.clip(-diff, 0, None)),
            )
            return Column(t.sql_type(), expand(vals), valid)
        if t.kind in (KIND_STRING, KIND_VARCHAR, KIND_CHAR):
            if enc in (ENC_DICTIONARY, ENC_DICTIONARY_V2):
                codes = rle(data, n_present, signed=False)
                dict_data = stream(type_id, STREAM_DICTIONARY_DATA) or b""
                lengths = rle(
                    stream(type_id, STREAM_LENGTH), encodings[type_id][1],
                    signed=False,
                )
                offs = np.concatenate([[0], np.cumsum(lengths)])
                values = [
                    dict_data[offs[i] : offs[i + 1]].decode("utf-8")
                    for i in range(len(lengths))
                ]
                d = Dictionary(values)
                out_codes = expand(codes.astype(np.int32), fill=-1)
                return Column(T.VARCHAR, out_codes, valid, d)
            lengths = rle(stream(type_id, STREAM_LENGTH), n_present, signed=False)
            offs = np.concatenate([[0], np.cumsum(lengths)])
            strings = [
                data[offs[i] : offs[i + 1]].decode("utf-8")
                for i in range(n_present)
            ]
            d, codes = Dictionary.from_strings(strings)
            return Column(T.VARCHAR, expand(codes, fill=-1), valid, d)
        raise ValueError(f"unsupported ORC column kind {t.kind}")


def _parse_col_stats(cbytes: bytes, t: Optional[OrcType]) -> ColumnStats:
    m = _proto(cbytes)
    num = _first(m, 1)
    has_null = bool(_first(m, 10, 0))
    mn = mx = None
    if 2 in m:  # integers
        s = _proto(m[2][0])
        mn = _signed_varint(_first(s, 1))
        mx = _signed_varint(_first(s, 2))
    elif 7 in m:  # date
        s = _proto(m[7][0])
        mn = _signed_varint(_first(s, 1))
        mx = _signed_varint(_first(s, 2))
    elif 4 in m:  # string
        s = _proto(m[4][0])
        mn = _first(s, 1)
        mx = _first(s, 2)
        mn = mn.decode() if mn is not None else None
        mx = mx.decode() if mx is not None else None
    elif 3 in m:  # double
        s = _proto(m[3][0])
        mn = _f64(_first(s, 1))
        mx = _f64(_first(s, 2))
    elif 6 in m:  # decimal (strings)
        s = _proto(m[6][0])
        mn = _first(s, 1)
        mx = _first(s, 2)
        mn = mn.decode() if mn is not None else None
        mx = mx.decode() if mx is not None else None
    return ColumnStats(num, has_null, mn, mx)


def _signed_varint(v):
    """sint64 fields arrive zigzag-encoded by protobuf."""
    if v is None:
        return None
    return _zigzag_int(v & 0xFFFFFFFFFFFFFFFF)


def _f64(v):
    if v is None:
        return None
    return float(np.frombuffer(v, dtype="<f8")[0])


def read_orc(path: str, columns: Optional[list[str]] = None) -> Batch:
    """Read a whole ORC file into one Batch (column subset optional)."""
    from trino_tpu.columnar import concat_batches

    with open(path, "rb") as f:
        data = f.read()
    f = OrcFile(data)
    want = set(columns) if columns is not None else None
    names = [
        n for n in f.column_names if want is None or n in want
    ]
    batches = []
    for stripe in f.stripes:
        cols = f.read_stripe(stripe, want)
        batches.append(
            Batch([cols[n] for n in names], stripe.num_rows)
        )
    if not batches:
        return Batch([], 0)
    return concat_batches(batches) if len(batches) > 1 else batches[0]