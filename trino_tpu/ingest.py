"""Device-resident columnar ingest tier.

After whole-pipeline fusion the kernels are no longer the bottleneck —
feeding them is: BENCH_r04 measured 37.7M rows/s in-kernel vs 0.9M rows/s
once host->device transfer is included, with a ~150us DMA latency floor
per transfer. This module closes that gap from three directions:

- **Coalesced H2D** (:func:`shard_batch_coalesced`): instead of one
  ``device_put`` per column per device (``parallel/mesh.py::_global``),
  every packable buffer of a shard — column data, validity lanes, the
  selection mask — is packed into ONE contiguous uint32 staging arena
  (native hot loop ``tt_pack_arena``, numpy fallback) and moved with a
  single transfer per device, then sliced back into columns *on device*
  by a cached shard_map program. One DMA latency amortizes across all
  columns, and the transfer dispatches async so it rides under compute.
  int64 moves as interleaved lo/hi uint32 word lanes (TPU x64 rewriting
  forbids 64-bit bitcasts) and is reconstructed exactly on device;
  float64 columns fall back to per-column placement.

- **Double-buffered decode** (:class:`SplitPrefetcher`): a two-slot
  pipeline where a background thread decodes split k+1 (Parquet/ORC
  chunk -> host columnar batch, the C hot loops in native/columnar.cpp)
  while the device executes over split k.

- **Device table cache** (:class:`DeviceTableCache`): the table-serving
  analogue of the cross-query program cache. Scanned tables stay
  HBM-resident keyed by (catalog, schema, table, data version,
  projection, split fingerprint, mesh), with a byte-budget LRU whose
  admission consults the device profiler's peak-HBM accounting — a warm
  repeat scan issues zero H2D bytes.

Reference: Trino keeps hot pages pinned in the worker heap
(``MemoryPool`` / ``PageCache``); HBM plays that role here.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.columnar import Batch, Column
from trino_tpu.parallel.mesh import (
    AXIS,
    _global,
    prepare_shards,
    row_sharding,
    smap,
)
from jax.sharding import PartitionSpec as PS

# === arena layout ===========================================================
#
# A segment is one host buffer's image in the arena: raw little-endian
# bytes at a word-aligned offset (zero tail padding). The device unpack
# program rebuilds each array from its word span:
#   - 4-byte dtypes: 32-bit bitcast (allowed on TPU)
#   - sub-word dtypes (bool/int8/int16): bitcast to lanes, slice to n
#   - 8-byte ints: interleaved (lo, hi) word pairs -> hi*2^32 + lo
# float64 has no TPU-legal reconstruction (64-bit bitcast is forbidden
# and arithmetic reassembly is inexact), so DOUBLE columns bypass the
# arena via per-column device_put.

_PACKABLE = {
    np.dtype(np.bool_),
    np.dtype(np.int8),
    np.dtype(np.uint8),
    np.dtype(np.int16),
    np.dtype(np.uint16),
    np.dtype(np.int32),
    np.dtype(np.uint32),
    np.dtype(np.float32),
    np.dtype(np.int64),
    np.dtype(np.uint64),
}


def packable(dtype) -> bool:
    return np.dtype(dtype) in _PACKABLE


def _segment_words(dtype, shape) -> int:
    nbytes = math.prod(shape) * np.dtype(dtype).itemsize
    return (nbytes + 3) // 4


def _unpack_segment(words, off: int, dtype, shape):
    """Rebuild one array from its word span (traced, runs on device)."""
    dt = np.dtype(dtype)
    n = math.prod(shape)
    w = _segment_words(dt, shape)
    seg = jax.lax.slice_in_dim(words, off, off + w)
    if dt.itemsize == 8:
        pair = seg.reshape(n, 2)  # interleaved (lo, hi), little-endian
        lo = pair[:, 0]
        if dt == np.dtype(np.uint64):
            out = (pair[:, 1].astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
        else:
            hi = jax.lax.bitcast_convert_type(pair[:, 1], jnp.int32)
            # exact two's-complement reassembly: sign-extended high word
            # times 2^32 plus zero-extended low word
            out = hi.astype(jnp.int64) * jnp.int64(1 << 32) + lo.astype(
                jnp.int64
            )
    elif dt.itemsize == 4:
        out = jax.lax.bitcast_convert_type(seg, dt)
    else:
        lane_dt = np.dtype(np.uint8) if dt == np.dtype(np.bool_) else dt
        lanes = jax.lax.bitcast_convert_type(seg, lane_dt)
        out = lanes.reshape(-1)[:n]
        if dt == np.dtype(np.bool_):
            out = out.astype(jnp.bool_)
    return out.reshape(shape), off + w


# one compiled unpack program per (mesh, segment signature); bounded so
# pathological shape churn cannot leak programs
_UNPACK_CACHE: "OrderedDict[tuple, Any]" = OrderedDict()
_UNPACK_CACHE_MAX = 64
_UNPACK_LOCK = threading.Lock()


def _unpack_program(mesh, signature: tuple):
    key = (mesh, signature)
    with _UNPACK_LOCK:
        fn = _UNPACK_CACHE.get(key)
        if fn is not None:
            _UNPACK_CACHE.move_to_end(key)
            return fn

    def unpack_shard(words):
        outs = []
        off = 0
        for dtype, shape in signature:
            arr, off = _unpack_segment(words, off, dtype, shape)
            outs.append(arr)
        return tuple(outs)

    fn = jax.jit(
        smap(
            unpack_shard,
            mesh=mesh,
            in_specs=PS(AXIS),
            out_specs=tuple(PS(AXIS) for _ in signature),
        )
    )
    with _UNPACK_LOCK:
        _UNPACK_CACHE[key] = fn
        while len(_UNPACK_CACHE) > _UNPACK_CACHE_MAX:
            _UNPACK_CACHE.popitem(last=False)
    return fn


# Below this many total bytes the coalescing can't pay for itself even
# on a real chip: a cold scan is unpack-program-cold too, so a handful
# of per-column transfers at the ~150us DMA floor costs less than the
# first-touch XLA compile of the unpack program (warm repeats skip H2D
# entirely via the table cache, so only cold scans ever face this
# trade). Small scans take the per-column path, H2D still accounted.
# Session property `coalesce_min_bytes` overrides per query.
COALESCE_MIN_BYTES = 1 << 23


def _batch_buffer_bytes(parts: Sequence[Batch]) -> tuple[int, int]:
    """(total bytes, buffer count) across all column/validity/capacity
    buffers — the transfer volume estimate gating coalescing."""
    total = 0
    bufs = 0
    for p in parts:
        for c in p.columns:
            total += c.data.nbytes
            bufs += 1
            if c.valid is not None:
                total += np.asarray(c.valid).nbytes
                bufs += 1
    return total, bufs


def shard_batch_coalesced(
    mesh,
    parts: Sequence[Batch],
    use_native: bool = True,
    stats: Optional[dict] = None,
    min_bytes: int = COALESCE_MIN_BYTES,
) -> Batch:
    """Assemble per-shard host batches into one globally-sharded Batch
    with ONE coalesced H2D transfer per device.

    Bit-identical to ``parallel/mesh.py::shard_batch`` (both build on
    :func:`prepare_shards`); only the transport differs. ``stats`` (the
    executor's ingest counters) receives h2d byte/transfer accounting.
    Scans under ``min_bytes`` delegate to the per-column path — the
    arena only wins once the transfer volume amortizes the unpack
    program's compile.
    """
    from trino_tpu.native import pack_arena

    n = mesh.devices.size
    est_bytes, est_bufs = _batch_buffer_bytes(parts)
    if est_bytes < min_bytes:
        from trino_tpu.obs.metrics import get_registry
        from trino_tpu.parallel.mesh import shard_batch

        batch = shard_batch(mesh, parts)
        get_registry().counter("trino_tpu_ingest_h2d_bytes_total").inc(
            est_bytes
        )
        if stats is not None:
            stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + est_bytes
            stats["h2d_transfers"] = (
                stats.get("h2d_transfers", 0) + est_bufs
            )
        return batch

    cap, sels, columns = prepare_shards(mesh, parts)
    sharding = row_sharding(mesh)

    # split buffers into arena segments vs per-column fallbacks
    signature: list[tuple] = []  # (dtype, per-shard shape)
    slots: list[tuple] = []  # ("sel",) | ("data", j) | ("valid", j)
    per_part: list[list[np.ndarray]] = [[] for _ in range(n)]

    def add_segment(slot, arrays):
        signature.append((arrays[0].dtype, arrays[0].shape))
        slots.append(slot)
        for i, a in enumerate(arrays):
            per_part[i].append(a)

    if sels is not None:
        add_segment(("sel",), sels)
    fallback: list[tuple] = []  # (slot, arrays)
    for j, (t, d, datas, valids) in enumerate(columns):
        if packable(datas[0].dtype):
            add_segment(("data", j), datas)
        else:
            fallback.append((("data", j), datas))
        if valids is not None:
            add_segment(("valid", j), valids)

    if not signature:
        # nothing packable (e.g. all-DOUBLE projection): plain path
        from trino_tpu.parallel.mesh import shard_batch

        return shard_batch(mesh, parts)

    t0 = time.perf_counter()
    arenas = [pack_arena(bufs, use_native=use_native) for bufs in per_part]
    words = arenas[0].size
    arena_g = _global(mesh, sharding, arenas)
    outs = _unpack_program(mesh, tuple(signature))(arena_g)

    # per-column device_put for non-arena dtypes (float64)
    results: dict[tuple, Any] = dict(zip(slots, outs))
    fallback_bytes = 0
    for slot, arrays in fallback:
        results[slot] = _global(mesh, sharding, arrays)
        fallback_bytes += sum(a.nbytes for a in arrays)

    total_bytes = n * words * 4 + fallback_bytes
    h2d_ms = (time.perf_counter() - t0) * 1000.0
    from trino_tpu.obs.metrics import get_registry
    from trino_tpu.obs.trace import get_tracer

    get_registry().counter("trino_tpu_ingest_h2d_bytes_total").inc(
        total_bytes
    )
    get_tracer().record(
        "ingest.h2d",
        h2d_ms,
        attrs={"bytes": total_bytes, "transfers": n + len(fallback) * n},
    )
    if stats is not None:
        stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + total_bytes
        stats["h2d_transfers"] = (
            stats.get("h2d_transfers", 0) + n + len(fallback) * n
        )
        stats["coalesced_columns"] = stats.get("coalesced_columns", 0) + len(
            columns
        ) - len(fallback)
        stats["fallback_columns"] = stats.get("fallback_columns", 0) + len(
            fallback
        )
        stats["h2d_ms"] = round(stats.get("h2d_ms", 0.0) + h2d_ms, 3)

    cols: list[Column] = []
    for j, (t, d, _datas, valids) in enumerate(columns):
        data_g = results[("data", j)]
        valid_g = None if valids is None else results[("valid", j)]
        cols.append(Column(t, data_g, valid_g, d))
    sel = None if sels is None else results[("sel",)]
    return Batch(cols, cap * n, sel)


# === double-buffered split decode ===========================================


class SplitPrefetcher:
    """Two-slot decode pipeline: a background thread decodes split k+1
    while the caller consumes split k, so host-side Parquet/ORC decode
    overlaps device execution instead of serializing ahead of it.

    Exactly two staging slots are live at any time (one being consumed,
    one being filled) — the bounded queue is the double buffer. Decode
    exceptions propagate to the consumer in order.
    """

    _SENTINEL = object()

    def __init__(
        self,
        fn: Callable[[Any], Any],
        items: Sequence[Any],
        enabled: bool = True,
        stats: Optional[dict] = None,
    ):
        self._fn = fn
        self._items = list(items)
        self._enabled = enabled and len(self._items) > 1
        self._stats = stats

    def _decode(self, item):
        t0 = time.perf_counter()
        out = self._fn(item)
        ms = (time.perf_counter() - t0) * 1000.0
        if self._stats is not None:
            self._stats["decode_ms"] = round(
                self._stats.get("decode_ms", 0.0) + ms, 3
            )
            self._stats["splits_decoded"] = (
                self._stats.get("splits_decoded", 0) + 1
            )
        from trino_tpu.obs.metrics import get_registry

        get_registry().histogram("trino_tpu_ingest_decode_ms").observe(ms)
        return out

    def __iter__(self):
        if not self._enabled:
            for item in self._items:
                yield self._decode(item)
            return
        import queue

        q: "queue.Queue" = queue.Queue(maxsize=1)
        stop = threading.Event()

        def worker():
            try:
                for item in self._items:
                    if stop.is_set():
                        break  # consumer bailed (limit): skip the tail
                    q.put(("ok", self._decode(item)))
            except BaseException as e:  # noqa: BLE001 — re-raised by consumer
                q.put(("err", e))
            finally:
                q.put((None, self._SENTINEL))

        t = threading.Thread(
            target=worker, name="tt-ingest-decode", daemon=True
        )
        t.start()
        try:
            while True:
                kind, payload = q.get()
                if payload is self._SENTINEL:
                    break
                if kind == "err":
                    raise payload
                yield payload
        finally:
            # unblock the producer if the consumer stops early (limit hint)
            stop.set()
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)


# === device-resident table cache ============================================


def hbm_headroom_ok(
    nbytes: int, peak_hbm_hint: int = 0, fraction: float = 0.9
) -> bool:
    """Admission check against real device memory: would pinning
    ``nbytes`` more HBM (on top of current use plus the profiler's peak
    program footprint) exceed ``fraction`` of the device limit? Backends
    without memory_stats (CPU meshes) admit — the byte budget still
    bounds the cache."""
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
        limit = int(ms.get("bytes_limit") or 0)
        in_use = int(ms.get("bytes_in_use") or 0)
        if limit:
            return in_use + nbytes + peak_hbm_hint <= fraction * limit
    except Exception:  # noqa: BLE001 — accounting must never fail a query
        pass
    return True


def device_hbm_limit() -> int:
    """The device's reported HBM byte limit, or 0 when the backend has no
    memory accounting (CPU meshes) — callers treat 0 as "gate inert"."""
    try:
        ms = jax.local_devices()[0].memory_stats() or {}
        return int(ms.get("bytes_limit") or 0)
    except Exception:  # noqa: BLE001
        return 0


def splits_fingerprint(splits: Sequence) -> str:
    """Stable identity of a split list. File-backed connectors encode
    (path, chunk) pairs in split info, so INSERT-appended part files
    change the fingerprint and naturally invalidate cached tables."""
    blob = repr([(s.index, s.total, s.info) for s in splits])
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def parts_delta(old_parts, new_parts):
    """Classify a part-version transition (pairs from connector
    ``data_versions()``): ``("same", [])`` when identical,
    ``("append", new_ids)`` when every old ``(id, token)`` pair survives
    untouched and only new ids were added, else ``("changed", [])``.
    Drives the result cache's maintain-vs-invalidate decision; anything
    ambiguous (duplicate ids, removed or re-tokened parts) is "changed"."""
    old = dict(old_parts)
    new = dict(new_parts)
    if len(old) != len(old_parts) or len(new) != len(new_parts):
        return "changed", []
    if old == new:
        return "same", []
    appended = [pid for pid, _ in new_parts if pid not in old]
    if not appended or len(new) != len(old) + len(appended):
        return "changed", []
    for pid, tok in old_parts:
        if new.get(pid) != tok:
            return "changed", []
    return "append", appended


class DeviceTableCache:
    """Byte-budget LRU of HBM-resident scanned tables.

    Keys carry the catalog's data version and the split-list fingerprint,
    so mutation (memory-connector ``_version`` bump, part-file append)
    misses naturally instead of serving stale rows. Admission consults
    :func:`hbm_headroom_ok` with the device profiler's peak-HBM hint so a
    cached table cannot crowd out the programs that read it.
    """

    def __init__(self):
        self._entries: "OrderedDict[tuple, tuple[Batch, int]]" = OrderedDict()
        self._lock = threading.Lock()
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.rejections = 0

    def lookup(self, key: tuple) -> Optional[Batch]:
        from trino_tpu.obs.metrics import get_registry

        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                get_registry().counter(
                    "trino_tpu_table_cache_misses_total"
                ).inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        get_registry().counter("trino_tpu_table_cache_hits_total").inc()
        return ent[0]

    def admit(
        self,
        key: tuple,
        batch: Batch,
        nbytes: int,
        max_bytes: int,
        peak_hbm_hint: int = 0,
    ) -> bool:
        if nbytes > max_bytes or not hbm_headroom_ok(nbytes, peak_hbm_hint):
            with self._lock:
                self.rejections += 1
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.total_bytes -= old[1]
            while self._entries and self.total_bytes + nbytes > max_bytes:
                _, (_b, nb) = self._entries.popitem(last=False)
                self.total_bytes -= nb
                self.evictions += 1
            self._entries[key] = (batch, nbytes)
            self.total_bytes += nbytes
        return True

    def invalidate(self, catalog: Optional[str] = None) -> int:
        """Drop entries (all, or one catalog's). Version/fingerprint keys
        already make stale entries unreachable; this frees their HBM."""
        with self._lock:
            if catalog is None:
                dropped = len(self._entries)
                self._entries.clear()
                self.total_bytes = 0
                return dropped
            doomed = [k for k in self._entries if k[0] == catalog]
            for k in doomed:
                _b, nb = self._entries.pop(k)
                self.total_bytes -= nb
            return len(doomed)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.total_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "rejections": self.rejections,
            }


def table_cache_key(
    catalog: str,
    schema: str,
    table: str,
    version: Any,
    column_names: Iterable[str],
    splits: Sequence,
    mesh,
) -> tuple:
    mesh_fp = tuple(str(d) for d in mesh.devices.flat)
    return (
        catalog,
        schema,
        table,
        version,
        tuple(column_names),
        splits_fingerprint(splits),
        mesh_fp,
    )
