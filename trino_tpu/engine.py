"""Statement execution engine: the coordinator's analyze/plan/execute core.

Reference: ``execution/SqlQueryExecution.java:373`` (the DQL path) plus the
``DataDefinitionTask`` short-circuit family (``execution/CreateTableTask.java``,
``DataDefinitionExecution.java``) for DDL/utility statements, and
``testing/LocalQueryRunner.java`` which drives the same core in-process.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from trino_tpu import types as T
from trino_tpu.analyzer import Analyzer, SemanticError
from trino_tpu.columnar import Batch
from trino_tpu.config import Session
from trino_tpu.connectors.api import CatalogManager, ColumnSchema, TableSchema
from trino_tpu.exec.local import ExecutionError, LocalExecutor
from trino_tpu.planner import plan as P
from trino_tpu.sql import parse_statement
from trino_tpu.sql import tree as t


@dataclasses.dataclass
class StatementResult:
    """What a statement produced (protocol-ready, host-side)."""

    rows: list[tuple]
    column_names: list[str]
    column_types: list[T.SqlType]
    update_type: Optional[str] = None  # e.g. "CREATE TABLE", "INSERT"
    update_count: Optional[int] = None
    set_session: dict[str, Any] = dataclasses.field(default_factory=dict)
    peak_memory_bytes: int = 0
    dynamic_filters: int = 0
    # prepared-statement session mutations (ride X-Trino-*-Prepare headers)
    added_prepare: Optional[tuple[str, str]] = None  # (name, sql)
    deallocated_prepare: Optional[str] = None
    # transaction mutations (X-Trino-Started-Transaction-Id / Clear-...)
    started_transaction_id: Optional[str] = None
    cleared_transaction: bool = False
    # cluster-mode retry/attempt counters (trino_tpu/ft): retry_policy,
    # task_retries, task_attempts, query_attempts — surfaced in /v1/query
    cluster_stats: dict[str, Any] = dataclasses.field(default_factory=dict)
    # skew-aware exchange counters (shuffle rows/bytes, padding ratio,
    # overflow retries, hot/salted keys) — surfaced in /v1/query
    exchange_stats: Optional[dict[str, Any]] = None
    # compile-time telemetry (cross-query program cache; Trino's
    # CacheStatsMBean analog) — surfaced in /v1/query
    compile_ms: float = 0.0  # trace+lower+compile wall paid by this query
    trace_count: int = 0  # programs traced (0 on a fully warm run)
    program_cache_hits: int = 0
    program_cache_misses: int = 0
    # device-level profiling rollup (obs/profiler.py): per-program XLA
    # FLOPs / bytes accessed / peak HBM + query totals — surfaced in
    # /v1/query as ``deviceStats``; None when profiling is off or the
    # backend reports nothing
    device_stats: Optional[dict[str, Any]] = None
    # columnar ingest tier (trino_tpu/ingest.py): split decode wall,
    # coalesced H2D bytes/transfers, device-table-cache hits/misses —
    # surfaced in /v1/query as ``ingestStats``; None when no scan ran
    ingest_stats: Optional[dict[str, Any]] = None
    # cross-query batching (exec/batching.py): batchedQueries/batchSize/
    # batchWaitMs for queries that shared a stacked dispatch — surfaced
    # in /v1/query queryStats; None when the query ran alone
    batch_stats: Optional[dict[str, Any]] = None
    # semantic result cache (trino_tpu/cache): resultCacheHit plus
    # incrementalMaintenance/deltaSplits when a statement was served (or
    # maintained) from the coordinator result cache; None on real runs
    result_cache_stats: Optional[dict[str, Any]] = None
    # in-program operator telemetry (exec/fragments.py op! channel):
    # {stable_site: {kind, rows_in, rows_out}} — surfaced in /v1/query as
    # ``operatorStats`` and as per-operator EXPLAIN ANALYZE rows; None
    # when operator_stats is off or nothing traced
    operator_stats: Optional[dict[str, Any]] = None
    # SLO sentinel verdict (obs/slo.py): regression/violation record vs
    # the fingerprint's history baseline — surfaced as
    # ``queryStats.regression``; None when within baseline or cold
    regression: Optional[dict[str, Any]] = None


class Engine:
    """Catalogs + memory pool + statement dispatch. One per server."""

    def __init__(
        self,
        catalogs: Optional[CatalogManager] = None,
        hbm_bytes: int = 16 << 30,
        mesh=None,
    ):
        from trino_tpu.memory import MemoryPool

        if catalogs is None:
            from trino_tpu.connectors.blackhole import BlackHoleConnector
            from trino_tpu.connectors.memory import MemoryConnector
            from trino_tpu.connectors.tpch import TpchConnector

            from trino_tpu.connectors.tpcds import TpcdsConnector

            catalogs = CatalogManager()
            catalogs.register("tpch", TpchConnector())
            catalogs.register("tpcds", TpcdsConnector())
            catalogs.register("memory", MemoryConnector())
            catalogs.register("blackhole", BlackHoleConnector())
        self.catalogs = catalogs
        self.memory_pool = MemoryPool(hbm_bytes)
        self.mesh = mesh  # used by execution_mode=distributed
        import threading

        self._query_seq = 0
        self._seq_lock = threading.Lock()
        # observability (QueryMonitor -> EventListenerManager; system catalog)
        from trino_tpu.events import EventListenerManager

        self.event_listeners = EventListenerManager()
        from collections import deque

        self._recent_queries: "deque[dict]" = deque(maxlen=200)
        self._runtime_nodes_fn = None  # server installs live node info
        # transactions + access control (SURVEY §2 Transactions / Security)
        from trino_tpu.security import AccessControlManager
        from trino_tpu.transaction import TransactionManager

        self.transaction_manager = TransactionManager(self.catalogs)
        self.access_control = AccessControlManager()
        # multi-host scheduling (server/cluster.py installs this on
        # coordinator servers; execution_mode=cluster routes through it)
        self.cluster_scheduler = None
        # multi-host SPMD (server installs SpmdRunner + peer discovery when
        # booted inside a jax.distributed group; fusable cluster queries
        # then run as one pjit program spanning every process)
        self.spmd = None
        self.spmd_peers = None
        try:
            from trino_tpu.connectors.system import SystemConnector

            self.catalogs.register("system", SystemConnector(self))
        except Exception:  # noqa: BLE001 — system catalog is best-effort
            pass
        # plan + compiled-program reuse for repeated read-only queries
        # (keyed by SQL text, session fingerprint, and catalog data
        # versions; jax.jit re-traces on its own if input shapes change)
        from collections import OrderedDict

        self._query_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self._query_cache_lock = threading.Lock()
        # device-resident table cache (trino_tpu/ingest.py): scanned
        # tables stay HBM-resident across queries, keyed by catalog data
        # version + projection + splits, so a warm repeat scan issues
        # zero H2D bytes; engine-owned so every executor shares it
        from trino_tpu.ingest import DeviceTableCache

        self.table_cache = DeviceTableCache()
        # cross-query batch collector (exec/batching.py): when
        # batch_window_ms > 0, compatible queries (same canonical-plan
        # fingerprint, differing only in hoisted literals) wait here for
        # a short window and share ONE stacked device dispatch
        from trino_tpu.exec.batching import BatchCollector

        self.batch_collector = BatchCollector(self)
        # query-history stores (obs/history.py): per-fingerprint observed
        # execution truth, keyed by the session's history_dir ("" = the
        # in-memory per-process store). Engine-owned so every query of a
        # dir shares one store object (and its lock)
        self._history_stores: dict[str, Any] = {}
        self._history_lock = threading.Lock()
        # semantic result cache (trino_tpu/cache): final result sets keyed
        # by (canonical fingerprint, hoisted-param vector) and validated
        # against data versions + ACL generation; the result_cache session
        # knob gates both probe and store
        from trino_tpu.cache.result_cache import ResultCache

        self.result_cache = ResultCache()

    _QUERY_CACHE_MAX = 64
    # statements whose results depend on evaluation time/randomness must
    # not reuse a cached plan; matched against whole lexer identifiers —
    # NOT substrings — so a function `brand()` or a column `randomness`
    # doesn't silently disable caching (`current_timestamp` and friends
    # lex as single IDENT tokens, underscores included)
    _UNCACHEABLE_IDENTS = frozenset({
        "random", "rand", "now", "uuid", "current_time", "current_date",
        "current_timestamp", "localtime", "localtimestamp",
    })

    def _sql_cacheable(self, sql: str) -> bool:
        from trino_tpu.sql.lexer import SqlSyntaxError, tokenize

        try:
            tokens = tokenize(sql)
        except SqlSyntaxError:
            return False  # let the parser produce the real error, uncached
        return not any(
            tok.kind in ("IDENT", "KW")
            and tok.text.lower() in self._UNCACHEABLE_IDENTS
            for tok in tokens
        )

    def _query_cache_entry(self, fingerprint: str) -> dict:
        """Cache slot for this (plan fingerprint, data-version) pair.

        The fingerprint already folds in plan shape, dtypes, mesh, and the
        codegen-relevant session properties (planner/canonicalize.py), so
        the key only adds what the fingerprint cannot see: catalog data
        versions (string dictionaries are trace-time constants, so new
        data must retrace) and the access-control generation (rule changes
        must drop entries immediately). The user is deliberately absent —
        per-user literals ride the parameter vector, and plans that differ
        structurally per user fingerprint differently on their own.
        """
        import threading

        versions = tuple(
            (name, getattr(self.catalogs.get(name), "_version", 0))
            for name in sorted(self.catalogs.names())
        )
        key = (fingerprint, versions, self.access_control.generation)
        with self._query_cache_lock:
            entry = self._query_cache.get(key)
            if entry is None:
                entry = {"plan": None, "programs": {}, "lock": threading.Lock()}
                self._query_cache[key] = entry
                while len(self._query_cache) > self._QUERY_CACHE_MAX:
                    self._query_cache.popitem(last=False)
            else:
                self._query_cache.move_to_end(key)
        return entry

    # --- runtime introspection (system connector backend) -----------------

    def runtime_queries(self) -> list[dict]:
        import time as _time

        out = []
        for rec in list(self._recent_queries):
            rec = dict(rec)
            if rec["state"] == "RUNNING":  # live elapsed for in-flight queries
                rec["elapsedTimeMillis"] = int(
                    (_time.monotonic() - rec["_start"]) * 1000
                )
            rec.pop("_start", None)
            out.append(rec)
        return out

    def _next_query_id(self) -> str:
        with self._seq_lock:
            self._query_seq += 1
            return f"q{self._query_seq}"

    def runtime_nodes(self) -> list[tuple]:
        if self._runtime_nodes_fn is not None:
            return self._runtime_nodes_fn()
        return [("local", "local://", "trino-tpu-0.1", True, "ACTIVE")]

    def runtime_tasks(self) -> list[dict]:
        """Live worker-task info for ``system.runtime.tasks``. The server
        installs ``_runtime_tasks_fn`` (its SqlTaskManager registry);
        standalone engines have no tasks."""
        fn = getattr(self, "_runtime_tasks_fn", None)
        if fn is not None:
            return fn()
        return []

    def runtime_metrics(self) -> list[tuple]:
        """Live metrics-registry snapshot for ``system.runtime.metrics``:
        one row per (name{labels}, kind, value) — histograms expose their
        count/sum/p50/p99 as separate rows."""
        from trino_tpu.obs.metrics import get_registry

        snap = get_registry().snapshot()
        rows: list[tuple] = []
        for key, val in sorted(snap.get("counters", {}).items()):
            rows.append((key, "counter", float(val)))
        for key, val in sorted(snap.get("gauges", {}).items()):
            rows.append((key, "gauge", float(val)))
        for key, h in sorted(snap.get("histograms", {}).items()):
            for field in ("count", "sum", "p50", "p99"):
                v = h.get(field)
                if v is not None:
                    rows.append((f"{key}.{field}", "histogram", float(v)))
        return rows

    def runtime_programs(self) -> list[dict]:
        """Cross-query program-cache contents for
        ``system.runtime.programs``: one row per cached compiled program,
        with the store's cumulative compile counters (the same numbers
        /v1/query reports per query) and the profiler's captured XLA
        cost/memory stats where the backend provided them."""
        from trino_tpu.exec.fragments import program_label

        with self._query_cache_lock:
            items = [
                (key[0], entry["programs"])
                for key, entry in self._query_cache.items()
            ]
        rows: list[dict] = []
        for fingerprint, programs in items:
            store_stats = programs.get("__stats__") or {}
            for key, val in programs.items():
                if not (
                    isinstance(key, tuple)
                    and len(key) == 2
                    and isinstance(key[0], tuple)
                    and isinstance(val, tuple)
                    and len(val) == 2
                ):
                    continue
                meta = val[1]
                ds = getattr(meta, "device_stats", None) or {}
                rows.append(
                    {
                        "fingerprint": fingerprint,
                        "program": program_label(key[0]),
                        "hits": int(store_stats.get("hits", 0)),
                        "misses": int(store_stats.get("misses", 0)),
                        "compile_ms": float(store_stats.get("compile_ms", 0.0)),
                        "flops": ds.get("flops"),
                        "peak_hbm_bytes": ds.get("peak_hbm_bytes"),
                        "bytes_accessed": ds.get("bytes_accessed"),
                    }
                )
        return rows

    # --- query history (obs/history.py) -----------------------------------

    def history_store(self, session: Session):
        """The :class:`QueryHistoryStore` this session resolves to, or
        None when ``query_history`` is off. One store per ``history_dir``
        ("" keeps it in-memory, the tier-1-safe default)."""
        try:
            if not bool(session.get("query_history")):
                return None
            hdir = str(session.get("history_dir") or "")
            max_entries = int(session.get("history_max_entries"))
            max_bytes = int(session.get("history_max_bytes"))
        except KeyError:
            return None
        import os

        from trino_tpu.obs.history import QueryHistoryStore

        path = os.path.join(hdir, "query_history.json") if hdir else ""
        with self._history_lock:
            store = self._history_stores.get(hdir)
            if store is None:
                store = QueryHistoryStore(
                    path=path, max_entries=max_entries, max_bytes=max_bytes
                )
                self._history_stores[hdir] = store
            return store

    def history_snapshot(self) -> dict:
        """Every history store this engine has resolved, merged — the
        ``GET /v1/history`` body."""
        with self._history_lock:
            stores = sorted(self._history_stores.items())
        return {"stores": [s.snapshot() for _, s in stores]}

    def runtime_history(self) -> list[dict]:
        """Flat per-fingerprint rows for ``system.runtime.history``."""
        with self._history_lock:
            stores = [s for _, s in sorted(self._history_stores.items())]
        rows: list[dict] = []
        for store in stores:
            for fp, ent in store.entries():
                rec = dict(ent)
                rec["fingerprint"] = fp
                rec["path"] = store.path
                rows.append(rec)
        return rows

    @staticmethod
    def _history_record(hist, fp, res, elapsed_ms: float) -> None:
        """Fold one finished query's observed stats into the history
        store. Best-effort by contract: history must never fail (or slow
        down observably) the query that feeds it."""
        if hist is None or fp is None or res is None:
            return
        try:
            ex = (
                res.exchange_stats
                if isinstance(res.exchange_stats, dict)
                else {}
            )
            ds = (
                res.device_stats if isinstance(res.device_stats, dict) else {}
            )
            bs = res.batch_stats if isinstance(res.batch_stats, dict) else {}
            caps: dict[str, dict] = {}
            for val in (ex.get("capacities") or {}).values():
                if not isinstance(val, dict):
                    continue
                site = val.get("site")
                # only restart-stable names persist — raw tracer names
                # embed id(node) and mean nothing to the next process
                if not isinstance(site, str) or "@" not in site:
                    continue
                caps[site] = {
                    "value": val.get("value"),
                    "provenance": val.get("provenance", ""),
                }
            observed: dict[str, Any] = {
                "elapsed_ms": round(float(elapsed_ms), 3),
                "rows": len(res.rows),
                "overflow_retries": int(ex.get("overflow_retries", 0) or 0),
                "compile_halvings": int(ex.get("compile_halvings", 0) or 0),
                "padding_ratio": float(ex.get("padding_ratio", 0.0) or 0.0),
                "shuffle_rows": int(ex.get("shuffle_rows", 0) or 0),
                "capacities": caps,
            }
            ops: dict[str, dict] = {}
            for site, ent in (ex.get("operators") or {}).items():
                if not isinstance(ent, dict) or "@" not in str(site):
                    continue
                ops[str(site)] = {
                    "kind": str(ent.get("kind", "")),
                    "rows_in": int(ent.get("rows_in", 0) or 0),
                    "rows_out": int(ent.get("rows_out", 0) or 0),
                }
            if ops:
                # the partial-agg reduction-ratio seed the mid-query
                # adaptivity roadmap item reads (EWMA'd per site in
                # obs/history.py)
                observed["operators"] = ops
            flops = ds.get("total_flops")
            if isinstance(flops, (int, float)):
                observed["flops"] = float(flops)
            peak = ds.get("peak_hbm_bytes")
            if isinstance(peak, (int, float)) and peak > 0:
                observed["peak_hbm_bytes"] = int(peak)
            if bs.get("batchSize"):
                observed["batch_size"] = int(bs["batchSize"])
            hist.record(fp, observed)
        except Exception:  # noqa: BLE001
            pass

    @staticmethod
    def _sentinel_check(
        session, fp, res, elapsed_ms: float, hist_entry, query_id=None
    ) -> None:
        """Judge this completion against the fingerprint's PRE-run history
        baseline and configured SLOs (obs/slo.py); the verdict rides the
        result as ``regression`` → queryStats. Best-effort like history:
        the sentinel must never fail the query it observes."""
        try:
            from trino_tpu.obs.slo import get_sentinel

            verdict = get_sentinel().evaluate(
                session,
                fp,
                elapsed_ms,
                hist_entry,
                query_id=query_id,
            )
            if res is not None and verdict is not None:
                res.regression = verdict
        except Exception:  # noqa: BLE001
            pass

    # === entry ============================================================

    def execute_statement(
        self,
        sql: str,
        session: Session,
        query_id: Optional[str] = None,
        fire_events: bool = True,
    ) -> StatementResult:
        """Run one statement.

        ``query_id`` lets a caller that already owns the query lifecycle
        (ManagedQuery on the server) pin its id so traces/events/system
        tables all agree; ``fire_events=False`` hands event ownership to
        that caller too, so server terminal paths (kill/cancel/reject)
        can fire exactly one completed event themselves.
        """
        import time as _time

        from trino_tpu.events import QueryCompletedEvent, QueryCreatedEvent
        from trino_tpu.obs.metrics import get_registry
        from trino_tpu.obs.trace import get_tracer

        qid = query_id or self._next_query_id()
        t0 = _time.time()  # epoch: event create_time / display only
        t0m = _time.monotonic()  # interval math
        if fire_events:
            self.event_listeners.fire_created(
                QueryCreatedEvent(qid, sql, session.user, t0)
            )
        tracer = get_tracer()
        # root span when standalone; child "execute" span when a server
        # ManagedQuery already opened the query root on this thread
        span = tracer.start_span(
            "query" if tracer.current() is None else "execute",
            trace_id=qid if tracer.current() is None else None,
            attrs={"queryId": qid, "user": session.user},
        )
        record = {
            "queryId": qid, "state": "RUNNING", "user": session.user,
            "source": session.source, "query": sql, "elapsedTimeMillis": 0,
            "peakMemoryBytes": 0, "outputRows": 0, "_start": t0m,
        }
        self._recent_queries.append(record)
        error: Optional[str] = None
        exc: Optional[BaseException] = None
        res: Optional[StatementResult] = None
        # Validate + pin the session's explicit transaction for the duration
        # of this statement: a stale/expired __txn must error (reference
        # errors on unknown transaction ids), and a live one must not be
        # idle-expired mid-statement.
        txn_info = None
        try:
            txn_id = session.properties.get("__txn")
            if txn_id:
                try:
                    txn_info = self.transaction_manager.get(txn_id)  # touches
                    txn_info.busy += 1
                except Exception:
                    session.properties.pop("__txn", None)
                    raise
            with tracer.activate(span):
                res = self._execute_statement_inner(sql, session, qid)
            return res
        except Exception as e:  # noqa: BLE001
            error = str(e)
            exc = e
            raise
        finally:
            if txn_info is not None:
                txn_info.busy -= 1
                txn_info.last_access = _time.time()
            end = _time.time()
            wall = _time.monotonic() - t0m
            record["state"] = "FINISHED" if error is None else "FAILED"
            record["elapsedTimeMillis"] = int(wall * 1000)
            if res is not None:
                record["peakMemoryBytes"] = res.peak_memory_bytes
                record["outputRows"] = len(res.rows)
            span.finish(
                status="OK" if error is None else "ERROR",
                state=record["state"],
                rows=record["outputRows"],
            )
            self._record_query_metrics(get_registry(), record, res, wall)
            if fire_events:
                err_code = err_type = None
                if exc is not None:
                    from trino_tpu.errors import classify_error

                    err_code, _, err_type = classify_error(exc)
                self.event_listeners.fire_completed(
                    QueryCompletedEvent(
                        qid, sql, session.user, t0, end,
                        record["state"],
                        output_rows=record["outputRows"],
                        peak_memory_bytes=record["peakMemoryBytes"],
                        error_message=error,
                        wall_seconds=wall,
                        error_code=err_code,
                        error_type=err_type,
                    )
                )

    @staticmethod
    def _record_query_metrics(reg, record: dict, res, wall_s: float) -> None:
        """Fold one statement's counters into the process registry."""
        reg.counter("trino_tpu_queries_total", state=record["state"]).inc()
        reg.histogram("trino_tpu_query_elapsed_ms").observe(wall_s * 1000.0)
        reg.counter("trino_tpu_output_rows_total").inc(record["outputRows"])
        if res is None:
            return
        reg.counter("trino_tpu_compile_ms_total").inc(res.compile_ms)
        reg.counter("trino_tpu_trace_count_total").inc(res.trace_count)
        reg.counter("trino_tpu_program_cache_hits_total").inc(
            res.program_cache_hits
        )
        reg.counter("trino_tpu_program_cache_misses_total").inc(
            res.program_cache_misses
        )
        for key, val in (res.exchange_stats or {}).items():
            # batchedQueries is shared verbatim by every member of a
            # batched dispatch — summing K copies of K is meaningless;
            # trino_tpu_batched_dispatches_total{size} is the real counter
            if key == "batchedQueries":
                continue
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                # key = exchange stat field names, a closed vocabulary
                reg.counter(f"trino_tpu_exchange_{key}_total").inc(val)  # lint: ignore[OBS002]
        for ent in (res.operator_stats or {}).values():
            # kind is a closed vocabulary minted by the tracer
            # (scan/filter/join/semijoin/partial-agg/final-agg/agg/exchange)
            if isinstance(ent, dict) and ent.get("kind"):
                reg.counter(
                    "trino_tpu_operator_rows_total",
                    kind=ent["kind"], io="in",
                ).inc(int(ent.get("rows_in", 0) or 0))
                reg.counter(
                    "trino_tpu_operator_rows_total",
                    kind=ent["kind"], io="out",
                ).inc(int(ent.get("rows_out", 0) or 0))
        ds = res.device_stats or {}
        if isinstance(ds.get("total_flops"), (int, float)):
            reg.counter("trino_tpu_query_flops_total").inc(ds["total_flops"])
        if isinstance(ds.get("peak_hbm_bytes"), (int, float)):
            reg.gauge("trino_tpu_query_peak_hbm_bytes").set(
                ds["peak_hbm_bytes"]
            )

    # --- semantic result cache (trino_tpu/cache) --------------------------

    def _result_cache_on(self, session: Session) -> bool:
        try:
            if not bool(session.get("result_cache")):
                return False
        except KeyError:
            return False
        # snapshot semantics inside explicit transactions are per-txn
        return "__txn" not in session.properties

    def try_cached_result(
        self, sql: str, session: Session, allow_maintenance: bool = True
    ) -> Optional[StatementResult]:
        """Serve this statement from the semantic result cache, or None.

        Pure-hit lookups are microseconds and safe anywhere off the event
        loop; ``allow_maintenance`` additionally permits an incremental
        delta merge, which executes a scan and therefore belongs on a
        worker/dispatch thread only (the QueryManager admission fast path
        passes False)."""
        if not self._result_cache_on(session):
            return None
        try:
            return self.result_cache.lookup(
                self, sql, session, allow_maintenance=allow_maintenance
            )
        except Exception:  # noqa: BLE001 — the cache must never fail a query
            return None

    def _result_cache_begin(
        self, sql_text: Optional[str], session: Session, plan: P.PlanNode
    ) -> Optional[dict]:
        """Pre-execution snapshot for the store: referenced tables + their
        data versions, captured BEFORE execution so a write landing during
        the run leaves the entry conservatively stale, never wrong."""
        if sql_text is None or not self._result_cache_on(session):
            return None
        if not self._sql_cacheable(sql_text):
            return None
        try:
            from trino_tpu.cache.result_cache import (
                referenced_tables,
                versions_snapshot,
            )

            tables = referenced_tables(plan)
            if not tables:
                return None  # literal-only results are not worth an entry
            for cat in dict.fromkeys(c for c, _, _ in tables):
                conn = self.catalogs.get(cat)
                if not getattr(conn, "supports_result_caching", True):
                    return None  # live state (system tables): never cache
            versions = versions_snapshot(self.catalogs, tables)
        except Exception:  # noqa: BLE001
            return None
        return {"tables": tables, "versions": versions}

    def _result_cache_store(
        self,
        ctx: Optional[dict],
        sql_text: str,
        session: Session,
        plan: P.PlanNode,
        res: Optional[StatementResult],
    ) -> None:
        if ctx is None or res is None or res.update_type is not None:
            return
        try:
            from trino_tpu.planner.canonicalize import canonicalize_plan

            mesh_n = (
                int(self.mesh.devices.size) if self.mesh is not None else 1
            )
            # recompute the (fingerprint, params) pair from the BAKED plan
            # here rather than reusing the dispatch path's: cluster mode
            # computes a record-only fingerprint with the param vector
            # discarded, and aliasing two literal variants onto one
            # entry key would serve one query's rows for the other
            _, params, fp = canonicalize_plan(plan, session, mesh_n)
            if fp is None:
                return
            maintain = None
            try:
                if bool(session.get("incremental_maintenance")):
                    from trino_tpu.planner.canonicalize import (
                        classify_maintainability,
                    )

                    maintain = classify_maintainability(plan)
            except KeyError:
                maintain = None
            try:
                max_bytes = int(session.get("result_cache_max_bytes"))
            except KeyError:
                max_bytes = None
            self.result_cache.store(
                sql=sql_text,
                session=session,
                fingerprint=fp,
                params=params,
                tables=ctx["tables"],
                versions=ctx["versions"],
                acl_generation=self.access_control.generation,
                res=res,
                maintain=maintain,
                plan=plan,
                max_bytes=max_bytes,
            )
        except Exception:  # noqa: BLE001 — the cache must never fail a query
            pass

    def _execute_statement_inner(
        self, sql: str, session: Session, query_id: Optional[str] = None
    ) -> StatementResult:
        # result-cache probe BEFORE parse: sub-millisecond hits cannot
        # afford parse+plan, so the cache's SQL-text memo (populated at
        # store time, validated against data versions + ACL generation)
        # routes repeat texts straight to host-resident rows
        cached = self.try_cached_result(sql, session)
        if cached is not None:
            return cached
        stmt = parse_statement(sql)
        if isinstance(stmt, t.Prepare):
            # keep the statement's SQL text: it must survive the stateless
            # HTTP protocol via X-Trino-Added-Prepare
            import re as _re

            m = _re.match(
                r"\s*prepare\s+\S+\s+from\s+(.*)$",
                sql.strip().rstrip(";"),
                _re.IGNORECASE | _re.DOTALL,
            )
            if m:
                stmt = dataclasses.replace(stmt, sql=m.group(1).strip())
        return self._dispatch_parsed(stmt, session, query_id, sql_text=sql)

    def _dispatch_parsed(
        self,
        stmt: t.Node,
        session: Session,
        query_id: Optional[str] = None,
        sql_text: Optional[str] = None,
    ) -> StatementResult:
        handler = getattr(self, f"_do_{type(stmt).__name__.lower()}", None)
        if handler is not None:
            return handler(stmt, session)
        if isinstance(stmt, t.Query):
            # always (re-)plan: planning is cheap host work, and the
            # canonical fingerprint of the optimized plan — not the SQL
            # text — keys the program cache, so `x < 24` and `x < 25`
            # land on the same entry with different parameter vectors
            plan = self.plan(stmt, session)
            # result-cache store context (tables + PRE-execution data
            # versions); None when the cache is off or the shape refuses
            rc_ctx = self._result_cache_begin(sql_text, session, plan)
            exec_plan, params, entry, fp = plan, [], None, None
            mode = session.get("execution_mode")
            try:
                wants_batch = int(session.get("batch_window_ms")) > 0
            except KeyError:
                wants_batch = False
            mesh_n = (
                int(self.mesh.devices.size) if self.mesh is not None else 1
            )
            if (
                sql_text is not None
                # cluster queries canonicalize only to join the batch
                # collector (grouping needs the fingerprint); each
                # member binds its own literals back before the
                # scheduler ships fragments (_execute_query_plan)
                and (
                    mode == "distributed"
                    or (mode == "cluster" and wants_batch)
                )
                and session.get("fragment_execution")
                and bool(session.get("program_cache"))
                and self._sql_cacheable(sql_text)
            ):
                from trino_tpu.planner.canonicalize import canonicalize_plan

                canonical, params, fp = canonicalize_plan(
                    plan, session, mesh_n
                )
                if fp is not None:
                    exec_plan = canonical
                    entry = self._query_cache_entry(fp)
                else:
                    params = []  # unserializable shape: run baked, uncached
            elif (
                sql_text is not None
                and mode == "cluster"
                and self._sql_cacheable(sql_text)
            ):
                # record-only fingerprint: cluster queries execute the
                # baked plan, but the history store still keys their
                # observed truth (and the admission gate their peak HBM)
                # by the same canonical fingerprint
                try:
                    from trino_tpu.planner.canonicalize import (
                        canonicalize_plan,
                    )

                    _, _, fp = canonicalize_plan(plan, session, mesh_n)
                except Exception:  # noqa: BLE001
                    fp = None
            hist = self.history_store(session) if fp is not None else None
            hist_entry = hist.get(fp) if hist is not None else None
            # cross-query batching: when the session opts in, compatible
            # queries (same fingerprint + same session signature) wait in
            # the collector for a short window and share ONE stacked
            # device dispatch through the cached programs. Transactions
            # are excluded (snapshot semantics are per-statement), and
            # window=0 — the default — keeps the path below verbatim.
            import time as _time

            if (
                entry is not None
                and wants_batch
                and "__txn" not in session.properties
            ):
                t0 = _time.monotonic()
                res = self.batch_collector.submit(
                    entry,
                    exec_plan,
                    session,
                    params,
                    query_id or self._next_query_id(),
                )
                elapsed_ms = (_time.monotonic() - t0) * 1000.0
                self._sentinel_check(
                    session, fp, res, elapsed_ms, hist_entry,
                    query_id=query_id,
                )
                self._history_record(hist, fp, res, elapsed_ms)
                if isinstance(res.exchange_stats, dict):
                    res.exchange_stats["history_hits"] = (
                        1 if hist_entry is not None else 0
                    )
                self._result_cache_store(rc_ctx, sql_text, session, plan, res)
                return res
            # shared program stores and capacity objects are not safe for
            # concurrent executors: a second in-flight run of the same
            # fingerprint executes uncached instead of waiting
            if entry is not None and not entry["lock"].acquire(blocking=False):
                entry = None
            try:
                programs = None
                if entry is not None:
                    if entry["plan"] is None:
                        entry["plan"] = exec_plan
                    # same fingerprint == same shape: execute the FIRST
                    # cached plan object so fragment node identities (and
                    # with them program keys and caps sites) stay stable
                    # across queries; this query's literals ride in via
                    # the parameter vector
                    exec_plan = entry["plan"]
                    programs = entry["programs"]
                t0 = _time.monotonic()
                res = self._execute_query_plan(
                    exec_plan, session, query_id=query_id,
                    programs=programs, params=params, history=hist_entry,
                )
                elapsed_ms = (_time.monotonic() - t0) * 1000.0
                self._sentinel_check(
                    session, fp, res, elapsed_ms, hist_entry,
                    query_id=query_id,
                )
                self._history_record(hist, fp, res, elapsed_ms)
                if isinstance(res.exchange_stats, dict):
                    # did a prior run of this fingerprint inform this one?
                    # (surfaced as queryStats.historyHits on /v1/query)
                    res.exchange_stats["history_hits"] = (
                        1 if hist_entry is not None else 0
                    )
                self._result_cache_store(rc_ctx, sql_text, session, plan, res)
                return res
            finally:
                if entry is not None:
                    entry["lock"].release()
        raise SemanticError(f"unsupported statement: {type(stmt).__name__}")

    def fingerprint(
        self, sql: str, session: Session
    ) -> tuple[Optional[str], list]:
        """Canonical-plan fingerprint + hoisted params for a SELECT —
        None for uncacheable statements (prewarm/test helper)."""
        from trino_tpu.planner.canonicalize import canonicalize_plan

        stmt = parse_statement(sql)
        if not isinstance(stmt, t.Query) or not self._sql_cacheable(sql):
            return None, []
        plan = self.plan(stmt, session)
        mesh_n = int(self.mesh.devices.size) if self.mesh is not None else 1
        _, params, fp = canonicalize_plan(plan, session, mesh_n)
        return fp, params

    def plan(self, stmt: t.Node, session: Session) -> P.PlanNode:
        from trino_tpu.obs.trace import get_tracer
        from trino_tpu.planner.optimizer import optimize

        tracer = get_tracer()
        analyzer = Analyzer(self.catalogs, session, self.access_control)
        with tracer.span("plan"):
            plan = analyzer.plan_statement(stmt)
        with tracer.span("optimize"):
            return optimize(plan, session, self.catalogs)

    # === DQL ==============================================================

    def _execute_query_plan(
        self,
        plan: P.PlanNode,
        session: Session,
        collector=None,
        query_id: Optional[str] = None,
        programs: Optional[dict] = None,
        params: Optional[list] = None,
        history: Optional[dict] = None,
    ) -> StatementResult:
        from trino_tpu.memory import QueryMemoryContext

        if session.get("execution_mode") == "cluster" and (
            self.cluster_scheduler is not None or self.spmd is not None
        ):
            if params:
                # a canonical (hoisted) plan reached the cluster path — a
                # batch member, or its sequential fallback. The wire serde
                # drops hoisted values, so bake this query's literals back
                from trino_tpu.planner.canonicalize import bind_params

                plan = bind_params(plan, params)
            batch = None
            if self.spmd is not None and self.spmd_peers is not None:
                from trino_tpu.parallel.spmd import SpmdUnsupported

                try:
                    batch, names = self.spmd.execute(
                        plan, session, self.spmd_peers()
                    )
                except SpmdUnsupported:
                    batch = None  # non-fusable: per-task scheduling below
            cluster_stats: dict[str, Any] = {}
            if batch is None and self.cluster_scheduler is not None:
                batch, names = self.cluster_scheduler.execute(
                    plan, session, stats_sink=cluster_stats,
                    query_id=query_id,
                )
            if batch is not None:
                return StatementResult(
                    batch.to_pylist(),
                    names,
                    [c.type for c in batch.columns],
                    cluster_stats=cluster_stats,
                    device_stats=cluster_stats.get("deviceStats"),
                    exchange_stats=cluster_stats.get("exchangeStats"),
                    ingest_stats=cluster_stats.get("ingestStats"),
                    operator_stats=(
                        cluster_stats.get("exchangeStats") or {}
                    ).get("operators"),
                )
        ctx = QueryMemoryContext(
            self.memory_pool,
            query_id or self._next_query_id(),
            max_bytes=int(session.get("query_max_memory_bytes")),
        )
        try:
            executor = self._executor(
                session, ctx, programs=programs, params=params,
                history=history,
            )
            executor.stats_collector = collector
            batch, names = executor.execute(plan)
            snap = getattr(executor, "exchange_stats_snapshot", None)
            exchange_stats = snap() if callable(snap) else (
                dict(executor.exchange_stats)
                if getattr(executor, "exchange_stats", None)
                else None
            )
            cs = getattr(executor, "compile_stats", None) or {}
            dsnap = getattr(executor, "device_stats_snapshot", None)
            return StatementResult(
                batch.to_pylist(),
                names,
                [c.type for c in batch.columns],
                peak_memory_bytes=ctx.peak_bytes,
                dynamic_filters=len(executor.dynamic_filters),
                exchange_stats=exchange_stats,
                compile_ms=round(float(cs.get("compile_ms", 0.0)), 3),
                trace_count=int(cs.get("trace_count", 0)),
                program_cache_hits=int(cs.get("program_cache_hits", 0)),
                program_cache_misses=int(cs.get("program_cache_misses", 0)),
                device_stats=dsnap() if callable(dsnap) else None,
                ingest_stats=executor.ingest_stats_snapshot(),
                operator_stats=(exchange_stats or {}).get("operators"),
            )
        finally:
            ctx.close()

    def _execute_query_plan_batched(
        self,
        plan: P.PlanNode,
        session: Session,
        query_ids: list[str],
        param_lists: list[list],
        programs: Optional[dict] = None,
    ) -> list[StatementResult]:
        """Run K literal-variant queries of the SAME cached plan as one
        stacked device dispatch, one StatementResult per member in
        submission order.

        One memory context and one FragmentedExecutor serve the whole
        batch, so exchange/compile/device snapshots are shared across the
        K results (each member reports the batch's dispatch, not a
        pro-rated share). Raises BatchUnsupported — or any execution
        error — for exec/batching.py to fall back to sequential runs.
        """
        from trino_tpu.exec.fragments import (
            BatchUnsupported,
            FragmentedExecutor,
        )
        from trino_tpu.memory import QueryMemoryContext

        ctx = QueryMemoryContext(
            self.memory_pool,
            query_ids[0],
            max_bytes=int(session.get("query_max_memory_bytes")),
        )
        try:
            executor = self._executor(
                session, ctx, programs=programs, params=param_lists[0]
            )
            if not isinstance(executor, FragmentedExecutor):
                raise BatchUnsupported("fragment execution disabled")
            param_sets = [[v for v, _ in pl] for pl in param_lists]
            batches, names = executor.execute_batched(plan, param_sets)
            snap = getattr(executor, "exchange_stats_snapshot", None)
            exchange_stats = snap() if callable(snap) else (
                dict(executor.exchange_stats)
                if getattr(executor, "exchange_stats", None)
                else None
            )
            cs = getattr(executor, "compile_stats", None) or {}
            dsnap = getattr(executor, "device_stats_snapshot", None)
            device_stats = dsnap() if callable(dsnap) else None
            ingest_stats = executor.ingest_stats_snapshot()
            return [
                StatementResult(
                    batch.to_pylist(),
                    list(names),
                    [c.type for c in batch.columns],
                    peak_memory_bytes=ctx.peak_bytes,
                    exchange_stats=exchange_stats,
                    compile_ms=round(float(cs.get("compile_ms", 0.0)), 3),
                    trace_count=int(cs.get("trace_count", 0)),
                    program_cache_hits=int(cs.get("program_cache_hits", 0)),
                    program_cache_misses=int(
                        cs.get("program_cache_misses", 0)
                    ),
                    device_stats=device_stats,
                    ingest_stats=ingest_stats,
                    operator_stats=(exchange_stats or {}).get("operators"),
                )
                for batch in batches
            ]
        finally:
            ctx.close()

    def _executor(
        self,
        session: Session,
        ctx,
        programs: Optional[dict] = None,
        params: Optional[list] = None,
        history: Optional[dict] = None,
    ) -> LocalExecutor:
        mode = session.get("execution_mode")
        if mode == "distributed":
            if session.get("fragment_execution"):
                from trino_tpu.exec.fragments import FragmentedExecutor

                ex = FragmentedExecutor(
                    self.catalogs, session, self.mesh, memory_ctx=ctx,
                    programs=programs, params=params, history=history,
                )
            else:
                from trino_tpu.parallel.distributed import (
                    DistributedExecutor,
                )

                ex = DistributedExecutor(
                    self.catalogs, session, self.mesh, memory_ctx=ctx
                )
            # share the engine-wide device table cache (warm repeat scans
            # skip H2D); the local interpreter keeps host batches, so
            # only the device-mesh executors get it
            ex.table_cache = self.table_cache
            return ex
        return LocalExecutor(self.catalogs, session, memory_ctx=ctx)

    def _run_query_rows(self, query: t.Query, session: Session) -> tuple[Batch, list[str]]:
        plan = self.plan(query, session)
        from trino_tpu.memory import QueryMemoryContext

        ctx = QueryMemoryContext(
            self.memory_pool,
            self._next_query_id(),
            max_bytes=int(session.get("query_max_memory_bytes")),
        )
        try:
            return self._executor(session, ctx).execute(plan)
        finally:
            ctx.close()

    # === session control ==================================================

    def _do_setsession(self, stmt: t.SetSession, session: Session) -> StatementResult:
        value = stmt.value
        v: Any = value.value if isinstance(value, t.Literal) else None
        session.set(stmt.name, v)
        return StatementResult(
            [], ["result"], [T.BOOLEAN],
            update_type="SET SESSION", set_session={stmt.name: v},
        )

    # === metadata / SHOW ==================================================

    def _do_showcatalogs(self, stmt, session) -> StatementResult:
        names = self.access_control.filter_catalogs(
            session.user, self.catalogs.names()
        )
        return StatementResult([(n,) for n in names], ["Catalog"], [T.VARCHAR])

    def _do_showschemas(self, stmt, session) -> StatementResult:
        catalog = stmt.catalog or session.catalog
        conn = self.catalogs.get(catalog)
        return StatementResult(
            [(s,) for s in conn.list_schemas()], ["Schema"], [T.VARCHAR]
        )

    def _do_showtables(self, stmt, session) -> StatementResult:
        parts = list(stmt.schema or ())
        if len(parts) == 2:
            catalog, schema = parts
        elif len(parts) == 1:
            catalog, schema = session.catalog, parts[0]
        else:
            catalog, schema = session.catalog, session.schema
        conn = self.catalogs.get(catalog)
        return StatementResult(
            [(x,) for x in conn.list_tables(schema)], ["Table"], [T.VARCHAR]
        )

    def _do_showcolumns(self, stmt, session) -> StatementResult:
        catalog, schema, table = self._qualify(stmt.table, session)
        conn = self.catalogs.get(catalog)
        ts = conn.get_table(schema, table)
        if ts is None:
            raise SemanticError(f"table not found: {catalog}.{schema}.{table}")
        rows = [(c.name, str(c.type), "", "") for c in ts.columns]
        return StatementResult(
            rows, ["Column", "Type", "Extra", "Comment"], [T.VARCHAR] * 4
        )

    # === EXPLAIN ==========================================================

    def _do_explain(self, stmt: t.Explain, session: Session) -> StatementResult:
        if getattr(stmt, "analyze", False):
            inner = stmt.statement
            if not isinstance(inner, t.Query):
                raise SemanticError("EXPLAIN ANALYZE supports queries only")
            from trino_tpu.stats import StatsCollector, render_plan_with_stats

            collector = StatsCollector()
            plan = self.plan(inner, session)
            res = self._execute_query_plan(plan, session, collector=collector)
            stages = (res.cluster_stats or {}).get("stages")
            if stages:
                # cluster execution: render the Trino-style distributed
                # plan from the per-stage stats the coordinator merged out
                # of every worker's shipped task stats
                from trino_tpu.stats import render_distributed_plan

                text = render_distributed_plan(
                    plan, res.cluster_stats, res.device_stats
                )
                wall_ms = max(
                    (s.get("elapsedMs", 0.0) for s in stages), default=0.0
                )
            else:
                text = render_plan_with_stats(plan, collector)
                if collector.fragments:
                    from trino_tpu.stats import render_fragment_stats

                    text += "\n\n" + render_fragment_stats(collector.fragments)
                if res.device_stats:
                    from trino_tpu.stats import render_device_stats

                    text += "\n\n" + render_device_stats(res.device_stats)
                ex_caps = (res.exchange_stats or {}).get("capacities")
                if isinstance(ex_caps, dict) and ex_caps:
                    from trino_tpu.stats import render_capacity_stats

                    text += "\n\n" + render_capacity_stats(ex_caps)
                if res.operator_stats:
                    from trino_tpu.stats import render_operator_stats

                    text += "\n\n" + render_operator_stats(
                        res.operator_stats
                    )
                wall_ms = collector.total_wall() * 1000
            text += (
                f"\n\npeak memory: {res.peak_memory_bytes} bytes"
                f"\ndynamic filters: {res.dynamic_filters}"
                f"\noutput rows: {len(res.rows)}"
                f"\nwall time: {wall_ms:.1f}ms"
            )
            return StatementResult(
                [(line,) for line in text.splitlines()], ["Query Plan"], [T.VARCHAR]
            )
        plan = self.plan(stmt.statement, session)
        from trino_tpu.planner.fragmenter import fragment_plan, subplan_text

        # EXPLAIN shows the distributed (fragmented) plan, like the
        # reference's default EXPLAIN output
        text = subplan_text(fragment_plan(plan))
        return StatementResult(
            [(line,) for line in text.splitlines()], ["Query Plan"], [T.VARCHAR]
        )

    # === DDL / DML ========================================================


    def _scaled_insert(
        self, conn, catalog: str, schema: str, table: str, batch, session
    ):
        """Distributed scaled writers, or None to insert locally.

        Reference: ``execution/scheduler/ScaledWriterScheduler.java`` +
        round-robin ``FIXED_ARBITRARY_DISTRIBUTION`` writer placement
        (``SystemPartitioningHandle.java:61,63``). ADR: the reference
        grows writers from runtime buffer-utilization signals; our
        exchanges prefetch, so the writer count scales statically from
        the materialized size (one writer per ~32MB, capped at the
        worker count) — same knob, compile-time signal. The coordinator
        writes the first chunk itself (file-format connectors anchor the
        table schema in the first part file), then ships the rest to
        workers over ``POST /v1/write`` as serialized pages.
        """
        if not session.get("scaled_writers"):
            return None
        if not getattr(conn, "supports_distributed_writes", False):
            return None
        if self.cluster_scheduler is None:
            return None
        nodes = self.cluster_scheduler.node_manager.active_nodes()
        if not nodes:
            return None
        from trino_tpu.memory import batch_nbytes

        batch = batch.compact()
        target = int(session.get("writer_target_bytes"))
        writers = max(1, min(len(nodes) + 1, -(-batch_nbytes(batch) // target)))
        if writers <= 1 or batch.num_rows < 2:
            return None
        from trino_tpu.exec.streaming import _slice_rows
        from trino_tpu.serde import serialize_batch
        from trino_tpu.server import auth

        rows_per = -(-batch.num_rows // writers)
        chunks = [
            _slice_rows(batch, lo, min(lo + rows_per, batch.num_rows))
            for lo in range(0, batch.num_rows, rows_per)
        ]
        if hasattr(conn, "insert_part"):
            total, anchor_part = conn.insert_part(schema, table, chunks[0])
        else:
            total, anchor_part = conn.insert(schema, table, chunks[0]), ""
        import threading
        import urllib.parse
        import urllib.request

        placements = self.cluster_scheduler.node_scheduler.select(
            nodes, len(chunks) - 1
        )
        errors: list[Exception] = []
        counts: list[int] = []
        parts: list[str] = [anchor_part]

        def write(node, chunk):
            try:
                import json as _json

                qs = urllib.parse.urlencode(
                    {"catalog": catalog, "schema": schema, "table": table}
                )
                req = urllib.request.Request(
                    f"{node.uri}/v1/write?{qs}",
                    data=serialize_batch(chunk),
                    method="POST",
                    headers=auth.headers(),
                )
                with urllib.request.urlopen(req, timeout=300) as r:
                    reply = _json.loads(r.read().decode())
                    counts.append(reply["rows"])
                    if reply.get("part"):
                        parts.append(reply["part"])
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=write, args=(n, c), daemon=True)
            for n, c in zip(placements, chunks[1:])
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        for node in placements:
            self.cluster_scheduler.node_scheduler.release(node)

        def abort(msg):
            # a failed scaled INSERT must not leave partial rows behind
            # (a retry would duplicate them): best-effort delete of every
            # part the successful writers committed — shared storage, so
            # the coordinator's connector can remove them directly
            if hasattr(conn, "delete_parts"):
                try:
                    conn.delete_parts(schema, table, parts)
                except Exception:  # noqa: BLE001
                    pass
            raise ExecutionError(msg)

        if any(t.is_alive() for t in threads):
            abort("scaled write failed: a writer task did not complete")
        if errors:
            abort(f"scaled write failed: {errors[0]}")
        if len(counts) != len(threads):
            abort(
                f"scaled write failed: {len(threads) - len(counts)} writer "
                f"tasks reported no row count"
            )
        return total + sum(counts)

    def _do_createtableasselect(
        self, stmt: t.CreateTableAsSelect, session: Session
    ) -> StatementResult:
        catalog, schema, table = self._qualify(stmt.name, session)
        self.access_control.check_can_create(session.user, catalog, schema, table)
        conn = self.catalogs.get(catalog)
        self._check_txn_writable(session, conn, catalog)
        batch, names = self._run_query_rows(stmt.query, session)
        cols = tuple(
            ColumnSchema(n.lower(), c.type) for n, c in zip(names, batch.columns)
        )
        with self._write_guard(session):
            conn.create_table(schema, table, TableSchema(table, cols))
            n = self._scaled_insert(conn, catalog, schema, table, batch, session)
            if n is None:
                n = conn.insert(schema, table, batch)
        return StatementResult(
            [], ["rows"], [T.BIGINT], update_type="CREATE TABLE", update_count=n
        )

    def _do_insertinto(self, stmt: t.InsertInto, session: Session) -> StatementResult:
        catalog, schema, table = self._qualify(stmt.name, session)
        self.access_control.check_can_insert(session.user, catalog, schema, table)
        conn = self.catalogs.get(catalog)
        self._check_txn_writable(session, conn, catalog)
        ts = conn.get_table(schema, table)
        if ts is None:
            raise SemanticError(f"table not found: {catalog}.{schema}.{table}")
        with self._write_guard(session):
            return self._do_insert_locked(stmt, session, conn, schema, table, ts)

    def _do_insert_locked(self, stmt, session, conn, schema, table, ts) -> StatementResult:
        batch, names = self._run_query_rows(stmt.query, session)
        ncols = len(stmt.columns) if stmt.columns else len(ts.columns)
        if len(batch.columns) != ncols:
            raise SemanticError(
                f"INSERT has {len(batch.columns)} columns, expected {ncols}"
            )
        if stmt.columns:
            # reorder/complete to table column order, NULL-filling the rest
            import numpy as np

            from trino_tpu.columnar import Column, Dictionary

            by_name = {c.lower(): i for i, c in enumerate(stmt.columns)}
            n = batch.num_rows
            cols = []
            for cs in ts.columns:
                if cs.name in by_name:
                    cols.append(batch.columns[by_name[cs.name]])
                else:
                    cols.append(
                        Column(
                            cs.type,
                            np.zeros(n, dtype=cs.type.storage_dtype),
                            np.zeros(n, dtype=np.bool_),
                            Dictionary([]) if T.is_string(cs.type) else None,
                        )
                    )
            batch = Batch(cols, n, batch.sel)
        n = self._scaled_insert(
            conn, self._qualify(stmt.name, session)[0], schema, table, batch,
            session,
        )
        if n is None:
            n = conn.insert(schema, table, batch)
        return StatementResult(
            [], ["rows"], [T.BIGINT], update_type="INSERT", update_count=n
        )

    def _do_droptable(self, stmt: t.DropTable, session: Session) -> StatementResult:
        catalog, schema, table = self._qualify(stmt.name, session)
        self.access_control.check_can_drop(session.user, catalog, schema, table)
        conn = self.catalogs.get(catalog)
        self._check_txn_writable(session, conn, catalog)
        if conn.get_table(schema, table) is None and stmt.if_exists:
            return StatementResult([], ["result"], [T.BOOLEAN], update_type="DROP TABLE")
        with self._write_guard(session):
            conn.drop_table(schema, table)
        return StatementResult([], ["result"], [T.BOOLEAN], update_type="DROP TABLE")

    def _do_createtable(self, stmt: t.CreateTable, session: Session) -> StatementResult:
        catalog, schema, table = self._qualify(stmt.name, session)
        self.access_control.check_can_create(session.user, catalog, schema, table)
        conn = self.catalogs.get(catalog)
        self._check_txn_writable(session, conn, catalog)
        if conn.get_table(schema, table) is not None:
            if stmt.not_exists:
                return StatementResult(
                    [], ["result"], [T.BOOLEAN], update_type="CREATE TABLE"
                )
            raise SemanticError(f"table already exists: {catalog}.{schema}.{table}")
        cols = tuple(
            ColumnSchema(n.lower(), T.parse_type(ty)) for n, ty in stmt.columns
        )
        with self._write_guard(session):
            conn.create_table(schema, table, TableSchema(table, cols))
        return StatementResult([], ["result"], [T.BOOLEAN], update_type="CREATE TABLE")

    def _do_delete(self, stmt: t.Delete, session: Session) -> StatementResult:
        """DELETE removes rows where the predicate is TRUE; rows where it is
        FALSE or NULL remain (reference DELETE semantics). Implemented as
        keep-filter + truncate + reinsert (connector-neutral)."""
        catalog, schema, table = self._qualify(stmt.name, session)
        self.access_control.check_can_insert(session.user, catalog, schema, table)
        conn = self.catalogs.get(catalog)
        self._check_txn_writable(session, conn, catalog)
        ts = conn.get_table(schema, table)
        if ts is None:
            raise SemanticError(f"table not found: {catalog}.{schema}.{table}")
        if not hasattr(conn, "truncate"):
            raise SemanticError(f"{conn.name}: DELETE not supported")
        with self._write_guard(session):
            return self._do_delete_locked(stmt, session, conn, catalog, schema, table)

    def _do_delete_locked(self, stmt, session, conn, catalog, schema, table) -> StatementResult:
        before = conn.estimate_rows(schema, table) or 0
        if stmt.where is None:
            conn.truncate(schema, table)
            return StatementResult(
                [], ["rows"], [T.BIGINT], update_type="DELETE", update_count=before
            )
        keep_pred = t.BinaryOp(
            "OR", t.UnaryOp("NOT", stmt.where), t.IsNull(stmt.where)
        )
        keep_query = t.Query(
            body=t.QuerySpec(
                select_items=(t.SelectItem(t.Star()),),
                from_=t.Table((catalog, schema, table)),
                where=keep_pred,
            )
        )
        batch, _names = self._run_query_rows(keep_query, session)
        if hasattr(conn, "replace_data"):
            # durable stores swap data atomically: truncate-then-insert
            # would lose kept rows on a crash between the two steps
            conn.replace_data(schema, table, batch)
        else:
            conn.truncate(schema, table)
            if batch.num_rows:
                conn.insert(schema, table, batch)
        return StatementResult(
            [], ["rows"], [T.BIGINT],
            update_type="DELETE", update_count=before - batch.num_rows,
        )



    def _check_txn_writable(self, session: Session, conn, catalog: str) -> None:
        """Connectors without snapshot/restore cannot participate in
        explicit transactions (reference: 'Catalog only supports writes
        using autocommit')."""
        if session.properties.get("__txn") and not hasattr(conn, "snapshot_state"):
            raise SemanticError(
                f"Catalog '{catalog}' only supports writes using autocommit"
            )

    def _write_guard(self, session: Session):
        """Single-writer enforcement for autocommit writes: inside an
        explicit transaction the session already holds the write lock;
        otherwise hold it for the duration of this statement."""
        import contextlib

        if session.properties.get("__txn"):
            return contextlib.nullcontext()
        self.transaction_manager.expire_idle()
        lock = self.transaction_manager.write_lock

        @contextlib.contextmanager
        def guard():
            if not lock.acquire(timeout=60):
                from trino_tpu.transaction import TransactionError

                raise TransactionError("timed out waiting for the write lock")
            try:
                yield
            finally:
                lock.release()

        return guard()

    # === transactions =====================================================

    def _do_starttransaction(self, stmt, session: Session) -> StatementResult:
        if session.properties.get("__txn"):
            raise SemanticError("transaction already in progress")
        txn_id = self.transaction_manager.begin()
        session.properties["__txn"] = txn_id
        return StatementResult(
            [], ["result"], [T.BOOLEAN], update_type="START TRANSACTION",
            started_transaction_id=txn_id,
        )

    def _do_commit(self, stmt, session: Session) -> StatementResult:
        txn = session.properties.get("__txn")
        if not txn:
            raise SemanticError("no transaction in progress")
        self.transaction_manager.commit(txn)
        session.properties.pop("__txn", None)
        return StatementResult(
            [], ["result"], [T.BOOLEAN], update_type="COMMIT",
            cleared_transaction=True,
        )

    def _do_rollback(self, stmt, session: Session) -> StatementResult:
        txn = session.properties.get("__txn")
        if not txn:
            raise SemanticError("no transaction in progress")
        self.transaction_manager.rollback(txn)
        session.properties.pop("__txn", None)
        return StatementResult(
            [], ["result"], [T.BOOLEAN], update_type="ROLLBACK",
            cleared_transaction=True,
        )

    # === prepared statements (reference: Session.preparedStatements) ======

    def _do_prepare(self, stmt: t.Prepare, session: Session) -> StatementResult:
        # store SQL text when available (portable across protocol requests);
        # fall back to the AST for purely in-process sessions
        session.prepared[stmt.name.lower()] = stmt.sql or stmt.statement
        return StatementResult(
            [], ["result"], [T.BOOLEAN], update_type="PREPARE",
            added_prepare=(stmt.name.lower(), stmt.sql or ""),
        )

    def _do_execute(self, stmt: t.Execute, session: Session) -> StatementResult:
        inner = session.prepared.get(stmt.name.lower())
        if inner is None:
            raise SemanticError(f"prepared statement not found: {stmt.name}")
        if isinstance(inner, str):
            inner = parse_statement(inner)
        bound = _bind_parameters(inner, stmt.parameters)
        return self._dispatch_parsed(bound, session)

    def _do_deallocate(self, stmt: t.Deallocate, session: Session) -> StatementResult:
        session.prepared.pop(stmt.name.lower(), None)
        return StatementResult(
            [], ["result"], [T.BOOLEAN], update_type="DEALLOCATE",
            deallocated_prepare=stmt.name.lower(),
        )

    def _qualify(self, name_parts, session: Session) -> tuple[str, str, str]:
        parts = list(name_parts)
        if len(parts) == 1:
            return session.catalog, session.schema, parts[0]
        if len(parts) == 2:
            return session.catalog, parts[0], parts[1]
        return parts[0], parts[1], parts[2]


def _bind_parameters(stmt: t.Node, params: tuple) -> t.Node:
    """Replace ? placeholders with the EXECUTE ... USING expressions."""
    import dataclasses as _dc

    def walk(node):
        if isinstance(node, t.Parameter):
            if node.index >= len(params):
                raise SemanticError(
                    f"no value provided for parameter {node.index + 1}"
                )
            return params[node.index]
        if _dc.is_dataclass(node) and isinstance(node, t.Node):
            changes = {}
            for f in _dc.fields(node):
                v = getattr(node, f.name)
                if isinstance(v, t.Node):
                    changes[f.name] = walk(v)
                elif isinstance(v, tuple):
                    changes[f.name] = tuple(
                        walk(x) if isinstance(x, t.Node)
                        else (
                            tuple(
                                walk(y) if isinstance(y, t.Node) else y
                                for y in x
                            )
                            if isinstance(x, tuple)
                            else x
                        )
                        for x in v
                    )
            return _dc.replace(node, **changes) if changes else node
        return node

    return walk(stmt)
