"""Client protocol library + statement client.

Reference: ``client/trino-client/src/main/java/io/trino/client/StatementClientV1.java:62,125,324``
— POST /v1/statement, then follow ``nextUri`` until absent; typed results
via ``columns``; session mutations via ``X-Trino-Set-Session`` headers.
Stdlib ``urllib`` only (the reference uses OkHttp).
"""

from __future__ import annotations

import dataclasses
import json
import urllib.error
import urllib.parse
import urllib.request
from decimal import Decimal
from typing import Any, Iterator, Optional

HEADER = "X-Trino"


class QueryFailure(Exception):
    def __init__(self, error: dict):
        self.error = error
        super().__init__(
            f"{error.get('errorName', 'ERROR')}: {error.get('message', '')}"
        )


@dataclasses.dataclass
class Column:
    name: str
    type: str


class StatementClient:
    """Drives one statement through the paged protocol."""

    def __init__(self, base_uri: str, sql: str, session: "ClientSession"):
        self.base_uri = base_uri.rstrip("/")
        self.sql = sql
        self.session = session
        self.columns: Optional[list[Column]] = None
        self.update_type: Optional[str] = None
        self.update_count: Optional[int] = None
        self.stats: dict = {}
        self.query_id: Optional[str] = None
        self._next_uri: Optional[str] = None
        self._current_data: list[list[Any]] = []
        self._started = False

    # --- protocol ---------------------------------------------------------

    def _headers(self) -> dict[str, str]:
        h = {
            f"{HEADER}-User": self.session.user,
            f"{HEADER}-Source": "trino-tpu-client",
        }
        if self.session.catalog:
            h[f"{HEADER}-Catalog"] = self.session.catalog
        if self.session.schema:
            h[f"{HEADER}-Schema"] = self.session.schema
        if self.session.properties:
            h[f"{HEADER}-Session"] = ",".join(
                f"{k}={urllib.parse.quote(str(v))}"
                for k, v in self.session.properties.items()
            )
        if self.session.prepared_statements:
            h[f"{HEADER}-Prepared-Statement"] = ",".join(
                f"{k}={urllib.parse.quote(v)}"
                for k, v in self.session.prepared_statements.items()
            )
        if self.session.transaction_id:
            h[f"{HEADER}-Transaction-Id"] = self.session.transaction_id
        return h

    def _request(self, method: str, uri: str, body: Optional[bytes] = None) -> dict:
        """One protocol round trip, honoring 503 + Retry-After shedding.

        A shed response (503 carrying Retry-After) means the server is
        overloaded, not failing: back off (jittered, deterministic —
        ``ft.retry.Backoff``, floored at the server's hint) and retry a
        bounded number of times. A 503 without Retry-After (draining
        server) is not retried — that server is going away."""
        attempts = max(1, int(self.session.shed_retry_attempts))
        for attempt in range(1, attempts + 1):
            try:
                return self._request_once(method, uri, body)
            except urllib.error.HTTPError as e:
                retry_after = e.headers.get("Retry-After") if e.headers else None
                if e.code != 503 or retry_after is None or attempt >= attempts:
                    raise
                _sleep_for_retry(retry_after, attempt)
        raise AssertionError("unreachable")

    def _request_once(self, method: str, uri: str, body: Optional[bytes] = None) -> dict:
        req = urllib.request.Request(uri, data=body, method=method)
        for k, v in self._headers().items():
            req.add_header(k, v)
        with urllib.request.urlopen(
            req, timeout=self.session.request_timeout
        ) as resp:
            set_session = resp.headers.get(f"{HEADER}-Set-Session")
            if set_session and "=" in set_session:
                k, v = set_session.split("=", 1)
                self.session.properties[k] = urllib.parse.unquote(v)
            added = resp.headers.get(f"{HEADER}-Added-Prepare")
            if added and "=" in added:
                k, v = added.split("=", 1)
                self.session.prepared_statements[k] = urllib.parse.unquote(v)
            dealloc = resp.headers.get(f"{HEADER}-Deallocated-Prepare")
            if dealloc:
                self.session.prepared_statements.pop(dealloc, None)
            started = resp.headers.get(f"{HEADER}-Started-Transaction-Id")
            if started:
                self.session.transaction_id = started
            if resp.headers.get(f"{HEADER}-Clear-Transaction-Id"):
                self.session.transaction_id = None
            return json.loads(resp.read().decode())

    def _advance_state(self, payload: dict) -> None:
        self.query_id = payload.get("id", self.query_id)
        self.stats = payload.get("stats", self.stats)
        if "columns" in payload and self.columns is None:
            self.columns = [
                Column(c["name"], c["type"]) for c in payload["columns"]
            ]
        self.update_type = payload.get("updateType", self.update_type)
        if "updateCount" in payload:
            self.update_count = payload["updateCount"]
        if payload.get("error"):
            raise QueryFailure(payload["error"])
        self._current_data = payload.get("data", [])
        self._next_uri = payload.get("nextUri")

    def advance(self) -> bool:
        """POST on first call, then follow nextUri (StatementClientV1.advance)."""
        if not self._started:
            self._started = True
            payload = self._request(
                "POST", f"{self.base_uri}/v1/statement", self.sql.encode()
            )
            self._advance_state(payload)
            return True
        if self._next_uri is None:
            return False
        self._advance_state(self._request("GET", self._next_uri))
        return True

    def cancel(self) -> None:
        if self._next_uri is not None:
            try:
                self._request("DELETE", self._next_uri)
            except urllib.error.HTTPError:
                pass
        self._next_uri = None

    # --- results ----------------------------------------------------------

    def rows(self) -> Iterator[tuple]:
        """All rows, typed (decimal strings -> Decimal)."""
        while self.advance():
            types = [c.type for c in self.columns] if self.columns else []
            for row in self._current_data:
                yield tuple(
                    _decode_value(v, types[i] if i < len(types) else "")
                    for i, v in enumerate(row)
                )


def _sleep_for_retry(retry_after: str, attempt: int) -> None:
    import time

    from trino_tpu.ft.retry import Backoff

    base_ms = 100.0
    try:
        base_ms = max(base_ms, float(retry_after) * 1000.0)
    except (TypeError, ValueError):
        pass
    delay = Backoff(
        initial_ms=base_ms, max_ms=max(4 * base_ms, 5000.0), seed=0
    ).delay(attempt)
    time.sleep(delay)


def _decode_value(v: Any, type_: str) -> Any:
    if v is None:
        return None
    if type_.startswith("decimal"):
        return Decimal(v)
    return v


@dataclasses.dataclass
class ClientSession:
    user: str = "user"
    catalog: Optional[str] = "tpch"
    schema: Optional[str] = "tiny"
    properties: dict[str, Any] = dataclasses.field(default_factory=dict)
    # name -> SQL text, mirrored via X-Trino-*-Prepare headers
    prepared_statements: dict[str, str] = dataclasses.field(default_factory=dict)
    # explicit transaction id (X-Trino-Transaction-Id roundtrip)
    transaction_id: Optional[str] = None
    # per-request socket timeout (seconds) for the statement protocol
    # (OkHttp client timeout analog; chaos tests shrink it)
    request_timeout: float = 120.0
    # total tries for a request shed with 503 + Retry-After (overload);
    # 1 disables retries entirely
    shed_retry_attempts: int = 3


class Connection:
    """DB-API-flavored convenience wrapper (the trino-jdbc analog tier)."""

    def __init__(self, base_uri: str, session: Optional[ClientSession] = None):
        self.base_uri = base_uri
        self.session = session or ClientSession()

    def execute(self, sql: str) -> tuple[list[tuple], list[str]]:
        client = StatementClient(self.base_uri, sql, self.session)
        rows = list(client.rows())
        names = [c.name for c in client.columns] if client.columns else []
        return rows, names

    # --- server introspection -------------------------------------------

    def server_info(self) -> dict:
        with urllib.request.urlopen(f"{self.base_uri}/v1/info", timeout=10) as r:
            return json.loads(r.read().decode())

    def list_queries(self) -> list[dict]:
        with urllib.request.urlopen(f"{self.base_uri}/v1/query", timeout=10) as r:
            return json.loads(r.read().decode())
