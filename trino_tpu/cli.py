"""Interactive SQL CLI.

Reference: ``client/trino-cli`` (``Console.java``, ``Query.java``,
``StatusPrinter.java``) — REPL, aligned/CSV/JSON output formats, \\commands.
Stdlib-only (the reference uses JLine).

Usage:
    python -m trino_tpu.cli --server http://127.0.0.1:8080 [--execute SQL]
                            [--output-format ALIGNED|CSV|JSON]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from trino_tpu.client import ClientSession, QueryFailure, StatementClient


def format_aligned(names: list[str], rows: list[tuple]) -> str:
    cols = [names] + [[("NULL" if v is None else str(v)) for v in r] for r in rows]
    widths = [max(len(row[i]) for row in cols) for i in range(len(names))]
    def line(row):
        return " | ".join(s.ljust(w) for s, w in zip(row, widths))
    sep = "-+-".join("-" * w for w in widths)
    out = [line(names), sep]
    out += [line(r) for r in cols[1:]]
    return "\n".join(out)


def format_csv(names: list[str], rows: list[tuple]) -> str:
    import csv
    import io

    buf = io.StringIO()
    w = csv.writer(buf)
    for r in rows:
        w.writerow(["" if v is None else v for v in r])
    return buf.getvalue().rstrip("\n")


def format_json(names: list[str], rows: list[tuple]) -> str:
    return "\n".join(
        json.dumps({n: (str(v) if v is not None and not isinstance(v, (int, float, bool, str)) else v)
                    for n, v in zip(names, r)})
        for r in rows
    )


FORMATS = {"ALIGNED": format_aligned, "CSV": format_csv, "JSON": format_json}


def run_statement(server: str, session: ClientSession, sql: str, fmt: str) -> int:
    t0 = time.time()
    client = StatementClient(server, sql, session)
    try:
        rows = list(client.rows())
    except QueryFailure as f:
        print(f"Query failed: {f}", file=sys.stderr)
        return 1
    names = [c.name for c in client.columns] if client.columns else []
    if client.update_type:
        n = f" {client.update_count} rows" if client.update_count is not None else ""
        print(f"{client.update_type}{n}")
    if rows or not client.update_type:
        formatter = FORMATS[fmt]
        if fmt == "ALIGNED":
            print(formatter(names, rows))
            dt = time.time() - t0
            print(f"({len(rows)} row{'s' if len(rows) != 1 else ''} in {dt:.2f}s)")
        else:
            print(formatter(names, rows))
    return 0


def repl(server: str, session: ClientSession, fmt: str) -> int:
    print(f"trino-tpu CLI — connected to {server}")
    print('Type a SQL statement ending with ";", or "quit".')
    buf: list[str] = []
    while True:
        try:
            prompt = "trino> " if not buf else "    -> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        stripped = line.strip()
        if not buf and stripped.lower() in ("quit", "exit", "quit;", "exit;"):
            return 0
        if not buf and not stripped:
            continue
        buf.append(line)
        if stripped.endswith(";"):
            sql = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            run_statement(server, session, sql, fmt)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--user", default="user")
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    ap.add_argument(
        "--output-format", default="ALIGNED", choices=sorted(FORMATS)
    )
    args = ap.parse_args(argv)
    session = ClientSession(args.user, args.catalog, args.schema)
    if args.execute:
        return run_statement(args.server, session, args.execute, args.output_format)
    return repl(args.server, session, args.output_format)


if __name__ == "__main__":
    sys.exit(main())
