"""SQL frontend: lexer, parser, AST.

Reference: ``core/trino-parser`` (ANTLR4 grammar ``SqlBase.g4``, 197 AST
classes). Here: a hand-rolled lexer + Pratt parser covering the
TPC-H/TPC-DS-class SQL subset, producing a compact AST.
"""

from trino_tpu.sql.parser import parse_statement  # noqa: F401
