"""SQL lexer.

Reference: the lexical rules of ``core/trino-parser/src/main/antlr4/io/trino/sql/parser/SqlBase.g4``
(identifiers, quoted identifiers, string literals with '' escape, numbers,
comments). Keywords are recognized case-insensitively; non-reserved words
may still be identifiers (handled in the parser).
"""

from __future__ import annotations

import dataclasses


class SqlSyntaxError(Exception):
    def __init__(self, message: str, line: int = 0, col: int = 0):
        super().__init__(f"line {line}:{col}: {message}" if line else message)
        self.line = line
        self.col = col


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str  # IDENT, QIDENT, STRING, NUMBER, OP, KW, EOF
    text: str
    line: int
    col: int

    @property
    def upper(self) -> str:
        return self.text.upper()


KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "AS", "AND", "OR", "NOT", "IN", "EXISTS", "BETWEEN", "LIKE", "ESCAPE",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "CAST", "TRY_CAST", "EXTRACT", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "OUTER", "CROSS", "ON", "USING", "UNION", "ALL", "DISTINCT", "EXCEPT",
    "INTERSECT", "WITH", "RECURSIVE", "ASC", "DESC", "NULLS", "FIRST",
    "LAST", "INTERVAL", "DATE", "TIME", "TIMESTAMP", "YEAR", "MONTH", "DAY",
    "HOUR", "MINUTE", "SECOND", "OVER", "PARTITION", "ROWS", "RANGE",
    "UNBOUNDED", "PRECEDING", "FOLLOWING", "CURRENT", "ROW", "VALUES",
    "INSERT", "INTO", "CREATE", "TABLE", "DROP", "DELETE", "UPDATE", "SET",
    "SHOW", "DESCRIBE", "EXPLAIN", "ANALYZE", "SUBSTRING", "FOR", "OFFSET",
    "FETCH", "NEXT", "ONLY", "GROUPING", "SETS", "ROLLUP", "CUBE", "IF",
    "SESSION", "TABLES", "SCHEMAS", "CATALOGS", "COLUMNS", "FILTER",
    "PREPARE", "EXECUTE", "DEALLOCATE", "ANY", "SOME", "POSITION",
    "START", "TRANSACTION", "COMMIT", "ROLLBACK",
}

_MULTI_OPS = ("<>", "<=", ">=", "!=", "||")
_SINGLE_OPS = "+-*/%(),.;<>=[]?"


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        ch = sql[i]
        if ch in " \t\r\n":
            advance(1)
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            advance((j - i) if j >= 0 else (n - i))
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlSyntaxError("unterminated block comment", line, col)
            advance(j + 2 - i)
            continue
        start_line, start_col = line, col
        if ch == "'":
            # string literal, '' escapes a quote
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlSyntaxError("unterminated string", start_line, start_col)
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token("STRING", "".join(buf), start_line, start_col))
            advance(j + 1 - i)
            continue
        if ch == '"':
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlSyntaxError("unterminated quoted identifier", start_line, start_col)
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        buf.append('"')
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token("QIDENT", "".join(buf), start_line, start_col))
            advance(j + 1 - i)
            continue
        if ch.isdigit() or (ch == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                c = sql[j]
                if c.isdigit():
                    j += 1
                elif c == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif c in "eE" and not seen_exp and j > i:
                    if j + 1 < n and (sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                        seen_exp = True
                        j += 2 if sql[j + 1] in "+-" else 1
                    else:
                        break
                else:
                    break
            tokens.append(Token("NUMBER", sql[i:j], start_line, start_col))
            advance(j - i)
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            text = sql[i:j]
            kind = "KW" if text.upper() in KEYWORDS else "IDENT"
            tokens.append(Token(kind, text, start_line, start_col))
            advance(j - i)
            continue
        matched = False
        for op in _MULTI_OPS:
            if sql.startswith(op, i):
                tokens.append(Token("OP", op, start_line, start_col))
                advance(len(op))
                matched = True
                break
        if matched:
            continue
        if ch in _SINGLE_OPS:
            tokens.append(Token("OP", ch, start_line, start_col))
            advance(1)
            continue
        raise SqlSyntaxError(f"unexpected character {ch!r}", line, col)
    tokens.append(Token("EOF", "", line, col))
    return tokens
