"""Recursive-descent + Pratt SQL parser.

Reference: ``core/trino-parser/src/main/java/io/trino/sql/parser/SqlParser.java:44,82``
and the grammar ``SqlBase.g4`` (precedence: OR < AND < NOT < predicate
(comparison/BETWEEN/IN/LIKE/IS) < additive/|| < multiplicative < unary).
"""

from __future__ import annotations

from typing import Optional

from trino_tpu.sql import tree as t
from trino_tpu.sql.lexer import SqlSyntaxError, Token, tokenize


def parse_statement(sql: str) -> t.Node:
    return Parser(tokenize(sql)).parse_statement()


def parse_expression(sql: str) -> t.Node:
    p = Parser(tokenize(sql))
    e = p.expression()
    p.expect_eof()
    return e


class Parser:
    def __init__(self, tokens: list[Token]):
        self.tokens = tokens
        self.pos = 0
        self._param_index = 0

    # --- token helpers ---------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.pos + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        tok = self.tokens[self.pos]
        if tok.kind != "EOF":
            self.pos += 1
        return tok

    def at_kw(self, *kws: str) -> bool:
        tok = self.peek()
        return tok.kind == "KW" and tok.upper in kws

    def at_op(self, *ops: str) -> bool:
        tok = self.peek()
        return tok.kind == "OP" and tok.text in ops

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> Token:
        tok = self.peek()
        if not self.at_kw(kw):
            raise SqlSyntaxError(f"expected {kw}, found {tok.text!r}", tok.line, tok.col)
        return self.next()

    def expect_op(self, op: str) -> Token:
        tok = self.peek()
        if not self.at_op(op):
            raise SqlSyntaxError(f"expected {op!r}, found {tok.text!r}", tok.line, tok.col)
        return self.next()

    def expect_eof(self):
        tok = self.peek()
        if tok.kind != "EOF":
            raise SqlSyntaxError(f"unexpected trailing input {tok.text!r}", tok.line, tok.col)

    def identifier(self) -> str:
        tok = self.peek()
        if tok.kind in ("IDENT", "QIDENT"):
            self.next()
            return tok.text
        # non-reserved keywords usable as identifiers
        if tok.kind == "KW" and tok.upper in _NONRESERVED:
            self.next()
            return tok.text
        raise SqlSyntaxError(f"expected identifier, found {tok.text!r}", tok.line, tok.col)

    def qualified_name(self) -> tuple[str, ...]:
        parts = [self.identifier()]
        while self.accept_op("."):
            parts.append(self.identifier())
        return tuple(parts)

    # --- statements ------------------------------------------------------
    def parse_statement(self) -> t.Node:
        stmt = self._statement()
        self.accept_op(";")
        self.expect_eof()
        return stmt

    def _statement(self) -> t.Node:
        if self.at_kw("EXPLAIN"):
            self.next()
            analyze = self.accept_kw("ANALYZE")
            return t.Explain(self._statement(), analyze=analyze)
        if self.at_kw("SET"):
            self.next()
            self.expect_kw("SESSION")
            name = ".".join(self.qualified_name())
            self.expect_op("=")
            value = self.expression()
            return t.SetSession(name, value)
        if self.at_kw("SHOW"):
            self.next()
            if self.accept_kw("TABLES"):
                schema = None
                if self.accept_kw("FROM") or self.accept_kw("IN"):
                    schema = self.qualified_name()
                return t.ShowTables(schema)
            if self.accept_kw("SCHEMAS"):
                catalog = None
                if self.accept_kw("FROM") or self.accept_kw("IN"):
                    catalog = self.identifier()
                return t.ShowSchemas(catalog)
            if self.accept_kw("CATALOGS"):
                return t.ShowCatalogs()
            if self.accept_kw("COLUMNS"):
                self.expect_kw("FROM")
                return t.ShowColumns(self.qualified_name())
            tok = self.peek()
            raise SqlSyntaxError(f"unsupported SHOW {tok.text!r}", tok.line, tok.col)
        if self.at_kw("DESCRIBE"):
            self.next()
            return t.ShowColumns(self.qualified_name())
        if self.at_kw("CREATE"):
            self.next()
            self.expect_kw("TABLE")
            not_exists = False
            if self.at_kw("IF"):
                self.next()
                tok = self.peek()
                if tok.kind == "KW" and tok.upper == "NOT":
                    self.next()
                    self.expect_kw("EXISTS")
                    not_exists = True
            name = self.qualified_name()
            if self.at_op("("):
                self.expect_op("(")
                cols = []
                while True:
                    cname = self.identifier()
                    ty = self._type_text()
                    cols.append((cname, ty))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                return t.CreateTable(name, tuple(cols), not_exists)
            self.expect_kw("AS")
            return t.CreateTableAsSelect(name, self.query())
        if self.at_kw("DELETE"):
            self.next()
            self.expect_kw("FROM")
            name = self.qualified_name()
            where = self.expression() if self.accept_kw("WHERE") else None
            return t.Delete(name, where)
        if self.at_kw("PREPARE"):
            self.next()
            name = self.identifier()
            self.expect_kw("FROM")
            stmt = self._statement()
            return t.Prepare(name, stmt)
        if self.at_kw("EXECUTE"):
            self.next()
            name = self.identifier()
            params: tuple[t.Node, ...] = ()
            if self.accept_kw("USING"):
                ps = [self.expression()]
                while self.accept_op(","):
                    ps.append(self.expression())
                params = tuple(ps)
            return t.Execute(name, params)
        if self.at_kw("START"):
            self.next()
            self.expect_kw("TRANSACTION")
            return t.StartTransaction()
        if self.at_kw("COMMIT"):
            self.next()
            return t.Commit()
        if self.at_kw("ROLLBACK"):
            self.next()
            return t.Rollback()
        if self.at_kw("DEALLOCATE"):
            self.next()
            self.expect_kw("PREPARE")
            return t.Deallocate(self.identifier())
        if self.at_kw("INSERT"):
            self.next()
            self.expect_kw("INTO")
            name = self.qualified_name()
            columns: tuple[str, ...] = ()
            if self.at_op("(") and self._looks_like_column_list():
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                columns = tuple(cols)
            return t.InsertInto(name, columns, self.query())
        if self.at_kw("DROP"):
            self.next()
            self.expect_kw("TABLE")
            if_exists = False
            if self.at_kw("IF"):
                self.next()
                # IF EXISTS
                tok = self.peek()
                if tok.kind == "KW" and tok.upper == "EXISTS":
                    self.next()
                    if_exists = True
            return t.DropTable(self.qualified_name(), if_exists)
        return self.query()

    def _looks_like_column_list(self) -> bool:
        # distinguish INSERT INTO t (a, b) SELECT ... from INSERT INTO t (SELECT ...)
        i = 1
        tok = self.peek(i)
        return tok.kind in ("IDENT", "QIDENT") or (
            tok.kind == "KW" and tok.upper in _NONRESERVED
        )

    # --- query -----------------------------------------------------------
    def query(self) -> t.Query:
        with_queries: list[t.WithQuery] = []
        if self.accept_kw("WITH"):
            self.accept_kw("RECURSIVE")  # parsed, not supported in analyzer
            while True:
                name = self.identifier()
                column_aliases: tuple[str, ...] = ()
                if self.at_op("("):
                    self.expect_op("(")
                    cols = [self.identifier()]
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                    column_aliases = tuple(cols)
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                with_queries.append(t.WithQuery(name, q, column_aliases))
                if not self.accept_op(","):
                    break
        body = self._set_operation()
        order_by: tuple[t.SortItem, ...] = ()
        if self.at_kw("ORDER"):
            order_by = self._order_by()
        limit: Optional[int] = None
        offset = 0
        if self.accept_kw("OFFSET"):
            offset = int(self.next().text)
            self.accept_kw("ROWS") or self.accept_kw("ROW")
        if self.accept_kw("LIMIT"):
            tok = self.next()
            limit = None if tok.upper == "ALL" else int(tok.text)
            if self.accept_kw("OFFSET"):  # LIMIT n OFFSET m order
                offset = int(self.next().text)
                self.accept_kw("ROWS") or self.accept_kw("ROW")
        elif self.accept_kw("FETCH"):
            self.accept_kw("FIRST") or self.accept_kw("NEXT")
            limit = int(self.next().text)
            self.accept_kw("ROWS") or self.accept_kw("ROW")
            self.expect_kw("ONLY")
        return t.Query(body, tuple(with_queries), order_by, limit, offset)

    def _order_by(self) -> tuple[t.SortItem, ...]:
        self.expect_kw("ORDER")
        self.expect_kw("BY")
        items = [self._sort_item()]
        while self.accept_op(","):
            items.append(self._sort_item())
        return tuple(items)

    def _sort_item(self) -> t.SortItem:
        e = self.expression()
        ascending = True
        if self.accept_kw("ASC"):
            pass
        elif self.accept_kw("DESC"):
            ascending = False
        nulls_first: Optional[bool] = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return t.SortItem(e, ascending, nulls_first)

    def _set_operation(self) -> t.Node:
        # SQL precedence: INTERSECT binds tighter than UNION/EXCEPT
        left = self._intersect_term()
        while self.at_kw("UNION", "EXCEPT"):
            op = self.next().upper
            distinct = True
            if self.accept_kw("ALL"):
                distinct = False
            else:
                self.accept_kw("DISTINCT")
            right = self._intersect_term()
            left = t.SetOperation(op, distinct, left, right)
        return left

    def _intersect_term(self) -> t.Node:
        left = self._query_term()
        while self.at_kw("INTERSECT"):
            self.next()
            distinct = True
            if self.accept_kw("ALL"):
                distinct = False
            else:
                self.accept_kw("DISTINCT")
            right = self._query_term()
            left = t.SetOperation("INTERSECT", distinct, left, right)
        return left

    def _query_term(self) -> t.Node:
        if self.at_op("("):
            # parenthesized query
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return q.body if not (q.order_by or q.limit or q.with_queries) else q
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return t.Values(tuple(rows))
        return self._query_spec()

    def _values_row(self) -> tuple[t.Node, ...]:
        if self.accept_op("("):
            items = [self.expression()]
            while self.accept_op(","):
                items.append(self.expression())
            self.expect_op(")")
            return tuple(items)
        return (self.expression(),)

    def _query_spec(self) -> t.QuerySpec:
        self.expect_kw("SELECT")
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        else:
            self.accept_kw("ALL")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_: Optional[t.Node] = None
        if self.accept_kw("FROM"):
            from_ = self._relation()
            while self.accept_op(","):
                right = self._relation()
                from_ = t.Join("CROSS", from_, right)
        where = self.expression() if self.accept_kw("WHERE") else None
        group_by: tuple[t.Node, ...] = ()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            exprs = [self._group_by_element()]
            while self.accept_op(","):
                exprs.append(self._group_by_element())
            group_by = tuple(exprs)
        having = self.expression() if self.accept_kw("HAVING") else None
        return t.QuerySpec(tuple(items), distinct, from_, where, group_by, having)

    def _group_by_element(self) -> t.Node:
        """Plain expression, or ROLLUP/CUBE/GROUPING SETS
        (reference grammar: SqlBase.g4 groupingElement)."""
        if self.at_kw("ROLLUP") or self.at_kw("CUBE"):
            kind = self.next().upper
            self.expect_op("(")
            cols = [self.expression()]
            while self.accept_op(","):
                cols.append(self.expression())
            self.expect_op(")")
            cols = tuple(cols)
            if kind == "ROLLUP":
                sets = tuple(tuple(cols[:i]) for i in range(len(cols), -1, -1))
            else:  # CUBE: all subsets, larger first
                import itertools as _it

                sets = tuple(
                    tuple(c)
                    for r in range(len(cols), -1, -1)
                    for c in _it.combinations(cols, r)
                )
            return t.GroupingSets(kind, sets)
        if self.at_kw("GROUPING"):
            save = self.pos
            self.next()
            if self.accept_kw("SETS"):
                self.expect_op("(")
                sets = []
                while True:
                    if self.accept_op("("):
                        inner = []
                        if not self.at_op(")"):
                            inner.append(self.expression())
                            while self.accept_op(","):
                                inner.append(self.expression())
                        self.expect_op(")")
                        sets.append(tuple(inner))
                    else:
                        sets.append((self.expression(),))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                return t.GroupingSets("GROUPING SETS", tuple(sets))
            self.pos = save  # grouping(...) function call
        return self.expression()

    def _select_item(self) -> t.SelectItem:
        if self.at_op("*"):
            self.next()
            return t.SelectItem(t.Star())
        # qualified star: only single-qualifier `t.*` is supported
        if (
            self.peek().kind in ("IDENT", "QIDENT")
            and self.peek(1).kind == "OP"
            and self.peek(1).text == "."
            and self.peek(2).kind == "OP"
            and self.peek(2).text == "*"
        ):
            q = self.identifier()
            self.expect_op(".")
            self.expect_op("*")
            return t.SelectItem(t.Star(qualifier=q))
        e = self.expression()
        alias = None
        if self.accept_kw("AS"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT") or (
            self.peek().kind == "KW" and self.peek().upper in _NONRESERVED
        ):
            alias = self.identifier()
        return t.SelectItem(e, alias)

    # --- relations -------------------------------------------------------
    def _relation(self) -> t.Node:
        left = self._aliased_relation()
        while True:
            if self.accept_kw("CROSS"):
                self.expect_kw("JOIN")
                right = self._aliased_relation()
                left = t.Join("CROSS", left, right)
                continue
            join_type = None
            if self.at_kw("JOIN"):
                join_type = "INNER"
                self.next()
            elif self.at_kw("INNER"):
                self.next()
                self.expect_kw("JOIN")
                join_type = "INNER"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                join_type = self.next().upper
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
            if join_type is None:
                return left
            right = self._aliased_relation()
            if self.accept_kw("ON"):
                criteria = self.expression()
                left = t.Join(join_type, left, right, criteria=criteria)
            elif self.accept_kw("USING"):
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                left = t.Join(join_type, left, right, using=tuple(cols))
            else:
                tok = self.peek()
                raise SqlSyntaxError("JOIN requires ON or USING", tok.line, tok.col)

    def _aliased_relation(self) -> t.Node:
        rel = self._primary_relation()
        alias = None
        column_aliases: tuple[str, ...] = ()
        if self.accept_kw("AS"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT") or (
            self.peek().kind == "KW" and self.peek().upper in _NONRESERVED
        ):
            alias = self.identifier()
        if alias is not None and self.at_op("(") and self._is_alias_list():
            self.expect_op("(")
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            column_aliases = tuple(cols)
        if alias is not None:
            return t.AliasedRelation(rel, alias, column_aliases)
        return rel

    def _is_alias_list(self) -> bool:
        tok = self.peek(1)
        return tok.kind in ("IDENT", "QIDENT") and self.peek(2).kind == "OP" and self.peek(2).text in (",", ")")

    def _primary_relation(self) -> t.Node:
        tok = self.peek()
        if (
            tok.kind == "IDENT"
            and tok.upper == "UNNEST"
            and self.peek(1).kind == "OP"
            and self.peek(1).text == "("
        ):
            self.next()
            self.expect_op("(")
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            with_ord = False
            if self.at_kw("WITH"):
                self.next()
                ident = self.next()
                if ident.upper != "ORDINALITY":
                    raise SqlSyntaxError(
                        "expected ORDINALITY", ident.line, ident.col
                    )
                with_ord = True
            return t.Unnest(tuple(exprs), with_ord)
        if self.at_op("("):
            self.expect_op("(")
            if self.at_kw("SELECT", "WITH", "VALUES"):
                q = self.query()
                self.expect_op(")")
                return t.SubqueryRelation(q)
            if self.at_op("("):
                # ambiguous: "((select ...) except (select ...))" is a
                # set-op subquery; "((a join b) ...)" is a relation.
                # Try the query grammar first, backtrack on failure.
                save = self.pos
                try:
                    q = self.query()
                    self.expect_op(")")
                    return t.SubqueryRelation(q)
                except SqlSyntaxError:
                    self.pos = save
            rel = self._relation()
            self.expect_op(")")
            return rel
        if self.at_kw("VALUES"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return t.SubqueryRelation(t.Query(t.Values(tuple(rows))))
        return t.Table(self.qualified_name())

    # --- expressions (Pratt) --------------------------------------------
    def expression(self) -> t.Node:
        return self._or_expr()

    def _or_expr(self) -> t.Node:
        left = self._and_expr()
        while self.accept_kw("OR"):
            left = t.BinaryOp("OR", left, self._and_expr())
        return left

    def _and_expr(self) -> t.Node:
        left = self._not_expr()
        while self.accept_kw("AND"):
            left = t.BinaryOp("AND", left, self._not_expr())
        return left

    def _not_expr(self) -> t.Node:
        if self.accept_kw("NOT"):
            return t.UnaryOp("NOT", self._not_expr())
        return self._predicate()

    def _predicate(self) -> t.Node:
        left = self._additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().text
                if op == "!=":
                    op = "<>"
                if self.at_kw("ANY", "SOME", "ALL"):
                    quant = self.next().upper
                    if quant == "SOME":
                        quant = "ANY"
                    self.expect_op("(")
                    q = self.query()
                    self.expect_op(")")
                    left = t.QuantifiedComparison(op, quant, left, q)
                    continue
                right = self._additive()
                left = t.BinaryOp(op, left, right)
                continue
            negated = False
            save = self.pos
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("BETWEEN"):
                low = self._additive()
                self.expect_kw("AND")
                high = self._additive()
                left = t.Between(left, low, high, negated)
                continue
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.query()
                    self.expect_op(")")
                    left = t.InSubquery(left, q, negated)
                else:
                    items = [self.expression()]
                    while self.accept_op(","):
                        items.append(self.expression())
                    self.expect_op(")")
                    left = t.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("LIKE"):
                pattern = self._additive()
                escape = None
                if self.accept_kw("ESCAPE"):
                    escape = self._additive()
                left = t.Like(left, pattern, escape, negated)
                continue
            if negated:
                self.pos = save
                break
            if self.accept_kw("IS"):
                neg = self.accept_kw("NOT")
                self.expect_kw("NULL")
                left = t.IsNull(left, negated=neg)
                continue
            break
        return left

    def _additive(self) -> t.Node:
        left = self._multiplicative()
        while self.at_op("+", "-", "||"):
            op = self.next().text
            left = t.BinaryOp(op, left, self._multiplicative())
        return left

    def _multiplicative(self) -> t.Node:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = t.BinaryOp(op, left, self._unary())
        return left

    def _unary(self) -> t.Node:
        if self.at_op("-", "+"):
            op = self.next().text
            return t.UnaryOp(op, self._unary())
        out = self._primary()
        # postfix subscript: base[index] (ARRAY element / MAP value / ROW
        # field — reference SqlBase.g4 subscript rule)
        while self.at_op("["):
            self.next()
            idx = self.expression()
            self.expect_op("]")
            out = t.Subscript(out, idx)
        return out

    def _primary(self) -> t.Node:
        tok = self.peek()
        if tok.kind == "NUMBER":
            self.next()
            text = tok.text
            if "e" in text.lower():
                return t.Literal(float(text), "double")
            if "." in text:
                return t.Literal(text, "decimal")
            return t.Literal(int(text), "integer")
        if tok.kind == "STRING":
            self.next()
            return t.Literal(tok.text, "string")
        if tok.kind == "OP" and tok.text == "?":
            self.next()
            self._param_index += 1
            return t.Parameter(self._param_index - 1)
        if tok.kind == "OP" and tok.text == "(":
            self.next()
            if self.at_kw("SELECT", "WITH"):
                q = self.query()
                self.expect_op(")")
                return t.ScalarSubquery(q)
            e = self.expression()
            self.expect_op(")")
            return e
        if tok.kind == "KW":
            kw = tok.upper
            if kw == "NULL":
                self.next()
                return t.Literal(None, "null")
            if kw in ("TRUE", "FALSE"):
                self.next()
                return t.Literal(kw == "TRUE", "boolean")
            if kw == "DATE":
                # DATE 'yyyy-mm-dd'
                if self.peek(1).kind == "STRING":
                    self.next()
                    s = self.next().text
                    return t.Literal(s, "date")
            if kw == "TIMESTAMP":
                if self.peek(1).kind == "STRING":
                    self.next()
                    s = self.next().text
                    return t.Literal(s, "timestamp")
            if kw == "INTERVAL":
                self.next()
                sign = 1
                if self.accept_op("-"):
                    sign = -1
                elif self.accept_op("+"):
                    pass
                v = self.next()
                unit = self.next().upper.rstrip("S")
                return t.IntervalLiteral(int(v.text), unit.lower(), sign)
            if kw in ("CAST", "TRY_CAST"):
                self.next()
                self.expect_op("(")
                e = self.expression()
                self.expect_kw("AS")
                target = self._type_text()
                self.expect_op(")")
                return t.Cast(e, target, safe=(kw == "TRY_CAST"))
            if kw == "EXTRACT":
                self.next()
                self.expect_op("(")
                field = self.next().upper
                self.expect_kw("FROM")
                e = self.expression()
                self.expect_op(")")
                return t.Extract(field.lower(), e)
            if kw == "CASE":
                return self._case()
            if kw == "EXISTS":
                self.next()
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                return t.Exists(q)
            if kw == "SUBSTRING":
                # SUBSTRING(x FROM a FOR b) or substring(x, a, b)
                self.next()
                self.expect_op("(")
                e = self.expression()
                if self.accept_kw("FROM"):
                    start = self.expression()
                    length = None
                    if self.accept_kw("FOR"):
                        length = self.expression()
                    self.expect_op(")")
                    args = (e, start) + ((length,) if length else ())
                    return t.FunctionCall("substr", args)
                args = [e]
                while self.accept_op(","):
                    args.append(self.expression())
                self.expect_op(")")
                return t.FunctionCall("substr", tuple(args))
            if kw in ("IF",):
                self.next()
                self.expect_op("(")
                cond = self.expression()
                self.expect_op(",")
                then = self.expression()
                default = None
                if self.accept_op(","):
                    default = self.expression()
                self.expect_op(")")
                whens = ((cond, then),)
                return t.Case(None, whens, default)
            if kw == "POSITION" and self.peek(1).kind == "OP" and self.peek(1).text == "(":
                # POSITION(needle IN haystack) -> strpos(haystack, needle)
                self.next()
                self.expect_op("(")
                needle = self._additive()
                self.expect_kw("IN")
                haystack = self.expression()
                self.expect_op(")")
                return t.FunctionCall("strpos", (haystack, needle))
            if kw in _NONRESERVED:
                pass  # fall through to identifier handling
            else:
                raise SqlSyntaxError(f"unexpected keyword {tok.text!r}", tok.line, tok.col)
        # typed literal with a non-keyword type name: DECIMAL '12.34'
        if (
            self.peek().kind == "IDENT"
            and self.peek().upper == "DECIMAL"
            and self.peek(1).kind == "STRING"
        ):
            self.next()
            s = self.next().text.strip()
            return t.Literal(s, "decimal")
        # ARRAY[e1, e2, ...] constructor
        if (
            self.peek().kind == "IDENT"
            and self.peek().upper == "ARRAY"
            and self.peek(1).kind == "OP"
            and self.peek(1).text == "["
        ):
            self.next()
            self.expect_op("[")
            items: list[t.Node] = []
            if not self.at_op("]"):
                items.append(self.expression())
                while self.accept_op(","):
                    items.append(self.expression())
            self.expect_op("]")
            return t.ArrayLiteral(tuple(items))
        # identifier, qualified name, or function call
        if self.peek().kind in ("IDENT", "QIDENT") or (
            self.peek().kind == "KW" and self.peek().upper in _NONRESERVED
        ):
            name = self.qualified_name()
            if self.at_op("(") :
                return self._function_call(".".join(name))
            if len(name) == 1 and name[0].lower() in _NILADIC:
                # current_date / current_timestamp etc. take no parens
                return t.FunctionCall(name[0].lower(), ())
            return t.Identifier(name)
        raise SqlSyntaxError(f"unexpected token {tok.text!r}", tok.line, tok.col)

    def _case(self) -> t.Node:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expression()
        whens = []
        while self.accept_kw("WHEN"):
            cond = self.expression()
            self.expect_kw("THEN")
            result = self.expression()
            whens.append((cond, result))
        default = None
        if self.accept_kw("ELSE"):
            default = self.expression()
        self.expect_kw("END")
        return t.Case(operand, tuple(whens), default)

    def _function_call(self, name: str) -> t.Node:
        self.expect_op("(")
        distinct = False
        args: list[t.Node] = []
        if self.at_op("*"):
            self.next()
            self.expect_op(")")
            fc = t.FunctionCall(name.lower(), (t.Star(),))
            if self.at_kw("FILTER"):  # count(*) FILTER (WHERE ...)
                self.next()
                self.expect_op("(")
                self.expect_kw("WHERE")
                cond = self.expression()
                self.expect_op(")")
                fc = t.FunctionCall(fc.name, fc.args, fc.distinct, filter=cond)
            return self._maybe_over(fc)
        if not self.at_op(")"):
            if self.accept_kw("DISTINCT"):
                distinct = True
            else:
                self.accept_kw("ALL")
            args.append(self.expression())
            while self.accept_op(","):
                args.append(self.expression())
        self.expect_op(")")
        fc = t.FunctionCall(name.lower(), tuple(args), distinct=distinct)
        # FILTER (WHERE ...)
        if self.at_kw("FILTER"):
            self.next()
            self.expect_op("(")
            self.expect_kw("WHERE")
            cond = self.expression()
            self.expect_op(")")
            fc = t.FunctionCall(fc.name, fc.args, fc.distinct, filter=cond)
        return self._maybe_over(fc)

    def _maybe_over(self, fc: t.FunctionCall) -> t.Node:
        if not self.at_kw("OVER"):
            return fc
        self.next()
        self.expect_op("(")
        partition_by: list[t.Node] = []
        order_by: tuple[t.SortItem, ...] = ()
        frame = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self.expression())
            while self.accept_op(","):
                partition_by.append(self.expression())
        if self.at_kw("ORDER"):
            order_by = self._order_by()
        if self.at_kw("ROWS", "RANGE"):
            frame_type = self.next().upper
            bounds = []
            if self.accept_kw("BETWEEN"):
                bounds.append(self._frame_bound())
                self.expect_kw("AND")
                bounds.append(self._frame_bound())
            else:
                bounds.append(self._frame_bound())
                bounds.append("CURRENT ROW")
            frame = (frame_type, bounds[0], bounds[1])
        self.expect_op(")")
        return t.FunctionCall(
            fc.name, fc.args, fc.distinct,
            window=t.WindowSpec(tuple(partition_by), order_by, frame),
            filter=fc.filter,
        )

    def _frame_bound(self) -> str:
        if self.accept_kw("UNBOUNDED"):
            tok = self.next()
            return f"UNBOUNDED {tok.upper}"
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return "CURRENT ROW"
        n = self.next().text
        tok = self.next()
        return f"{n} {tok.upper}"

    def _type_text(self) -> str:
        parts = [self.next().text]
        if self.at_op("("):
            self.expect_op("(")
            parts.append("(")
            while not self.at_op(")"):
                parts.append(self.next().text)
            self.expect_op(")")
            parts.append(")")
        return "".join(parts)


# keywords that may appear as identifiers (column/table names, functions)
_NONRESERVED = {
    "YEAR", "MONTH", "DAY", "HOUR", "MINUTE", "SECOND", "DATE", "TIME",
    "TIMESTAMP", "IF", "FILTER", "SHOW", "TABLES", "SCHEMAS", "CATALOGS",
    "COLUMNS", "SESSION", "ANALYZE", "OVER", "PARTITION", "RANGE", "ROWS",
    "ROW", "FIRST", "LAST", "NEXT", "ONLY", "VALUES", "SETS", "OFFSET",
    "SUBSTRING", "CURRENT", "GROUPING", "POSITION", "PREPARE",
    "EXECUTE", "DEALLOCATE", "START", "TRANSACTION", "COMMIT", "ROLLBACK",
}

_NILADIC = {"current_date", "current_timestamp", "localtimestamp", "now"}
