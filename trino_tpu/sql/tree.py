"""SQL AST nodes.

Reference: ``core/trino-parser/src/main/java/io/trino/sql/tree/`` (197 node
classes). We keep a compact set covering the TPC-H/TPC-DS query surface plus
the utility statements the engine needs (EXPLAIN, SHOW, SET SESSION).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Node:
    pass


# --- expressions -----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Identifier(Node):
    parts: tuple[str, ...]  # possibly qualified: (table, column) or (column,)

    def __str__(self):
        return ".".join(self.parts)


@dataclasses.dataclass(frozen=True)
class Literal(Node):
    value: Any  # python value; None for NULL
    kind: str  # 'null'|'boolean'|'integer'|'decimal'|'double'|'string'|'date'|'timestamp'|'interval'


@dataclasses.dataclass(frozen=True)
class IntervalLiteral(Node):
    value: int
    unit: str  # 'year'|'month'|'day'|'hour'|'minute'|'second'
    sign: int = 1


@dataclasses.dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None  # t.* has qualifier 't'


@dataclasses.dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # '-' | '+' | 'NOT'
    operand: Node


@dataclasses.dataclass(frozen=True)
class BinaryOp(Node):
    op: str  # arithmetic: + - * / % || ; comparison: = <> < <= > >= ; logical: AND OR
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class IsNull(Node):
    operand: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Between(Node):
    value: Node
    low: Node
    high: Node
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class InList(Node):
    value: Node
    items: tuple[Node, ...]
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class QuantifiedComparison(Node):
    """value OP ANY|ALL (query) — reference: tree/QuantifiedComparisonExpression."""

    op: str = "="
    quantifier: str = "ANY"  # ANY | ALL (SOME == ANY)
    value: Optional[Node] = None
    query: Optional["Query"] = None


@dataclasses.dataclass(frozen=True)
class GroupingSets(Node):
    """GROUP BY GROUPING SETS / ROLLUP / CUBE — reference: tree/GroupingSets."""

    kind: str = "GROUPING SETS"  # GROUPING SETS | ROLLUP | CUBE
    sets: tuple[tuple[Node, ...], ...] = ()


@dataclasses.dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Like(Node):
    value: Node
    pattern: Node
    escape: Optional[Node] = None
    negated: bool = False


@dataclasses.dataclass(frozen=True)
class FunctionCall(Node):
    name: str
    args: tuple[Node, ...]
    distinct: bool = False
    window: Optional["WindowSpec"] = None
    filter: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class WindowSpec(Node):
    partition_by: tuple[Node, ...] = ()
    order_by: tuple["SortItem", ...] = ()
    frame: Optional[tuple[str, str, str]] = None  # (type, start, end)


@dataclasses.dataclass(frozen=True)
class Cast(Node):
    operand: Node
    target: str  # type text, parsed by types.parse_type
    safe: bool = False  # TRY_CAST


@dataclasses.dataclass(frozen=True)
class Extract(Node):
    field: str  # YEAR/MONTH/DAY/...
    operand: Node


@dataclasses.dataclass(frozen=True)
class Case(Node):
    operand: Optional[Node]  # simple CASE has operand; searched has None
    whens: tuple[tuple[Node, Node], ...]
    default: Optional[Node]


# --- relations -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Table(Node):
    name: tuple[str, ...]  # catalog.schema.table, any suffix length


@dataclasses.dataclass(frozen=True)
class AliasedRelation(Node):
    relation: Node
    alias: str
    column_aliases: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Join(Node):
    join_type: str  # INNER | LEFT | RIGHT | FULL | CROSS
    left: Node
    right: Node
    criteria: Optional[Node] = None  # ON expression
    using: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class Values(Node):
    rows: tuple[tuple[Node, ...], ...]


@dataclasses.dataclass(frozen=True)
class Unnest(Node):
    expressions: tuple[Node, ...]
    with_ordinality: bool = False


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Node):
    items: tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Subscript(Node):
    """``base[index]`` — ARRAY element, MAP value, or ROW field access."""

    base: Node
    index: Node


# --- query structure -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expression: Node  # or Star
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    expression: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = type default (Trino: last for asc)


@dataclasses.dataclass(frozen=True)
class QuerySpec(Node):
    select_items: tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Node] = None
    where: Optional[Node] = None
    group_by: tuple[Node, ...] = ()
    having: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class SetOperation(Node):
    op: str  # UNION | EXCEPT | INTERSECT
    distinct: bool  # True unless ALL
    left: Node  # QuerySpec | SetOperation
    right: Node


@dataclasses.dataclass(frozen=True)
class WithQuery(Node):
    name: str
    query: "Query"
    column_aliases: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Query(Node):
    body: Node  # QuerySpec | SetOperation | Values
    with_queries: tuple[WithQuery, ...] = ()
    order_by: tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


# --- statements ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Explain(Node):
    statement: Node
    analyze: bool = False


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str
    value: Node


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    schema: Optional[tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Node):
    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class CreateTableAsSelect(Node):
    name: tuple[str, ...] = ()
    query: Optional[Query] = None


@dataclasses.dataclass(frozen=True)
class InsertInto(Node):
    name: tuple[str, ...] = ()
    columns: tuple[str, ...] = ()
    query: Optional[Query] = None


@dataclasses.dataclass(frozen=True)
class CreateTable(Node):
    """CREATE TABLE name (col type, ...) — reference: tree/CreateTable."""

    name: tuple[str, ...] = ()
    columns: tuple[tuple[str, str], ...] = ()  # (name, type text)
    not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    name: tuple[str, ...] = ()
    where: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    name: str = ""
    statement: Optional[Node] = None
    sql: str = ""


@dataclasses.dataclass(frozen=True)
class Execute(Node):
    name: str = ""
    parameters: tuple[Node, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    name: str = ""


@dataclasses.dataclass(frozen=True)
class Parameter(Node):
    """A ? placeholder in a prepared statement."""

    index: int = 0


@dataclasses.dataclass(frozen=True)
class StartTransaction(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Commit(Node):
    pass


@dataclasses.dataclass(frozen=True)
class Rollback(Node):
    pass


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    name: tuple[str, ...] = ()
    if_exists: bool = False
