"""SQL type system mapped onto TPU-friendly storage dtypes.

Reference: ``core/trino-spi/src/main/java/io/trino/spi/type/`` (40+ types).
We cover the engine-relevant core: BOOLEAN, the integer ladder, REAL, DOUBLE,
DECIMAL(p,s), VARCHAR/CHAR, DATE, TIMESTAMP, plus UNKNOWN (the NULL type).

Storage design (TPU-first, not a port):
- Every type has a fixed-width device representation. Strings are
  dictionary-encoded int32 codes over a host-side dictionary (Trino's
  ``DictionaryBlock`` is an optimization; here it is the *primary* string
  representation since TPUs need fixed-width lanes).
- DECIMAL(p<=18,s) is an int64 scaled integer (exact arithmetic; reference
  semantics: ``spi/type/UnscaledDecimal128Arithmetic.java``). p>18 is
  unsupported in v1 (TPC-H/TPC-DS fit in 18 digits).
- DATE is int32 days since 1970-01-01; TIMESTAMP int64 microseconds.
"""

from __future__ import annotations

import dataclasses
from functools import total_ordering

import numpy as np


@dataclasses.dataclass(frozen=True)
class SqlType:
    """Base for all SQL types. Frozen + hashable so types are usable as keys."""

    name: str

    @property
    def storage_dtype(self) -> np.dtype:
        raise NotImplementedError

    def __str__(self) -> str:
        return self.name

    # display helpers -----------------------------------------------------
    def to_python(self, storage_value, dictionary=None):
        """Convert one storage scalar to a Python value for client output."""
        return storage_value


@dataclasses.dataclass(frozen=True)
class BooleanType(SqlType):
    name: str = "boolean"

    @property
    def storage_dtype(self):
        return np.dtype(np.bool_)

    def to_python(self, v, dictionary=None):
        return bool(v)


@dataclasses.dataclass(frozen=True)
class IntegerLikeType(SqlType):
    bits: int = 64

    @property
    def storage_dtype(self):
        return np.dtype({8: np.int8, 16: np.int16, 32: np.int32, 64: np.int64}[self.bits])

    def to_python(self, v, dictionary=None):
        return int(v)


@dataclasses.dataclass(frozen=True)
class RealType(SqlType):
    name: str = "real"

    @property
    def storage_dtype(self):
        return np.dtype(np.float32)

    def to_python(self, v, dictionary=None):
        return float(v)


@dataclasses.dataclass(frozen=True)
class DoubleType(SqlType):
    name: str = "double"

    @property
    def storage_dtype(self):
        return np.dtype(np.float64)

    def to_python(self, v, dictionary=None):
        return float(v)


@dataclasses.dataclass(frozen=True)
class DecimalType(SqlType):
    """DECIMAL(precision, scale) as a scaled integer.

    Storage: int64 for any precision whose *values* fit (narrow storage);
    columns whose values exceed int64 — SUM accumulations over big data —
    use *wide* storage: an (n, 2) int64 array of (hi, lo) two's-complement
    128-bit lanes (``trino_tpu.ops.decimal128``, reference semantics
    ``spi/type/UnscaledDecimal128Arithmetic.java``). ``p <= 38`` as in the
    reference; a column's representation is visible from its data shape.
    """

    precision: int = 18
    scale: int = 0
    name: str = ""

    def __post_init__(self):
        if self.precision > 38:
            raise NotImplementedError("DECIMAL precision > 38 is invalid")
        object.__setattr__(self, "name", f"decimal({self.precision},{self.scale})")

    @property
    def wide(self) -> bool:
        """True when values may exceed int64 (needs 128-bit lanes)."""
        return self.precision > 18

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    @property
    def unscale(self) -> int:
        return 10**self.scale

    def to_python(self, v, dictionary=None):
        from decimal import Decimal

        if np.ndim(v) == 1:  # wide storage scalar: (hi, lo) lanes
            from trino_tpu.ops.decimal128 import pair_to_int

            iv = pair_to_int(int(v[0]), int(v[1]))
        else:
            iv = int(v)
        return Decimal(iv) / (10**self.scale) if self.scale else Decimal(iv)


@dataclasses.dataclass(frozen=True)
class VarcharType(SqlType):
    """VARCHAR(n): dictionary-encoded int32 codes. n is advisory."""

    length: int | None = None
    name: str = ""

    def __post_init__(self):
        object.__setattr__(
            self, "name", "varchar" if self.length is None else f"varchar({self.length})"
        )

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        if dictionary is None:
            raise ValueError("varchar column without dictionary")
        return dictionary.decode(int(v))


@dataclasses.dataclass(frozen=True)
class CharType(SqlType):
    length: int = 1
    name: str = ""

    def __post_init__(self):
        object.__setattr__(self, "name", f"char({self.length})")

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        if dictionary is None:
            raise ValueError("char column without dictionary")
        return dictionary.decode(int(v))


@dataclasses.dataclass(frozen=True)
class DateType(SqlType):
    name: str = "date"

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        import datetime

        return (datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v))).isoformat()


@dataclasses.dataclass(frozen=True)
class TimestampType(SqlType):
    name: str = "timestamp"

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    def to_python(self, v, dictionary=None):
        import datetime

        return (
            datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(v))
        ).isoformat(sep=" ")


@dataclasses.dataclass(frozen=True)
class ArrayType(SqlType):
    """ARRAY(element). TPU-first storage mirrors varchar: int32 codes into
    a host-side pool of distinct array VALUES (python tuples). Equality,
    grouping and joining work on codes; cardinality/element_at become
    per-code lookup tables; UNNEST expands host-side at the (inherently
    row-count-changing) operator boundary.
    Reference: ``spi/block/ArrayBlock.java`` (offsets + values block)."""

    element: SqlType = None  # type: ignore[assignment]
    name: str = ""

    def __post_init__(self):
        object.__setattr__(self, "name", f"array({self.element})")

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        if dictionary is None:
            raise ValueError("array column without value pool")
        tup = dictionary.decode(int(v))
        if tup is None:
            return None
        return [
            None
            if e is None
            else (e if isinstance(e, str) else self.element.to_python(e, None))
            for e in tup
        ]


@dataclasses.dataclass(frozen=True)
class MapType(SqlType):
    """MAP(key, value). Pool-coded like ARRAY: int32 codes into a host
    pool of distinct map VALUES, each a tuple of (key, value) pairs in
    insertion order. Equality/grouping/joining work on codes.
    Reference: ``spi/block/MapBlock.java`` (offsets + key/value blocks)."""

    key: SqlType = None  # type: ignore[assignment]
    value: SqlType = None  # type: ignore[assignment]
    name: str = ""

    def __post_init__(self):
        object.__setattr__(self, "name", f"map({self.key}, {self.value})")

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        if dictionary is None:
            raise ValueError("map column without value pool")
        pairs = dictionary.decode(int(v))
        if pairs is None:
            return None
        out = {}
        for k, val in pairs:
            kk = k if isinstance(k, str) else self.key.to_python(k, None)
            vv = (
                None
                if val is None
                else (val if isinstance(val, str) else self.value.to_python(val, None))
            )
            out[kk] = vv
        return out


@dataclasses.dataclass(frozen=True)
class RowType(SqlType):
    """ROW(f0, f1, ...). Pool-coded: int32 codes into a host pool of
    distinct row VALUES (tuples of field storage scalars).
    Reference: ``spi/block/RowBlock.java`` (parallel field blocks)."""

    fields: tuple = ()  # tuple[(name or None, SqlType), ...]
    name: str = ""

    def __post_init__(self):
        inner = ", ".join(
            f"{n} {t}" if n else str(t) for n, t in self.fields
        )
        object.__setattr__(self, "name", f"row({inner})")

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def to_python(self, v, dictionary=None):
        if dictionary is None:
            raise ValueError("row column without value pool")
        tup = dictionary.decode(int(v))
        if tup is None:
            return None
        out = []
        for (fname, ft), e in zip(self.fields, tup):
            out.append(
                None
                if e is None
                else (e if isinstance(e, str) else ft.to_python(e, None))
            )
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class UnknownType(SqlType):
    """The type of a bare NULL literal (reference: ``spi/type/UnknownType``)."""

    name: str = "unknown"

    @property
    def storage_dtype(self):
        return np.dtype(np.bool_)


BOOLEAN = BooleanType()
TINYINT = IntegerLikeType("tinyint", 8)
SMALLINT = IntegerLikeType("smallint", 16)
INTEGER = IntegerLikeType("integer", 32)
BIGINT = IntegerLikeType("bigint", 64)
REAL = RealType()
DOUBLE = DoubleType()
DATE = DateType()
TIMESTAMP = TimestampType()
UNKNOWN = UnknownType()
VARCHAR = VarcharType()


def decimal(precision: int, scale: int) -> DecimalType:
    return DecimalType(precision=precision, scale=scale)


def varchar(length: int | None = None) -> VarcharType:
    return VarcharType(length=length)


def char(length: int) -> CharType:
    return CharType(length=length)


def is_integer(t: SqlType) -> bool:
    return isinstance(t, IntegerLikeType)


def is_numeric(t: SqlType) -> bool:
    return isinstance(t, (IntegerLikeType, RealType, DoubleType, DecimalType))


def is_string(t: SqlType) -> bool:
    return isinstance(t, (VarcharType, CharType))


def is_orderable(t: SqlType) -> bool:
    return is_numeric(t) or is_string(t) or isinstance(t, (DateType, TimestampType, BooleanType))


_INT_ORDER = {"tinyint": 0, "smallint": 1, "integer": 2, "bigint": 3}


def common_super_type(a: SqlType, b: SqlType) -> SqlType | None:
    """Implicit coercion lattice (reference: ``type/TypeCoercion.java``)."""
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    if is_integer(a) and is_integer(b):
        return a if _INT_ORDER[a.name] >= _INT_ORDER[b.name] else b
    # integer + decimal -> decimal wide enough to hold the integer
    if is_integer(a) and isinstance(b, DecimalType):
        return DecimalType(precision=18, scale=b.scale)
    if isinstance(a, DecimalType) and is_integer(b):
        return DecimalType(precision=18, scale=a.scale)
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        return DecimalType(precision=18, scale=scale)
    # anything numeric + double/real -> double
    numeric = (IntegerLikeType, DecimalType, RealType, DoubleType)
    if isinstance(a, numeric) and isinstance(b, numeric):
        if DOUBLE in (a, b) or (isinstance(a, RealType) or isinstance(b, RealType)):
            if isinstance(a, RealType) and isinstance(b, RealType):
                return REAL
            return DOUBLE
    if is_string(a) and is_string(b):
        return VARCHAR
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return TIMESTAMP
    if isinstance(a, TimestampType) and isinstance(b, DateType):
        return TIMESTAMP
    return None


def parse_type(text: str) -> SqlType:
    """Parse a type name as it appears in SQL (CAST target, DDL)."""
    t = text.strip().lower()
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "integer": INTEGER,
        "int": INTEGER,
        "bigint": BIGINT,
        "real": REAL,
        "double": DOUBLE,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "varchar": VARCHAR,
        "unknown": UNKNOWN,  # NULL-typed fields inside row(...) on the wire
    }
    if t in simple:
        return simple[t]
    if t.startswith("decimal"):
        inner = t[t.index("(") + 1 : t.index(")")]
        p, s = ([int(x) for x in inner.split(",")] + [0])[:2]
        return decimal(p, s)
    if t.startswith("varchar"):
        inner = t[t.index("(") + 1 : t.index(")")]
        return varchar(int(inner))
    if t.startswith("char"):
        inner = t[t.index("(") + 1 : t.index(")")]
        return char(int(inner))
    if t.startswith("array(") and t.endswith(")"):
        return ArrayType(element=parse_type(t[6:-1]))
    if t.startswith("map(") and t.endswith(")"):
        k, v = _split_top(t[4:-1])
        return MapType(key=parse_type(k), value=parse_type(v))
    if t.startswith("row(") and t.endswith(")"):
        fields = []
        for part in _split_all_top(t[4:-1]):
            part = part.strip()
            bits = part.split(" ", 1)
            if len(bits) == 2 and not bits[0].endswith(","):
                try:
                    fields.append((bits[0], parse_type(bits[1])))
                    continue
                except ValueError:
                    pass
            fields.append((None, parse_type(part)))
        return RowType(fields=tuple(fields))
    raise ValueError(f"cannot parse type: {text!r}")


def _split_all_top(s: str) -> list[str]:
    """Split on commas at paren depth 0."""
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    out.append(s[start:])
    return out


def _split_top(s: str) -> tuple[str, str]:
    parts = _split_all_top(s)
    if len(parts) != 2:
        raise ValueError(f"expected two type arguments in {s!r}")
    return parts[0], parts[1]
