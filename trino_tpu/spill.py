"""Spill-to-host: partitioned execution for memory-revocable operators.

Reference: ``core/trino-main/.../spiller/`` —
``GenericPartitioningSpiller.java`` (hash-partition oversized join/agg
state, process partitions sequentially) and the four revocable operators
(HashBuilderOperator, HashAggregationOperator, OrderByOperator,
WindowOperator). Our "disk" is host RAM: partitions are compacted numpy
arrays (device -> host), processed one at a time on device, results
concatenated host-side. HBM holds only one partition's working set at a
time — the TPU analog of grouped/bucketed execution
(``execution/Lifespan.java:26``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from trino_tpu.columnar import Batch, Column


def partition_assignment(
    hashes: np.ndarray, sel: np.ndarray, n_partitions: int
) -> np.ndarray:
    """partition id per row (-1 for unselected rows)."""
    part = (hashes.astype(np.uint64) % np.uint64(n_partitions)).astype(np.int64)
    return np.where(sel, part, -1)


def slice_rows(batch: Batch, rows: np.ndarray) -> Batch:
    """Physically gather ``rows`` (host-side compaction) into a new Batch."""
    cols = []
    for c in batch.columns:
        data, valid = c.to_numpy()
        cols.append(Column(c.type, data[rows], valid[rows], c.dictionary))
    return Batch(cols, len(rows))


def pad_to_one_unselected(batch: Batch) -> Batch:
    """A 1-row batch with nothing selected (kernels reject 0-row arrays)."""
    cols = []
    for c in batch.columns:
        data, _valid = c.to_numpy()
        cols.append(
            Column(
                c.type,
                np.zeros(1, dtype=data.dtype),
                np.zeros(1, dtype=np.bool_),
                c.dictionary,
            )
        )
    return Batch(cols, 1, np.zeros(1, dtype=np.bool_))


def partitioned_run(
    batches: Sequence[tuple[Batch, np.ndarray]],
    n_partitions: int,
    run: Callable[[Sequence[Batch], int], Optional[Batch]],
) -> list[Batch]:
    """Split each (batch, hash) input into hash partitions; call ``run``
    once per partition with the compacted per-input sub-batches.

    Rows whose hash partition differs never join/aggregate together, so
    per-partition processing is exact for equi-joins and group-bys (the
    GenericPartitioningSpiller guarantee).
    """
    assignments = []
    for batch, hashes in batches:
        sel = np.asarray(batch.selection_mask())
        assignments.append(partition_assignment(np.asarray(hashes), sel, n_partitions))
    out: list[Batch] = []
    for p in range(n_partitions):
        subs = []
        for (batch, _), assign in zip(batches, assignments):
            rows = np.nonzero(assign == p)[0]
            subs.append(slice_rows(batch, rows))
        res = run(subs, p)
        if res is not None and res.num_rows > 0:
            out.append(res)
    return out
