"""Semantic analysis + logical planning: AST -> typed logical plan.

Reference: ``core/trino-main/src/main/java/io/trino/sql/analyzer/``
(``Analyzer.java:44``, ``StatementAnalyzer.java:284``,
``ExpressionAnalyzer.java``) and ``sql/planner/QueryPlanner.java:139`` /
``RelationPlanner.java``. Trino splits analysis (side-tables) from planning;
we fuse them: one pass resolves names/types and emits plan nodes whose
expressions are RowExpr trees over Symbols.

Typing rules implemented (Trino semantics, DECIMAL capped at precision 18):
- integer literal -> bigint; '1.2' -> decimal(2,1); string -> varchar
- decimal add/sub: s=max(s1,s2); mul: s=s1+s2; div: s=max(s1,s2) (Trino
  keeps max scale and rounds half-up); anything with double -> double
- sum(decimal(p,s)) -> decimal(18,s)  [Trino: (38,s)]
- avg(decimal(p,s)) -> decimal(p,s); avg(int) -> double; count -> bigint
- date +/- interval day -> date; +/- interval month/year -> calendar add
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from trino_tpu import types as T
from trino_tpu.compiler import days_from_civil
from trino_tpu.config import Session
from trino_tpu.connectors.api import CatalogManager
from trino_tpu.ir import (
    Call,
    Constant,
    RowExpr,
    SpecialForm,
    Variable,
    call,
    const,
    referenced_variables,
    special,
    variable,
)
from trino_tpu.planner import plan as P
from trino_tpu.sql import tree as t


class SemanticError(Exception):
    pass


@dataclasses.dataclass
class Field:
    name: Optional[str]  # None for anonymous expressions
    qualifier: Optional[str]
    symbol: P.Symbol


class Scope:
    def __init__(self, fields: list[Field], parent: Optional["Scope"] = None):
        self.fields = fields
        self.parent = parent

    def resolve(self, parts: tuple[str, ...]) -> P.Symbol:
        name = parts[-1].lower()
        qualifier = parts[-2].lower() if len(parts) > 1 else None
        matches = [
            f
            for f in self.fields
            if f.name == name and (qualifier is None or f.qualifier == qualifier)
        ]
        if len(matches) == 1:
            return matches[0].symbol
        if len(matches) > 1:
            raise SemanticError(f"ambiguous column: {'.'.join(parts)}")
        if len(parts) == 1:
            # normalized identifiers carry unique symbol names directly
            sym_matches = [f for f in self.fields if f.symbol.name == parts[0]]
            if len(sym_matches) == 1:
                return sym_matches[0].symbol
        if self.parent is not None:
            return self.parent.resolve(parts)
        raise SemanticError(f"column not found: {'.'.join(parts)}")

    def try_resolve(self, parts: tuple[str, ...]) -> Optional[P.Symbol]:
        try:
            return self.resolve(parts)
        except SemanticError:
            return None


@dataclasses.dataclass
class RelationPlan:
    node: P.PlanNode
    scope: Scope


class Analyzer:
    def __init__(
        self,
        catalogs: CatalogManager,
        session: Session,
        access_control=None,
    ):
        self.catalogs = catalogs
        self.session = session
        self.access_control = access_control
        self.ctes: dict[str, RelationPlan] = {}

    # ==== entry =========================================================
    def plan_statement(self, stmt: t.Node) -> P.PlanNode:
        if isinstance(stmt, t.Query):
            rp, names = self.plan_query(stmt)
            return P.Output(rp.node, names, rp.node.output_symbols)
        raise SemanticError(f"unsupported statement: {type(stmt).__name__}")

    # ==== queries =======================================================
    def plan_query(
        self, q: t.Query, outer: Optional[Scope] = None
    ) -> tuple[RelationPlan, list[str]]:
        """``outer`` is the enclosing query's scope: set only for expression
        subqueries, enabling correlated column references (reference:
        StatementAnalyzer's Scope.parent chain)."""
        saved_ctes = dict(self.ctes)
        try:
            for wq in q.with_queries:
                rp, names = self.plan_query(wq.query)
                if wq.column_aliases:
                    names = list(wq.column_aliases)
                fields = [
                    Field(n.lower(), wq.name.lower(), s)
                    for n, s in zip(names, rp.node.output_symbols)
                ]
                self.ctes[wq.name.lower()] = RelationPlan(rp.node, Scope(fields))
            rp, names = self._plan_query_body(
                q.body, q.order_by, q.limit, q.offset, outer
            )
            return rp, names
        finally:
            self.ctes = saved_ctes

    def _plan_query_body(
        self,
        body: t.Node,
        order_by: tuple[t.SortItem, ...],
        limit: Optional[int],
        offset: int,
        outer: Optional[Scope] = None,
    ) -> tuple[RelationPlan, list[str]]:
        if isinstance(body, t.QuerySpec):
            return self._plan_query_spec(body, order_by, limit, offset, outer)
        if isinstance(body, t.SetOperation):
            rp, names = self._plan_set_operation(body)
            rp = self._apply_order_limit(rp, names, order_by, limit, offset)
            return rp, names
        if isinstance(body, t.Values):
            rp, names = self._plan_values(body)
            rp = self._apply_order_limit(rp, names, order_by, limit, offset)
            return rp, names
        if isinstance(body, t.Query):
            return self.plan_query(body)
        raise SemanticError(f"unsupported query body: {type(body).__name__}")

    def _apply_order_limit(self, rp, names, order_by, limit, offset):
        if order_by:
            scope = Scope(
                [Field(n.lower(), None, s) for n, s in zip(names, rp.node.output_symbols)]
            )
            orderings = []
            for si in order_by:
                e = self._rewrite(si.expression, scope)
                if not isinstance(e, Variable):
                    raise SemanticError("ORDER BY over set op must reference columns")
                sym = P.Symbol(e.name, e.type)
                orderings.append(self._ordering(sym, si))
            node = P.Sort(rp.node, orderings)
            rp = RelationPlan(node, rp.scope)
        if limit is not None or offset:
            rp = RelationPlan(P.Limit(rp.node, limit, offset), rp.scope)
        return rp

    def _ordering(self, sym: P.Symbol, si: t.SortItem) -> P.Ordering:
        nulls_first = si.nulls_first
        if nulls_first is None:
            nulls_first = not si.ascending  # Trino: NULLS LAST for ASC, FIRST for DESC
        return P.Ordering(sym, si.ascending, nulls_first)

    def _plan_values(self, v: t.Values) -> tuple[RelationPlan, list[str]]:
        rows = []
        col_types: list[T.SqlType] = []
        for row in v.rows:
            vals = []
            for j, e in enumerate(row):
                ex = self._rewrite(e, Scope([]))
                ex = _fold(ex)
                if not isinstance(ex, Constant):
                    raise SemanticError("VALUES entries must be constant")
                vals.append(ex)
                if j >= len(col_types):
                    col_types.append(ex.type)
                else:
                    ct = T.common_super_type(col_types[j], ex.type)
                    if ct is None:
                        raise SemanticError("incompatible VALUES column types")
                    col_types[j] = ct
            rows.append(vals)
        symbols = [
            P.Symbol(P.fresh_name(f"col{j}"), ct) for j, ct in enumerate(col_types)
        ]
        storage_rows = []
        for row in rows:
            srow = []
            for cexpr, ct in zip(row, col_types):
                srow.append(_coerce_constant_value(cexpr, ct))
            storage_rows.append(srow)
        names = [f"_col{j}" for j in range(len(col_types))]
        node = P.Values(symbols, storage_rows)
        fields = [Field(None, None, s) for s in symbols]
        return RelationPlan(node, Scope(fields)), names

    def _plan_set_operation(self, op: t.SetOperation) -> tuple[RelationPlan, list[str]]:
        left_rp, left_names = self._plan_query_body(op.left, (), None, 0)
        right_rp, _ = self._plan_query_body(op.right, (), None, 0)
        lsyms = left_rp.node.output_symbols
        rsyms = right_rp.node.output_symbols
        if len(lsyms) != len(rsyms):
            raise SemanticError("set operation column count mismatch")
        out_syms = []
        for a, b in zip(lsyms, rsyms):
            ct = T.common_super_type(a.type, b.type)
            if ct is None:
                raise SemanticError(f"set operation type mismatch: {a.type} vs {b.type}")
            out_syms.append(P.Symbol(P.fresh_name(a.name), ct))
        node = P.SetOp(op.op, op.distinct, [left_rp.node, right_rp.node], out_syms)
        fields = [
            Field(n.lower(), None, s) for n, s in zip(left_names, out_syms)
        ]
        return RelationPlan(node, Scope(fields)), left_names

    # ==== SELECT core ===================================================
    def _plan_query_spec(
        self,
        spec: t.QuerySpec,
        order_by: tuple[t.SortItem, ...],
        limit: Optional[int],
        offset: int,
        outer: Optional[Scope] = None,
    ) -> tuple[RelationPlan, list[str]]:
        # FROM
        if spec.from_ is not None:
            rp = self._plan_relation(spec.from_)
        else:
            sym = P.Symbol(P.fresh_name("dual"), T.BIGINT)
            rp = RelationPlan(P.Values([sym], [[0]]), Scope([]))
        if outer is not None:
            # chain to the enclosing scope: unresolved names become
            # correlated references to outer symbols
            rp = RelationPlan(rp.node, Scope(rp.scope.fields, outer))
        # WHERE
        if spec.where is not None:
            pred, rp = self._rewrite_with_subqueries(spec.where, rp)
            pred = _fold(pred)
            rp = RelationPlan(P.Filter(rp.node, pred), rp.scope)

        # expand stars, gather select expressions
        select_entries: list[tuple[t.Node, Optional[str]]] = []
        for item in spec.select_items:
            if isinstance(item.expression, t.Star):
                q = item.expression.qualifier
                for f in rp.scope.fields:
                    if f.name is None:
                        continue
                    if q is not None and f.qualifier != q.lower():
                        continue
                    select_entries.append(
                        (t.Identifier((f.qualifier, f.name) if f.qualifier else (f.name,)), f.name)
                    )
            else:
                alias = item.alias
                if alias is None and isinstance(item.expression, t.Identifier):
                    alias = item.expression.parts[-1]
                select_entries.append(
                    (self._normalize(item.expression, rp.scope), alias)
                )

        has_aggs = any(
            _contains_aggregate(e) for e, _ in select_entries
        ) or (spec.having is not None and _contains_aggregate(spec.having)) or bool(
            spec.group_by
        )

        if has_aggs:
            return self._plan_aggregation(
                spec, rp, select_entries, order_by, limit, offset
            )

        # window functions (no aggregation): plan Window nodes over the input
        window_calls: list[t.FunctionCall] = []
        for e, _ in select_entries:
            _collect_windows(e, window_calls)
        for si in order_by:
            _collect_windows(self._normalize(si.expression, rp.scope), window_calls)
        win_repl: dict[t.Node, P.Symbol] = {}
        if window_calls:
            wnode, win_repl = self._plan_windows(
                rp.node, window_calls, lambda ast: self._rewrite(ast, rp.scope)
            )
            rp = RelationPlan(wnode, rp.scope)

        # plain projection
        out_syms: list[P.Symbol] = []
        assignments: list[tuple[P.Symbol, RowExpr]] = []
        names: list[str] = []
        for e_ast, alias in select_entries:
            ex, rp = self._rewrite_with_subqueries(e_ast, rp, win_repl or None)
            ex = _fold(ex)
            name = (alias or "_col").lower()
            sym = P.Symbol(P.fresh_name(name), ex.type)
            assignments.append((sym, ex))
            out_syms.append(sym)
            names.append(alias.lower() if alias else f"_col{len(names)}")

        # ORDER BY may reference hidden input columns: keep them through sort
        sort_items = []
        extra_syms: list[P.Symbol] = []
        if order_by:
            select_scope = Scope(
                [Field(n, None, s) for (n, s) in zip(names, out_syms)],
            )
            for si in order_by:
                # alias resolution first (raw form), then structural match
                # against normalized select expressions
                sym = self._resolve_sort_symbol(
                    si, select_scope, rp.scope, select_entries, out_syms
                )
                if sym is None:
                    si = dataclasses.replace(
                        si, expression=self._normalize(si.expression, rp.scope)
                    )
                    sym = self._resolve_sort_symbol(
                        si, select_scope, rp.scope, select_entries, out_syms
                    )
                if sym is None:
                    ex = self._rewrite(
                        si.expression, rp.scope, replacements=win_repl or None
                    )
                    ex = _fold(ex)
                    sym = P.Symbol(P.fresh_name("sortkey"), ex.type)
                    assignments.append((sym, ex))
                    extra_syms.append(sym)
                sort_items.append(self._ordering(sym, si))

        node: P.PlanNode = P.Project(rp.node, assignments)
        if spec.distinct:
            if extra_syms:
                raise SemanticError(
                    "ORDER BY expression must appear in select list with DISTINCT"
                )
            node = P.Distinct(node)
        if sort_items:
            if limit is not None and offset == 0:
                node = P.TopN(node, limit, sort_items)
                limit = None
            else:
                node = P.Sort(node, sort_items)
        if extra_syms:
            node = P.Project(
                node, [(s, variable(s.name, s.type)) for s in out_syms]
            )
        if limit is not None or offset:
            node = P.Limit(node, limit, offset)
        fields = [Field(n, None, s) for n, s in zip(names, out_syms)]
        return RelationPlan(node, Scope(fields)), names

    def _resolve_sort_symbol(
        self, si, select_scope, input_scope, select_entries, out_syms
    ) -> Optional[P.Symbol]:
        e = si.expression
        if isinstance(e, t.Literal) and e.kind == "integer":
            idx = int(e.value) - 1
            if not (0 <= idx < len(out_syms)):
                raise SemanticError(f"ORDER BY ordinal {e.value} out of range")
            return out_syms[idx]
        if isinstance(e, t.Identifier):
            sym = select_scope.try_resolve(e.parts)
            if sym is not None:
                return sym
        # structural match against select expressions
        for (se, _), sym in zip(select_entries, out_syms):
            if se == e:
                return sym
        return None

    # ==== aggregation ===================================================
    def _plan_aggregation(
        self, spec, rp, select_entries, order_by, limit, offset
    ) -> tuple[RelationPlan, list[str]]:
        input_scope = rp.scope
        # resolve group keys (ordinals or expressions), normalized for
        # structural matching against (already-normalized) select entries
        # GROUPING SETS / ROLLUP / CUBE: the cross product of all grouping
        # elements' sets (SQL semantics); plain expressions are singleton
        # elements. grouping_sets is None for ordinary GROUP BY.
        def resolve_one(g: t.Node) -> t.Node:
            if isinstance(g, t.Literal) and g.kind == "integer":
                idx = int(g.value) - 1
                if not (0 <= idx < len(select_entries)):
                    raise SemanticError(f"GROUP BY ordinal {g.value} out of range")
                return select_entries[idx][0]
            return self._normalize(g, input_scope)

        grouping_sets: Optional[list[list[t.Node]]] = None
        if any(isinstance(g, t.GroupingSets) for g in spec.group_by):
            combos: list[list[t.Node]] = [[]]
            for g in spec.group_by:
                if isinstance(g, t.GroupingSets):
                    combos = [
                        prefix + [resolve_one(x) for x in s]
                        for prefix in combos
                        for s in g.sets
                    ]
                else:
                    resolved = resolve_one(g)
                    combos = [prefix + [resolved] for prefix in combos]
            grouping_sets = combos
            group_asts = []
            for s in combos:
                for x in s:
                    if x not in group_asts:
                        group_asts.append(x)
        else:
            group_asts = [resolve_one(g) for g in spec.group_by]

        having_ast = (
            self._normalize(spec.having, input_scope)
            if spec.having is not None
            else None
        )
        # keep raw sort expressions: ORDER BY <alias> must resolve against
        # the SELECT outputs even when an input column shares the name
        # (normalization would rewrite it to the input symbol)
        raw_order_by = order_by
        order_by = tuple(
            dataclasses.replace(
                si, expression=self._normalize(si.expression, input_scope)
            )
            for si in order_by
        )

        # collect aggregate calls from select + having + order_by
        agg_asts: list[t.FunctionCall] = []
        for e, _ in select_entries:
            _collect_aggregates(e, agg_asts)
        if having_ast is not None:
            _collect_aggregates(having_ast, agg_asts)
        for si in order_by:
            _collect_aggregates(si.expression, agg_asts)

        # pre-projection: group key exprs + agg argument exprs
        pre_assignments: list[tuple[P.Symbol, RowExpr]] = []
        key_symbols: list[P.Symbol] = []
        key_map: dict[t.Node, P.Symbol] = {}
        for g_ast in group_asts:
            if g_ast in key_map:
                continue
            ex = self._rewrite(g_ast, input_scope)
            ex = _fold(ex)
            sym = P.Symbol(P.fresh_name("gk"), ex.type)
            pre_assignments.append((sym, ex))
            key_symbols.append(sym)
            key_map[g_ast] = sym

        aggs: list[tuple[P.Symbol, P.AggFunction]] = []
        agg_map: dict[t.Node, P.Symbol] = {}
        # derived aggregates (stddev/variance/bool_and/...) compose simple
        # aggregates plus a post-projection expression (reference: the
        # input/combine/output decomposition of AccumulatorCompiler states)
        derived_exprs: list[tuple[P.Symbol, RowExpr]] = []

        def add_agg(kind, arg_expr, result_type, distinct=False, filt=None):
            sym_in = None
            if arg_expr is not None:
                sym_in = P.Symbol(P.fresh_name("aggarg"), arg_expr.type)
                pre_assignments.append((sym_in, arg_expr))
            out = P.Symbol(P.fresh_name(kind), result_type)
            aggs.append(
                (out, P.AggFunction(
                    kind,
                    variable(sym_in.name, sym_in.type) if sym_in else None,
                    result_type, distinct, filt,
                ))
            )
            return out

        for fc in agg_asts:
            if fc in agg_map:
                continue
            kind = fc.name
            if kind not in AGGREGATE_NAMES:
                raise SemanticError(f"unsupported aggregate: {kind}")
            # FILTER clause applies to every decomposed sub-aggregate
            # (the plain sum/count/avg/min/max path handles fc.filter itself)
            fc_filter = None
            if fc.filter is not None and (
                kind in _DERIVED_AGGS
                or kind in ("approx_distinct", "arbitrary", "any_value")
            ):
                f_ex = _fold(self._rewrite(fc.filter, input_scope))
                sym_f = P.Symbol(P.fresh_name("aggfilter"), T.BOOLEAN)
                pre_assignments.append((sym_f, f_ex))
                fc_filter = variable(sym_f.name, T.BOOLEAN)
            if kind in _DERIVED_AGGS:
                if fc.distinct:
                    raise SemanticError(f"{kind}(DISTINCT ...) is not supported")
                derived = self._plan_derived_aggregate(
                    fc, input_scope, add_agg, fc_filter
                )
                dsym = P.Symbol(P.fresh_name(kind), derived.type)
                derived_exprs.append((dsym, derived))
                agg_map[fc] = dsym
                continue
            if kind == "count_if":
                cond = _fold(self._rewrite(fc.args[0], input_scope))
                if fc.filter is not None:
                    # count_if(x) FILTER (WHERE f) counts rows where both hold
                    f_ex = _fold(self._rewrite(fc.filter, input_scope))
                    cond = special("and", T.BOOLEAN, cond, f_ex)
                sym_f = P.Symbol(P.fresh_name("aggfilter"), T.BOOLEAN)
                pre_assignments.append((sym_f, cond))
                out_sym = P.Symbol(P.fresh_name("count_if"), T.BIGINT)
                aggs.append(
                    (out_sym, P.AggFunction(
                        "count_star", None, T.BIGINT, False,
                        variable(sym_f.name, T.BOOLEAN),
                    ))
                )
                agg_map[fc] = out_sym
                continue
            if kind == "approx_distinct":
                # exact distinct count (HLL sketch: future work; documented)
                arg = _fold(self._rewrite(fc.args[0], input_scope))
                agg_map[fc] = add_agg(
                    "count", arg, T.BIGINT, distinct=True, filt=fc_filter
                )
                continue
            if kind in ("arbitrary", "any_value"):
                if fc.distinct:
                    raise SemanticError(f"{kind}(DISTINCT ...) is not supported")
                arg = _fold(self._rewrite(fc.args[0], input_scope))
                agg_map[fc] = add_agg("min", arg, arg.type, filt=fc_filter)
                continue
            if kind == "array_agg":
                if fc.distinct:
                    raise SemanticError("array_agg(DISTINCT ...) is not supported")
                arg = _fold(self._rewrite(fc.args[0], input_scope))
                agg_map[fc] = add_agg(
                    "array_agg", arg, T.ArrayType(element=arg.type),
                    filt=fc_filter,
                )
                continue
            if kind == "count" and len(fc.args) == 1 and isinstance(fc.args[0], t.Star):
                arg_expr = None
                result_type: T.SqlType = T.BIGINT
                kind = "count_star"
                arg_sym_expr = None
            else:
                arg = self._rewrite(fc.args[0], input_scope)
                arg = _fold(arg)
                if kind == "count":
                    result_type = T.BIGINT
                elif kind == "sum":
                    if isinstance(arg.type, T.DecimalType):
                        # reference: sum(decimal(p,s)) -> decimal(38,s)
                        # (DecimalSumAggregation); values beyond int64 use
                        # 128-bit limb accumulation (ops/decimal128.py)
                        result_type = T.decimal(38, arg.type.scale)
                    elif T.is_integer(arg.type):
                        result_type = T.BIGINT
                    else:
                        result_type = arg.type
                elif kind == "avg":
                    if isinstance(arg.type, T.DecimalType):
                        result_type = arg.type
                    else:
                        result_type = T.DOUBLE
                else:  # min/max
                    result_type = arg.type
                sym_in = P.Symbol(P.fresh_name("aggarg"), arg.type)
                pre_assignments.append((sym_in, arg))
                arg_sym_expr = variable(sym_in.name, sym_in.type)
            filt = None
            if fc.filter is not None:
                f_ex = self._rewrite(fc.filter, input_scope)
                sym_f = P.Symbol(P.fresh_name("aggfilter"), T.BOOLEAN)
                pre_assignments.append((sym_f, _fold(f_ex)))
                filt = variable(sym_f.name, T.BOOLEAN)
            out_sym = P.Symbol(P.fresh_name(fc.name), result_type)
            aggs.append(
                (out_sym, P.AggFunction(kind, arg_sym_expr, result_type, fc.distinct, filt))
            )
            agg_map[fc] = out_sym

        # count(*)-only aggregations have no inputs to project; feed the
        # source directly (a zero-column projection would lose row counts)
        pre_project = (
            P.Project(rp.node, pre_assignments) if pre_assignments else rp.node
        )
        if grouping_sets is not None:
            # GroupIdNode: replicate rows per set, null absent keys, add gid
            groups = [
                [key_map[ast] for ast in s] for s in grouping_sets
            ]
            gid = P.Symbol(P.fresh_name("groupid"), T.BIGINT)
            pre_project = P.GroupId(pre_project, groups, list(key_symbols), gid)
            agg_keys = key_symbols + [gid]
        else:
            agg_keys = key_symbols
        agg_node = P.Aggregate(pre_project, agg_keys, aggs, step="single")
        if derived_exprs:
            passthrough = [
                (s, variable(s.name, s.type)) for s in agg_node.output_symbols
            ]
            agg_node = P.Project(agg_node, passthrough + derived_exprs)

        # post-agg scope: group-by ASTs and agg ASTs -> symbols
        post_replacements: dict[t.Node, P.Symbol] = {}
        post_replacements.update(key_map)
        post_replacements.update(agg_map)

        def rewrite_post(e_ast: t.Node) -> RowExpr:
            return self._rewrite(
                e_ast, Scope([]), replacements=post_replacements
            )

        node: P.PlanNode = agg_node
        if having_ast is not None:
            # HAVING may contain (uncorrelated) subqueries — TPC-H Q11
            rp_h = RelationPlan(node, Scope([]))
            pred, rp_h = self._rewrite_with_subqueries(
                having_ast, rp_h, post_replacements or None
            )
            node = P.Filter(rp_h.node, _fold(pred))

        # windows over aggregation results (rank() OVER (ORDER BY sum(x)))
        window_calls: list[t.FunctionCall] = []
        for e, _ in select_entries:
            _collect_windows(e, window_calls)
        for si in order_by:
            _collect_windows(si.expression, window_calls)
        if window_calls:
            node, win_repl = self._plan_windows(node, window_calls, rewrite_post)
            post_replacements.update(win_repl)

        out_syms: list[P.Symbol] = []
        assignments = []
        names = []
        # select entries may contain (uncorrelated) subqueries: join them
        # onto the post-aggregation relation
        rp_post = RelationPlan(node, Scope([]))
        for e_ast, alias in select_entries:
            ex, rp_post = self._rewrite_with_subqueries(
                e_ast, rp_post, post_replacements or None
            )
            ex = _fold(ex)
            name = (alias or "_col").lower()
            sym = P.Symbol(P.fresh_name(name), ex.type)
            assignments.append((sym, ex))
            out_syms.append(sym)
            names.append(alias.lower() if alias else f"_col{len(names)}")
        node = rp_post.node
        sort_items = []
        extra_syms: list[P.Symbol] = []
        if order_by:
            select_scope = Scope([Field(n, None, s) for n, s in zip(names, out_syms)])
            for raw_si, si in zip(raw_order_by, order_by):
                # alias/ordinal resolution uses the RAW form (the output
                # alias wins over a same-named input column, SQL semantics)
                sym = self._resolve_sort_symbol(
                    raw_si, select_scope, None, select_entries, out_syms
                )
                if sym is None:
                    sym = self._resolve_sort_symbol(
                        si, select_scope, None, select_entries, out_syms
                    )
                if sym is None:
                    ex = _fold(rewrite_post(si.expression))
                    sym = P.Symbol(P.fresh_name("sortkey"), ex.type)
                    assignments.append((sym, ex))
                    extra_syms.append(sym)
                sort_items.append(self._ordering(sym, si))
        node = P.Project(node, assignments)
        if spec.distinct:
            node = P.Distinct(node)
        if sort_items:
            if limit is not None and offset == 0:
                node = P.TopN(node, limit, sort_items)
                limit = None
            else:
                node = P.Sort(node, sort_items)
        if extra_syms:
            node = P.Project(node, [(s, variable(s.name, s.type)) for s in out_syms])
        if limit is not None or offset:
            node = P.Limit(node, limit, offset)
        fields = [Field(n, None, s) for n, s in zip(names, out_syms)]
        return RelationPlan(node, Scope(fields)), names

    # ==== relations =====================================================
    def _plan_relation(self, rel: t.Node) -> RelationPlan:
        if isinstance(rel, t.Table):
            return self._plan_table(rel)
        if isinstance(rel, t.AliasedRelation):
            inner = self._plan_relation(rel.relation)
            alias = rel.alias.lower()
            fields = []
            for i, f in enumerate(inner.scope.fields):
                name = (
                    rel.column_aliases[i].lower()
                    if i < len(rel.column_aliases)
                    else f.name
                )
                fields.append(Field(name, alias, f.symbol))
            return RelationPlan(inner.node, Scope(fields))
        if isinstance(rel, t.SubqueryRelation):
            rp, names = self.plan_query(rel.query)
            fields = [
                Field(n.lower(), None, s)
                for n, s in zip(names, rp.node.output_symbols)
            ]
            return RelationPlan(rp.node, Scope(fields))
        if isinstance(rel, t.Join):
            return self._plan_join(rel)
        if isinstance(rel, t.Unnest):
            # bare FROM UNNEST(array): expand over a one-row dual
            dual = P.Values([P.Symbol(P.fresh_name("dual"), T.BIGINT)], [[0]])
            return self._plan_unnest(
                RelationPlan(dual, Scope([])), rel, None, ()
            )
        raise SemanticError(f"unsupported relation: {type(rel).__name__}")

    def _plan_unnest(
        self,
        left: RelationPlan,
        rel: t.Unnest,
        alias: Optional[str],
        col_aliases: tuple[str, ...],
    ) -> RelationPlan:
        """Plan UNNEST (reference: UnnestOperator.java:39; RelationPlanner
        handles CROSS JOIN UNNEST laterally)."""
        array_exprs = []
        element_symbols = []
        for i, e_ast in enumerate(rel.expressions):
            ex = _fold(self._rewrite(e_ast, left.scope))
            if not isinstance(ex.type, T.ArrayType):
                raise SemanticError("UNNEST argument must be an ARRAY")
            array_exprs.append(ex)
            name = (
                col_aliases[i].lower()
                if i < len(col_aliases)
                else P.fresh_name("unnest")
            )
            element_symbols.append(
                P.Symbol(P.fresh_name(name), ex.type.element)
            )
        ordinality = None
        if rel.with_ordinality:
            oname = (
                col_aliases[len(rel.expressions)].lower()
                if len(col_aliases) > len(rel.expressions)
                else "ordinality"
            )
            ordinality = P.Symbol(P.fresh_name(oname), T.BIGINT)
        node = P.Unnest(left.node, array_exprs, element_symbols, ordinality)
        fields = list(left.scope.fields)
        for i, s in enumerate(element_symbols):
            fname = (
                col_aliases[i].lower()
                if i < len(col_aliases)
                else (alias if len(element_symbols) == 1 and alias else None)
            )
            fields.append(Field(fname, alias, s))
        if ordinality is not None:
            oname = (
                col_aliases[len(rel.expressions)].lower()
                if len(col_aliases) > len(rel.expressions)
                else "ordinality"
            )
            fields.append(Field(oname, alias, ordinality))
        return RelationPlan(node, Scope(fields))

    def _plan_table(self, rel: t.Table) -> RelationPlan:
        parts = tuple(p.lower() for p in rel.name)
        if len(parts) == 1 and parts[0] in self.ctes:
            cte = self.ctes[parts[0]]
            # re-instantiate per reference: sharing one plan (and its
            # symbols) across references turns cross-reference predicates
            # like t1.k = t2.k into tautologies over a single symbol
            node, mapping = P.instantiate(cte.node)
            fields = [
                dataclasses.replace(
                    f, symbol=mapping.get(f.symbol.name, f.symbol)
                )
                for f in cte.scope.fields
            ]
            return RelationPlan(node, Scope(fields))
        if len(parts) == 3:
            catalog, schema, table = parts
        elif len(parts) == 2:
            catalog = self.session.catalog
            schema, table = parts
        elif len(parts) == 1:
            catalog = self.session.catalog
            schema = self.session.schema
            table = parts[0]
        else:
            raise SemanticError(f"invalid table name: {'.'.join(parts)}")
        if catalog is None or schema is None:
            raise SemanticError("no default catalog/schema set")
        connector = self.catalogs.get(catalog)
        ts = connector.get_table(schema, table)
        if ts is None:
            raise SemanticError(f"table not found: {catalog}.{schema}.{table}")
        if self.access_control is not None:
            self.access_control.check_can_select(
                self.session.user, catalog, schema, table
            )
        symbols = [
            P.Symbol(P.fresh_name(c.name), c.type) for c in ts.columns
        ]
        node = P.TableScan(catalog, schema, table, symbols, ts.column_names())
        fields = [
            Field(c.name.lower(), table, s) for c, s in zip(ts.columns, symbols)
        ]
        return RelationPlan(node, Scope(fields))

    def _plan_join(self, rel: t.Join) -> RelationPlan:
        left = self._plan_relation(rel.left)
        # CROSS JOIN UNNEST(expr): the unnest references the LEFT relation
        # (lateral semantics) — plan an Unnest node instead of a join
        unnest_ast, u_alias, u_cols = _unwrap_unnest(rel.right)
        if unnest_ast is not None and rel.join_type == "CROSS":
            return self._plan_unnest(left, unnest_ast, u_alias, u_cols)
        right = self._plan_relation(rel.right)
        combined_scope = Scope(left.scope.fields + right.scope.fields)
        if rel.join_type == "CROSS":
            node = P.Join("CROSS", left.node, right.node, [])
            return RelationPlan(node, combined_scope)
        criteria: list[tuple[P.Symbol, P.Symbol]] = []
        residual: list[RowExpr] = []
        left_extra: list[tuple[P.Symbol, RowExpr]] = []
        right_extra: list[tuple[P.Symbol, RowExpr]] = []
        if rel.using:
            for col in rel.using:
                ls = left.scope.resolve((col,))
                rs = right.scope.resolve((col,))
                criteria.append((ls, rs))
        elif rel.criteria is not None:
            conjuncts = _split_conjuncts(rel.criteria)
            left_names = {f.symbol.name for f in left.scope.fields}
            right_names = {f.symbol.name for f in right.scope.fields}
            for c in conjuncts:
                eq = self._as_equi_criterion(c, combined_scope, left_names, right_names)
                if eq is not None:
                    criteria.append(eq)
                    continue
                # complex equi-criterion: each side references one relation
                # only -> project the expression onto that side
                # (Trino: ExtractCommonPredicatesExpressionRewriter +
                # EqualityInference in PredicatePushDown)
                if isinstance(c, t.BinaryOp) and c.op == "=":
                    le = _fold(self._rewrite(c.left, combined_scope))
                    re_ = _fold(self._rewrite(c.right, combined_scope))
                    le, re_ = _coerce_pair(le, re_)
                    lrefs = referenced_variables(le)
                    rrefs = referenced_variables(re_)
                    sides = None
                    if lrefs <= left_names and rrefs <= right_names:
                        sides = (le, re_)
                    elif lrefs <= right_names and rrefs <= left_names:
                        sides = (re_, le)
                    if sides is not None and lrefs and rrefs:
                        lex, rex = sides
                        if isinstance(lex, Variable):
                            lsym = P.Symbol(lex.name, lex.type)
                        else:
                            lsym = P.Symbol(P.fresh_name("jk"), lex.type)
                            left_extra.append((lsym, lex))
                        if isinstance(rex, Variable):
                            rsym = P.Symbol(rex.name, rex.type)
                        else:
                            rsym = P.Symbol(P.fresh_name("jk"), rex.type)
                            right_extra.append((rsym, rex))
                        criteria.append((lsym, rsym))
                        continue
                residual.append(_fold(self._rewrite(c, combined_scope)))
        lnode, rnode = left.node, right.node
        if left_extra:
            lnode = P.Project(
                lnode,
                [(s, variable(s.name, s.type)) for s in lnode.output_symbols]
                + left_extra,
            )
        if right_extra:
            rnode = P.Project(
                rnode,
                [(s, variable(s.name, s.type)) for s in rnode.output_symbols]
                + right_extra,
            )
        filt = None
        if residual:
            filt = residual[0]
            for r in residual[1:]:
                filt = special("and", T.BOOLEAN, filt, r)
        node = P.Join(rel.join_type, lnode, rnode, criteria, filter=filt)
        return RelationPlan(node, combined_scope)

    def _as_equi_criterion(self, c, scope, left_names, right_names):
        if not (isinstance(c, t.BinaryOp) and c.op == "="):
            return None
        a = self._try_symbol(c.left, scope)
        b = self._try_symbol(c.right, scope)
        if a is None or b is None:
            return None
        if a.name in left_names and b.name in right_names:
            return (a, b)
        if b.name in left_names and a.name in right_names:
            return (b, a)
        return None

    def _try_symbol(self, e: t.Node, scope: Scope) -> Optional[P.Symbol]:
        if isinstance(e, t.Identifier):
            sym = scope.try_resolve(e.parts)
            return sym
        return None

    # ==== window functions ==============================================
    _RANKING_WINDOW = (
        "row_number", "rank", "dense_rank", "ntile", "percent_rank", "cume_dist"
    )
    _VALUE_WINDOW = ("lead", "lag", "first_value", "last_value", "nth_value")
    _AGG_WINDOW = ("sum", "count", "avg", "min", "max")

    def _plan_windows(self, node: P.PlanNode, window_calls, rewrite_fn):
        """Plan window functions over ``node``. One :class:`P.Window` per
        distinct (PARTITION BY, ORDER BY, frame) spec, mirroring Trino's
        ``WindowOperator`` grouping (``sql/planner/QueryPlanner.java``'s
        window planning). ``rewrite_fn`` rewrites argument ASTs in the
        enclosing context (input scope or post-aggregation replacements).
        Returns (new_node, {window_call_ast: output_symbol})."""
        replacements: dict[t.Node, P.Symbol] = {}
        groups: dict[tuple, list[t.FunctionCall]] = {}
        for fc in window_calls:
            if fc in replacements:
                continue
            key = (fc.window.partition_by, fc.window.order_by, fc.window.frame)
            groups.setdefault(key, [])
            if fc not in groups[key]:
                groups[key].append(fc)

        for (pb, ob, frame), fcs in groups.items():
            pre: list[tuple[P.Symbol, RowExpr]] = []

            def proj(ex: RowExpr) -> P.Symbol:
                if isinstance(ex, Variable):
                    return P.Symbol(ex.name, ex.type)
                sym = P.Symbol(P.fresh_name("w"), ex.type)
                pre.append((sym, ex))
                return sym

            part_syms = [proj(_fold(rewrite_fn(p))) for p in pb]
            orderings = [
                self._ordering(proj(_fold(rewrite_fn(si.expression))), si)
                for si in ob
            ]
            if frame is not None:
                ftype, fstart, fend = frame
                ok = (fstart, fend) in (
                    ("UNBOUNDED PRECEDING", "CURRENT ROW"),
                    ("UNBOUNDED PRECEDING", "UNBOUNDED FOLLOWING"),
                )
                bounded = (
                    ftype == "ROWS"
                    and fend == "CURRENT ROW"
                    and fstart.endswith(" PRECEDING")
                    and fstart.split()[0].isdigit()
                )
                if bounded and int(fstart.split()[0]) > 256:
                    raise SemanticError("ROWS frame wider than 256 unsupported")
                if not ok and not bounded:
                    raise SemanticError(f"unsupported window frame: {frame}")
            functions: list[tuple[P.Symbol, P.WindowFunction]] = []
            for fc in fcs:
                kind = fc.name
                if fc.distinct:
                    raise SemanticError("DISTINCT in window aggregates unsupported")
                arg_expr = None
                offset = 1
                default = None
                if kind in self._RANKING_WINDOW:
                    result_type: T.SqlType = (
                        T.DOUBLE if kind in ("percent_rank", "cume_dist") else T.BIGINT
                    )
                    if kind == "ntile":
                        if len(fc.args) != 1:
                            raise SemanticError("ntile takes one argument")
                        k = _fold(rewrite_fn(fc.args[0]))
                        if not isinstance(k, Constant) or k.value is None:
                            raise SemanticError("ntile argument must be constant")
                        offset = int(k.value)
                        if offset <= 0:
                            raise SemanticError("NTILE n must be positive")
                    elif fc.args:
                        raise SemanticError(f"{kind} takes no arguments")
                    if not ob and kind != "ntile":
                        pass  # permitted; order within partition unspecified
                elif kind in self._VALUE_WINDOW:
                    arg = _fold(rewrite_fn(fc.args[0]))
                    result_type = arg.type
                    arg_expr = variable(proj(arg).name, arg.type)
                    if kind == "nth_value":
                        if len(fc.args) != 2:
                            raise SemanticError("nth_value takes two arguments")
                        k = _fold(rewrite_fn(fc.args[1]))
                        if not isinstance(k, Constant) or not k.value or int(k.value) < 1:
                            raise SemanticError("nth_value offset must be a positive constant")
                        offset = int(k.value)
                    if kind in ("lead", "lag"):
                        if len(fc.args) >= 2:
                            off = _fold(rewrite_fn(fc.args[1]))
                            if not isinstance(off, Constant) or off.value is None:
                                raise SemanticError(f"{kind} offset must be constant")
                            offset = int(off.value)
                        if len(fc.args) >= 3:
                            d = _coerce_to(_fold(rewrite_fn(fc.args[2])), arg.type)
                            if isinstance(d, Constant):
                                default = d
                            else:
                                default = variable(proj(d).name, d.type)
                elif kind in self._AGG_WINDOW:
                    if len(fc.args) == 1 and isinstance(fc.args[0], t.Star):
                        kind = "count_star"
                        result_type = T.BIGINT
                    else:
                        arg = _fold(rewrite_fn(fc.args[0]))
                        if kind == "count":
                            result_type = T.BIGINT
                        elif kind == "sum":
                            if isinstance(arg.type, T.DecimalType):
                                result_type = T.decimal(18, arg.type.scale)
                            elif T.is_integer(arg.type):
                                result_type = T.BIGINT
                            else:
                                result_type = arg.type
                        elif kind == "avg":
                            result_type = (
                                arg.type
                                if isinstance(arg.type, T.DecimalType)
                                else T.DOUBLE
                            )
                        else:
                            result_type = arg.type
                        if kind == "avg" and not isinstance(
                            arg.type, T.DecimalType
                        ):
                            arg = _coerce_to(arg, T.DOUBLE)
                        arg_expr = variable(proj(arg).name, arg.type)
                else:
                    raise SemanticError(f"unknown window function: {kind}")
                out_sym = P.Symbol(P.fresh_name(kind), result_type)
                functions.append(
                    (out_sym, P.WindowFunction(kind, arg_expr, result_type, offset, default))
                )
                replacements[fc] = out_sym
            if pre:
                node = P.Project(
                    node,
                    [(s, variable(s.name, s.type)) for s in node.output_symbols]
                    + pre,
                )
            node = P.Window(node, part_syms, orderings, functions, frame)
        return node, replacements

    def _plan_derived_aggregate(
        self, fc: t.FunctionCall, input_scope, add_agg, fc_filter=None
    ) -> RowExpr:
        """stddev/variance family and boolean aggregates composed from
        sum/count/min/max plus a post-aggregation expression. ``fc_filter``
        (the FILTER clause) applies to every sub-aggregate."""
        kind = fc.name
        arg = _fold(self._rewrite(fc.args[0], input_scope))
        if kind == "checksum":
            # order-insensitive: wrapping SUM of per-row 64-bit hashes.
            # (reference 'checksum' XORs hashes into a varbinary; BIGINT
            # output is a documented deviation). Strings hash by CONTENT
            # (str_hash64 dictionary table), not by code assignment.
            hash_fn = "str_hash64" if T.is_string(arg.type) else "hash64"
            hashed = call(hash_fn, T.BIGINT, arg)
            s = add_agg("sum", hashed, T.BIGINT, filt=fc_filter)
            # NULL only for EMPTY groups (all-NULL groups hash the NULLs)
            rows = add_agg("count_star", None, T.BIGINT, filt=fc_filter)
            return special(
                "if", T.BIGINT,
                call(
                    "gt", T.BOOLEAN,
                    variable(rows.name, T.BIGINT),
                    const(0, T.BIGINT),
                ),
                variable(s.name, T.BIGINT),
                Constant(type=T.BIGINT, value=None),
            )
        if kind in ("corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept"):
            # two-argument moments family composed from sums (reference:
            # CentralMomentsAggregation / CorrelationAggregation states)
            y = _coerce_to(arg, T.DOUBLE)
            x = _coerce_to(
                _fold(self._rewrite(fc.args[1], input_scope)), T.DOUBLE
            )
            both = call(
                "multiply", T.DOUBLE,
                special("if", T.DOUBLE, special("not", T.BOOLEAN, special("is_null", T.BOOLEAN, x)), y, Constant(type=T.DOUBLE, value=None)),
                const(1.0, T.DOUBLE),
            )
            xboth = call(
                "multiply", T.DOUBLE,
                special("if", T.DOUBLE, special("not", T.BOOLEAN, special("is_null", T.BOOLEAN, y)), x, Constant(type=T.DOUBLE, value=None)),
                const(1.0, T.DOUBLE),
            )
            sy = variable(add_agg("sum", both, T.DOUBLE, filt=fc_filter).name, T.DOUBLE)
            sx = variable(add_agg("sum", xboth, T.DOUBLE, filt=fc_filter).name, T.DOUBLE)
            sxy = variable(
                add_agg("sum", call("multiply", T.DOUBLE, x, y), T.DOUBLE, filt=fc_filter).name,
                T.DOUBLE,
            )
            sxx = variable(
                add_agg("sum", call("multiply", T.DOUBLE, xboth, xboth), T.DOUBLE, filt=fc_filter).name,
                T.DOUBLE,
            )
            syy = variable(
                add_agg("sum", call("multiply", T.DOUBLE, both, both), T.DOUBLE, filt=fc_filter).name,
                T.DOUBLE,
            )
            n = _coerce_to(
                variable(
                    add_agg("count", call("multiply", T.DOUBLE, x, y), T.BIGINT, filt=fc_filter).name,
                    T.BIGINT,
                ),
                T.DOUBLE,
            )
            cov_n = call(
                "subtract", T.DOUBLE,
                call("multiply", T.DOUBLE, n, sxy),
                call("multiply", T.DOUBLE, sx, sy),
            )
            var_x_n = call(
                "subtract", T.DOUBLE,
                call("multiply", T.DOUBLE, n, sxx),
                call("multiply", T.DOUBLE, sx, sx),
            )
            var_y_n = call(
                "subtract", T.DOUBLE,
                call("multiply", T.DOUBLE, n, syy),
                call("multiply", T.DOUBLE, sy, sy),
            )
            if kind == "covar_pop":
                expr = call(
                    "divide", T.DOUBLE, cov_n,
                    call("multiply", T.DOUBLE, n, n),
                )
                min_n = 0.0
            elif kind == "covar_samp":
                expr = call(
                    "divide", T.DOUBLE, cov_n,
                    call("multiply", T.DOUBLE, n,
                         call("subtract", T.DOUBLE, n, const(1.0, T.DOUBLE))),
                )
                min_n = 1.0
            elif kind == "regr_slope":
                expr = call("divide", T.DOUBLE, cov_n, var_x_n)
                min_n = 1.0
            elif kind == "regr_intercept":
                slope = call("divide", T.DOUBLE, cov_n, var_x_n)
                expr = call(
                    "divide", T.DOUBLE,
                    call("subtract", T.DOUBLE, sy,
                         call("multiply", T.DOUBLE, slope, sx)),
                    n,
                )
                min_n = 1.0
            else:  # corr
                expr = call(
                    "divide", T.DOUBLE, cov_n,
                    call(
                        "sqrt", T.DOUBLE,
                        call("multiply", T.DOUBLE, var_x_n, var_y_n),
                    ),
                )
                min_n = 1.0
            return special(
                "if", T.DOUBLE,
                call("gt", T.BOOLEAN, n, const(min_n, T.DOUBLE)),
                expr,
                Constant(type=T.DOUBLE, value=None),
            )
        if kind in ("bool_and", "every", "bool_or"):
            # NULL inputs are IGNORED by aggregates: map TRUE->1, FALSE->0,
            # NULL->NULL (the nested IF keeps NULL invalid, so min/max skip it)
            as_int = special(
                "if", T.BIGINT, arg, const(1, T.BIGINT),
                special(
                    "if", T.BIGINT, special("not", T.BOOLEAN, arg),
                    const(0, T.BIGINT), Constant(type=T.BIGINT, value=None),
                ),
            )
            agg_kind = "min" if kind in ("bool_and", "every") else "max"
            s = add_agg(agg_kind, as_int, T.BIGINT, filt=fc_filter)
            return call("eq", T.BOOLEAN, variable(s.name, T.BIGINT), const(1, T.BIGINT))
        # variance family over doubles
        xd = _coerce_to(arg, T.DOUBLE)
        s_sum = add_agg("sum", xd, T.DOUBLE, filt=fc_filter)
        s_sq = add_agg(
            "sum", call("multiply", T.DOUBLE, xd, xd), T.DOUBLE, filt=fc_filter
        )
        s_cnt = add_agg("count", xd, T.BIGINT, filt=fc_filter)
        n = _coerce_to(variable(s_cnt.name, T.BIGINT), T.DOUBLE)
        sum_v = variable(s_sum.name, T.DOUBLE)
        sq_v = variable(s_sq.name, T.DOUBLE)
        # m2 = sum(x^2) - sum(x)^2 / n
        m2 = call(
            "subtract", T.DOUBLE, sq_v,
            call("divide", T.DOUBLE, call("multiply", T.DOUBLE, sum_v, sum_v), n),
        )
        pop = kind in ("var_pop", "stddev_pop")
        denom = (
            n if pop else call("subtract", T.DOUBLE, n, const(1.0, T.DOUBLE))
        )
        var_expr = call("divide", T.DOUBLE, m2, denom)
        # NULL when n == 0 (pop) or n <= 1 (samp), per reference semantics
        min_n = const(0.0 if pop else 1.0, T.DOUBLE)
        guarded = special(
            "if", T.DOUBLE,
            call("gt", T.BOOLEAN, n, min_n),
            var_expr,
            Constant(type=T.DOUBLE, value=None),
        )
        if kind in ("stddev", "stddev_samp", "stddev_pop"):
            return call("sqrt", T.DOUBLE, guarded)
        return guarded

    # ==== decorrelation =================================================
    # (_conjuncts_of lives at module scope below)

    def _produced_symbols(self, node: P.PlanNode) -> set[str]:
        out: set[str] = set()

        def walk(n: P.PlanNode):
            for s in n.output_symbols:
                out.add(s.name)
            for src in n.sources:
                walk(src)

        walk(node)
        return out

    def _decorrelate(self, node: P.PlanNode, produced: set[str], ctx: dict):
        """Strip Filter conjuncts referencing symbols outside ``produced``
        (correlated references to the enclosing query) and hoist them to the
        top, adding pass-through projections so inner symbols the conjuncts
        need stay visible. A correlated filter below a global Aggregate
        turns its inner equality symbols into group keys (classic
        decorrelation; ``ctx['grouped']`` records it so the caller joins
        LEFT and fixes COUNT-over-empty). Reference: the effect of Trino's
        TransformCorrelated* rule family (iterative/rule/).

        Returns (new_node, corr_conjuncts: list[RowExpr])."""
        if isinstance(node, P.Filter):
            src, corr = self._decorrelate(node.source, produced, ctx)
            keep: list[RowExpr] = []
            for c in _conjuncts_of(node.predicate):
                if referenced_variables(c) - produced:
                    corr = corr + [c]
                else:
                    keep.append(c)
            if keep:
                pred = keep[0]
                for k in keep[1:]:
                    pred = special("and", T.BOOLEAN, pred, k)
                return P.Filter(src, pred), corr
            return src, corr

        if isinstance(node, P.Project):
            src, corr = self._decorrelate(node.source, produced, ctx)
            if not corr:
                return P.Project(src, node.assignments), corr
            # pass through inner symbols the hoisted conjuncts reference
            available = {s.name: s for s in src.output_symbols}
            have = {s.name for s, _ in node.assignments}
            extra = []
            for c in corr:
                for r in referenced_variables(c):
                    if r in produced and r not in have and r in available:
                        sym = available[r]
                        extra.append((sym, variable(sym.name, sym.type)))
                        have.add(r)
            return P.Project(src, list(node.assignments) + extra), corr

        if isinstance(node, P.Join):
            lsrc, lcorr = self._decorrelate(node.left, produced, ctx)
            rsrc, rcorr = self._decorrelate(node.right, produced, ctx)
            out = P.Join(
                node.join_type, lsrc, rsrc, node.criteria, node.filter,
                node.distribution, node.mark_symbol,
            )
            return out, lcorr + rcorr

        if isinstance(node, P.Aggregate):
            src, corr = self._decorrelate(node.source, produced, ctx)
            if not corr:
                return P.Aggregate(src, node.group_keys, node.aggregates, node.step), corr
            if node.group_keys:
                raise SemanticError(
                    "correlated subquery with GROUP BY is not supported"
                )
            # global agg over correlated filter: group by the inner symbols
            # of the correlated EQUALITIES. Non-equality correlated
            # predicates cannot be hoisted above the aggregate (they would
            # filter after aggregation, changing its input) — reject.
            available = {s.name: s for s in src.output_symbols}
            keys: list[P.Symbol] = []
            for c in corr:
                is_eq = (
                    isinstance(c, Call)
                    and c.name == "eq"
                    and len(c.args) == 2
                    and all(isinstance(a, Variable) for a in c.args)
                )
                if not is_eq:
                    raise SemanticError(
                        "correlated aggregate subquery supports only "
                        "equality correlation predicates"
                    )
                for r in referenced_variables(c):
                    if r in produced:
                        if r not in available:
                            raise SemanticError(
                                "correlated reference not available for decorrelation"
                            )
                        if available[r] not in keys:
                            keys.append(available[r])
            ctx["grouped"] = True
            ctx["agg_kinds"] = {
                s.name: fn.kind for s, fn in node.aggregates
            }
            return P.Aggregate(src, keys, node.aggregates, node.step), corr

        if isinstance(node, P.Sort):
            src, corr = self._decorrelate(node.source, produced, ctx)
            return P.Sort(src, node.order_by), corr

        # correlation below cardinality-changing nodes cannot be hoisted
        has_corr_below = self._has_correlated_filter(node, produced)
        if has_corr_below:
            raise SemanticError(
                f"correlated subquery through {type(node).__name__} is not supported"
            )
        return node, []

    def _has_correlated_filter(self, node: P.PlanNode, produced: set[str]) -> bool:
        if isinstance(node, P.Filter):
            for c in _conjuncts_of(node.predicate):
                if referenced_variables(c) - produced:
                    return True
        return any(self._has_correlated_filter(s, produced) for s in node.sources)

    def _trace_agg_kind(self, node: P.PlanNode, name: str, ctx: dict) -> Optional[str]:
        """Follow identity projections from ``name`` down to an Aggregate
        output and return its aggregate kind (for COUNT-coalesce fixes)."""
        kinds = ctx.get("agg_kinds", {})
        while True:
            if name in kinds:
                return kinds[name]
            if isinstance(node, P.Project):
                nxt = None
                for s, e in node.assignments:
                    if s.name == name and isinstance(e, Variable):
                        nxt = e.name
                        break
                if nxt is None:
                    return None
                name = nxt
                node = node.source
                continue
            if isinstance(node, (P.Filter, P.Sort)):
                node = node.source
                continue
            return kinds.get(name)

    def _split_correlation(
        self, corr: list[RowExpr], outer_syms: dict, inner_syms: dict
    ):
        """Split hoisted conjuncts into equi-join criteria (outer, inner)
        and residual filter conjuncts."""
        criteria: list[tuple[P.Symbol, P.Symbol]] = []
        residual: list[RowExpr] = []
        for c in corr:
            pair = None
            if isinstance(c, Call) and c.name == "eq" and len(c.args) == 2:
                a, b = c.args
                if isinstance(a, Variable) and isinstance(b, Variable):
                    if a.name in outer_syms and b.name in inner_syms:
                        pair = (outer_syms[a.name], inner_syms[b.name])
                    elif b.name in outer_syms and a.name in inner_syms:
                        pair = (outer_syms[b.name], inner_syms[a.name])
            if pair is not None:
                criteria.append(pair)
            else:
                residual.append(c)
        return criteria, residual

    # ==== subqueries in expressions =====================================
    def _rewrite_with_subqueries(
        self, e: t.Node, rp: RelationPlan, replacements=None
    ):
        """Rewrite an expression, planning any subqueries into the relation:
        - uncorrelated scalar subquery -> CROSS join of single-row subplan
        - [NOT] IN (subquery) / EXISTS -> SEMI/ANTI join with mark symbol
        Returns (RowExpr, updated RelationPlan)."""
        state = {"rp": rp}

        def combine(conj: list[RowExpr]) -> Optional[RowExpr]:
            if not conj:
                return None
            out = conj[0]
            for c in conj[1:]:
                out = special("and", T.BOOLEAN, out, c)
            return out

        def plan_sub(query: t.Query):
            """Plan a subquery allowing correlated outer references; returns
            (decorrelated plan, criteria, residual filter, ctx). ctx carries
            'n_columns': the subquery's own column count (decorrelation may
            append pass-through columns after it)."""
            cur = state["rp"]
            sub_rp, _ = self.plan_query(query, outer=cur.scope)
            produced = self._produced_symbols(sub_rp.node)
            ctx: dict = {"n_columns": len(sub_rp.node.output_symbols)}
            new_sub, corr = self._decorrelate(sub_rp.node, produced, ctx)
            outer_syms = {s.name: s for s in cur.node.output_symbols}
            inner_syms = {s.name: s for s in new_sub.output_symbols}
            criteria, residual = self._split_correlation(corr, outer_syms, inner_syms)
            for c in residual:
                bad = referenced_variables(c) - set(outer_syms) - set(inner_syms)
                if bad:
                    raise SemanticError(
                        f"correlated reference not resolvable: {sorted(bad)}"
                    )
            return new_sub, criteria, residual, ctx

        def handle(node: t.Node) -> Optional[RowExpr]:
            if isinstance(node, t.ScalarSubquery):
                new_sub, criteria, residual, ctx = plan_sub(node.query)
                if ctx["n_columns"] != 1:
                    raise SemanticError("scalar subquery must return one column")
                # scalar output = the subquery's first (only) select column
                out_sym = new_sub.output_symbols[0]
                cur = state["rp"]
                if not criteria and not residual:
                    join = P.Join(
                        "CROSS", cur.node, new_sub, [], single_row=True
                    )
                    state["rp"] = RelationPlan(join, cur.scope)
                    return variable(out_sym.name, out_sym.type)
                # correlated scalar: LEFT join on the correlation keys;
                # >1 match per outer row is a runtime error
                join = P.Join(
                    "LEFT", cur.node, new_sub, criteria,
                    combine(residual), None, None, single_row=True,
                )
                state["rp"] = RelationPlan(join, cur.scope)
                result = variable(out_sym.name, out_sym.type)
                kind = self._trace_agg_kind(new_sub, out_sym.name, ctx)
                if ctx.get("grouped") and kind in ("count", "count_star"):
                    # COUNT over an empty correlated group is 0, but the
                    # LEFT join yields NULL for unmatched outer rows
                    result = special(
                        "coalesce", out_sym.type, result,
                        const(0, out_sym.type),
                    )
                return result
            if isinstance(node, (t.InSubquery, t.Exists)):
                cur = state["rp"]
                if isinstance(node, t.InSubquery):
                    new_sub, criteria, residual, _ctx = plan_sub(node.query)
                    if _ctx["n_columns"] != 1:
                        raise SemanticError("IN subquery must return one column")
                    syms = new_sub.output_symbols
                    value = self._rewrite(node.value, cur.scope)
                    cur = state["rp"]
                    if not isinstance(value, Variable):
                        vsym = P.Symbol(P.fresh_name("inval"), value.type)
                        proj = P.Project(
                            cur.node,
                            [
                                (s, variable(s.name, s.type))
                                for s in cur.node.output_symbols
                            ]
                            + [(vsym, value)],
                        )
                        cur = RelationPlan(proj, cur.scope)
                        value = variable(vsym.name, vsym.type)
                    mark = P.Symbol(P.fresh_name("in_mark"), T.BOOLEAN)
                    jt = "ANTI" if node.negated else "SEMI"
                    join = P.Join(
                        jt,
                        cur.node,
                        new_sub,
                        [(P.Symbol(value.name, value.type), syms[0])] + criteria,
                        combine(residual),
                        mark_symbol=mark,
                    )
                    state["rp"] = RelationPlan(join, cur.scope)
                    return variable(mark.name, T.BOOLEAN)
                # EXISTS (correlated or not): SEMI/ANTI join on correlation
                new_sub, criteria, residual, _ctx = plan_sub(node.query)
                cur = state["rp"]
                mark = P.Symbol(P.fresh_name("exists_mark"), T.BOOLEAN)
                join = P.Join(
                    "SEMI" if not node.negated else "ANTI",
                    cur.node,
                    new_sub,
                    criteria,
                    combine(residual),
                    mark_symbol=mark,
                    null_aware=False,  # EXISTS is two-valued
                )
                state["rp"] = RelationPlan(join, cur.scope)
                return variable(mark.name, T.BOOLEAN)
            return None

        ex = self._rewrite(
            e,
            rp.scope,
            replacements=replacements,
            subquery_handler=handle,
            scope_getter=lambda: state["rp"].scope,
        )
        return ex, state["rp"]

    # ==== AST normalization =============================================
    def _normalize(self, e: t.Node, scope: Scope) -> t.Node:
        """Canonicalize an AST expression for structural matching: every
        resolvable Identifier becomes Identifier((symbol_name,)) so that
        'X' vs 'x' vs 't.x' compare equal (name resolution is
        case-insensitive; structural dataclass equality is not). Subquery
        bodies are left untouched (their identifiers resolve in inner
        scopes)."""
        if isinstance(e, t.Identifier):
            sym = scope.try_resolve(e.parts)
            if sym is not None:
                return t.Identifier((sym.name,))
            return t.Identifier(tuple(p.lower() for p in e.parts))
        if isinstance(e, (t.ScalarSubquery, t.InSubquery, t.Exists, t.Query)):
            return e
        if dataclasses.is_dataclass(e) and isinstance(e, t.Node):
            changes = {}
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, t.Node):
                    changes[f.name] = self._normalize(v, scope)
                elif isinstance(v, tuple):
                    new_items = tuple(
                        self._normalize(item, scope)
                        if isinstance(item, t.Node)
                        else (
                            tuple(
                                self._normalize(sub, scope)
                                if isinstance(sub, t.Node)
                                else sub
                                for sub in item
                            )
                            if isinstance(item, tuple)
                            else item
                        )
                        for item in v
                    )
                    changes[f.name] = new_items
            if changes:
                return dataclasses.replace(e, **changes)
        return e

    # ==== expression rewriting ==========================================
    def _rewrite(
        self,
        e: t.Node,
        scope: Scope,
        replacements: Optional[dict[t.Node, P.Symbol]] = None,
        subquery_handler=None,
        scope_getter=None,
    ) -> RowExpr:
        def rw(node: t.Node) -> RowExpr:
            if replacements is not None and node in replacements:
                s = replacements[node]
                return variable(s.name, s.type)
            if subquery_handler is not None:
                out = subquery_handler(node)
                if out is not None:
                    return out
            cur_scope = scope_getter() if scope_getter is not None else scope
            return self._rewrite_node(node, cur_scope, rw)

        return rw(e)

    def _rewrite_node(self, e: t.Node, scope: Scope, rw) -> RowExpr:
        if isinstance(e, t.Identifier):
            sym = scope.resolve(e.parts)
            return variable(sym.name, sym.type)
        if isinstance(e, t.Literal):
            return _literal(e)
        if isinstance(e, t.ArrayLiteral):
            # constant element lists fold into an ARRAY Constant whose value
            # is a tuple of STORAGE scalars (None = NULL element)
            items = [_fold(rw(it)) for it in e.items]
            et: T.SqlType = T.UNKNOWN
            for it in items:
                et = T.common_super_type(et, it.type) or et
            if isinstance(et, T.UnknownType):
                et = T.BIGINT
            coerced = [_fold(_coerce_to(it, et)) for it in items]
            if not all(isinstance(it, Constant) for it in coerced):
                raise SemanticError(
                    "ARRAY constructor elements must be constant (v1)"
                )
            return Constant(
                type=T.ArrayType(element=et),
                value=tuple(it.value for it in coerced),
            )
        if isinstance(e, t.Subscript):
            base = rw(e.base)
            idx = _fold(rw(e.index))
            bt = base.type
            if isinstance(bt, T.ArrayType):
                return call(
                    "element_at", bt.element, base, _coerce_to(idx, T.BIGINT)
                )
            if isinstance(bt, T.MapType):
                return call(
                    "map_element_at", bt.value, base, _coerce_to(idx, bt.key)
                )
            if isinstance(bt, T.RowType):
                if not isinstance(idx, Constant) or idx.value is None:
                    raise SemanticError("ROW subscript must be a constant")
                i = int(idx.value)
                if not 1 <= i <= len(bt.fields):
                    raise SemanticError(
                        f"ROW subscript {i} out of range 1..{len(bt.fields)}"
                    )
                return call(
                    "row_field", bt.fields[i - 1][1], base,
                    Constant(type=T.BIGINT, value=i),
                )
            raise SemanticError(
                f"subscript requires ARRAY, MAP, or ROW (got {bt})"
            )
        if isinstance(e, t.IntervalLiteral):
            return Constant(type=T.UNKNOWN, value=e)  # consumed by date arith
        if isinstance(e, t.UnaryOp):
            operand = rw(e.operand)
            if e.op == "NOT":
                return special("not", T.BOOLEAN, operand)
            if e.op == "-":
                return call("negate", operand.type, operand)
            return operand
        if isinstance(e, t.BinaryOp):
            return self._binary(e, rw)
        if isinstance(e, t.IsNull):
            inner = special("is_null", T.BOOLEAN, rw(e.operand))
            return special("not", T.BOOLEAN, inner) if e.negated else inner
        if isinstance(e, t.Between):
            v, lo, hi = rw(e.value), rw(e.low), rw(e.high)
            v, lo = _coerce_pair(v, lo)
            v, hi = _coerce_pair(v, hi)
            out = special("between", T.BOOLEAN, v, lo, hi)
            return special("not", T.BOOLEAN, out) if e.negated else out
        if isinstance(e, t.InList):
            v = rw(e.value)
            items = []
            for item in e.items:
                iv = rw(item)
                _, iv = _coerce_pair(v, iv)
                items.append(iv)
            out = special("in", T.BOOLEAN, v, *items)
            return special("not", T.BOOLEAN, out) if e.negated else out
        if isinstance(e, t.Like):
            v = rw(e.value)
            p = rw(e.pattern)
            if not isinstance(p, Constant):
                raise SemanticError("LIKE pattern must be constant")
            out = call("like", T.BOOLEAN, v, p)
            return special("not", T.BOOLEAN, out) if e.negated else out
        if isinstance(e, t.Cast):
            operand = rw(e.operand)
            target = T.parse_type(e.target)
            if isinstance(operand, Constant) and operand.type == T.UNKNOWN:
                return Constant(type=target, value=None)
            if isinstance(operand, Constant) and T.is_string(target):
                v = operand.value
                if v is None:
                    return Constant(type=target, value=None)
                if isinstance(operand.type, T.DecimalType):
                    from decimal import Decimal as _D

                    # scaleb keeps the declared scale: 1.50 -> '1.50'
                    s = str(_D(v).scaleb(-operand.type.scale))
                elif isinstance(operand.type, T.BooleanType):
                    s = "true" if v else "false"
                else:
                    s = str(v)
                return Constant(type=target, value=s)
            if isinstance(operand, Constant) and T.is_string(operand.type):
                if e.safe:
                    # TRY_CAST: invalid conversion yields NULL, not an error
                    # (ArithmeticError covers decimal.InvalidOperation)
                    try:
                        return _cast_string_constant(operand, target)
                    except (ValueError, ArithmeticError, SemanticError):
                        return Constant(type=target, value=None)
                return _cast_string_constant(operand, target)
            return call("cast", target, operand)
        if isinstance(e, t.Extract):
            operand = rw(e.operand)
            field = {"dow": "day_of_week", "doy": "day_of_year",
                     "day_of_week": "day_of_week", "day_of_year": "day_of_year",
                     "week": "week", "quarter": "quarter"}.get(e.field, e.field)
            if field not in ("year", "month", "day", "day_of_week",
                             "day_of_year", "week", "quarter"):
                raise SemanticError(f"EXTRACT({e.field}) unsupported")
            return call(field, T.BIGINT, operand)
        if isinstance(e, t.Case):
            return self._case(e, rw)
        if isinstance(e, t.FunctionCall):
            return self._function(e, rw)
        if isinstance(e, t.QuantifiedComparison):
            return rw(_expand_quantified(e))
        if isinstance(e, t.ScalarSubquery):
            raise SemanticError("scalar subquery not allowed in this context")
        if isinstance(e, (t.InSubquery, t.Exists)):
            raise SemanticError("subquery predicate not allowed in this context")
        raise SemanticError(f"unsupported expression: {type(e).__name__}")

    def _case(self, e: t.Case, rw) -> RowExpr:
        whens = []
        result_type: Optional[T.SqlType] = None
        results = []
        for cond_ast, res_ast in e.whens:
            res = rw(res_ast)
            results.append(res)
            result_type = (
                res.type
                if result_type is None
                else (T.common_super_type(result_type, res.type) or result_type)
            )
        default = rw(e.default) if e.default is not None else None
        if default is not None:
            result_type = T.common_super_type(result_type, default.type) or result_type
        if e.operand is not None:
            op = rw(e.operand)
            conds = [
                _make_comparison("eq", op, rw(c_ast)) for c_ast, _ in e.whens
            ]
        else:
            conds = [rw(c_ast) for c_ast, _ in e.whens]
        out = (
            _coerce_to(default, result_type)
            if default is not None
            else Constant(type=result_type, value=None)
        )
        for cond, res in reversed(list(zip(conds, results))):
            out = special("if", result_type, cond, _coerce_to(res, result_type), out)
        return out

    def _function(self, e: t.FunctionCall, rw) -> RowExpr:
        if e.window is not None:
            raise SemanticError("window functions not yet supported in this context")
        name = e.name
        if name in ("sum", "count", "avg", "min", "max"):
            raise SemanticError(f"aggregate {name} not allowed here")
        args = [rw(a) for a in e.args]
        if name == "coalesce":
            rt = args[0].type
            for a in args[1:]:
                rt = T.common_super_type(rt, a.type) or rt
            return special(
                "coalesce", rt, *[_coerce_to(a, rt) for a in args]
            )
        if name == "nullif":
            a, b = _coerce_pair(args[0], args[1])
            return special("null_if", a.type, a, b)
        if name == "abs":
            return call("abs", args[0].type, args[0])
        if name == "sqrt":
            return call("sqrt", T.DOUBLE, _coerce_to(args[0], T.DOUBLE))
        if name in ("floor", "ceil", "ceiling"):
            n = "ceil" if name == "ceiling" else name
            return call(n, args[0].type, args[0])
        if name == "round":
            return call("round", args[0].type, *args)
        if name in ("year", "month", "day"):
            return call(name, T.BIGINT, args[0])
        if name == "mod":
            a, b = _coerce_pair(args[0], args[1])
            return call("modulus", a.type, a, b)
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor"):
            return call(
                name, T.BIGINT,
                _coerce_to(args[0], T.BIGINT), _coerce_to(args[1], T.BIGINT),
            )
        if name == "bitwise_not":
            return call(name, T.BIGINT, _coerce_to(args[0], T.BIGINT))
        if name in ("bitwise_left_shift", "bitwise_right_shift",
                    "bitwise_right_shift_arithmetic", "shiftleft", "shiftright"):
            canon = {
                "shiftleft": "bitwise_left_shift",
                "shiftright": "bitwise_right_shift",
            }.get(name, name)
            return call(
                canon, T.BIGINT,
                _coerce_to(args[0], T.BIGINT), _coerce_to(args[1], T.BIGINT),
            )
        if name == "hash64":
            return call("hash64", T.BIGINT, _coerce_to(args[0], T.BIGINT))
        if name == "width_bucket":
            return call(
                "width_bucket", T.BIGINT,
                _coerce_to(args[0], T.DOUBLE), _coerce_to(args[1], T.DOUBLE),
                _coerce_to(args[2], T.DOUBLE), _coerce_to(args[3], T.BIGINT),
            )
        if name in ("format_datetime", "date_format"):
            if not isinstance(args[1], Constant):
                raise SemanticError(f"{name} pattern must be a literal")
            return call(name, T.VARCHAR, args[0], args[1])
        if name in ("json_extract_scalar", "json_extract"):
            return call(name, T.VARCHAR, *args)
        if name == "cardinality":
            if isinstance(args[0].type, T.MapType):
                return call("map_cardinality", T.BIGINT, args[0])
            if not isinstance(args[0].type, T.ArrayType):
                raise SemanticError(
                    "cardinality requires an ARRAY or MAP argument"
                )
            return call("cardinality", T.BIGINT, args[0])
        if name == "element_at":
            if isinstance(args[0].type, T.MapType):
                mt = args[0].type
                return call(
                    "map_element_at", mt.value, args[0],
                    _coerce_to(args[1], mt.key),
                )
            if not isinstance(args[0].type, T.ArrayType):
                raise SemanticError(
                    "element_at requires an ARRAY or MAP argument"
                )
            return call(
                "element_at", args[0].type.element, args[0],
                _coerce_to(args[1], T.BIGINT),
            )
        if name == "map":
            # MAP(ARRAY[k...], ARRAY[v...]) constructor (constant v1, like
            # the ARRAY constructor) -> pool-coded MAP constant
            if len(args) != 2 or not all(
                isinstance(a, Constant) and isinstance(a.type, T.ArrayType)
                for a in args
            ):
                raise SemanticError(
                    "map() requires two constant ARRAY arguments (v1)"
                )
            karr, varr = args
            if karr.value is None or varr.value is None:
                raise SemanticError("map() arrays must be non-null")
            if len(karr.value) != len(varr.value):
                raise SemanticError("map() key/value arrays differ in length")
            if any(k is None for k in karr.value):
                raise SemanticError("map key cannot be null")
            if len(set(karr.value)) != len(karr.value):
                raise SemanticError("Duplicate map keys are not allowed")
            # canonical key order: equality/grouping compare pool codes, so
            # equal maps must pool identically regardless of build order
            pairs = tuple(sorted(zip(karr.value, varr.value), key=lambda p: p[0]))
            return Constant(
                type=T.MapType(key=karr.type.element, value=varr.type.element),
                value=pairs,
            )
        if name == "map_keys" or name == "map_values":
            # producing a NEW pool column from an expression needs the
            # projection-level pool plumbing; not wired yet
            raise SemanticError(f"{name} is not supported yet")
        if name == "row":
            if not all(isinstance(a, Constant) for a in args):
                raise SemanticError("row() fields must be constant (v1)")
            return Constant(
                type=T.RowType(
                    fields=tuple((None, a.type) for a in args)
                ),
                value=tuple(a.value for a in args),
            )
        if name == "contains":
            if not isinstance(args[0].type, T.ArrayType):
                raise SemanticError("contains requires an ARRAY argument")
            return call(
                "array_contains", T.BOOLEAN, args[0],
                _coerce_to(args[1], args[0].type.element),
            )
        if name == "power" or name == "pow":
            return call(
                "power",
                T.DOUBLE,
                _coerce_to(args[0], T.DOUBLE),
                _coerce_to(args[1], T.DOUBLE),
            )
        if name == "length":
            if isinstance(args[0], Constant):
                v = args[0].value
                return Constant(
                    type=T.BIGINT, value=None if v is None else len(str(v))
                )
            return call("length", T.BIGINT, args[0])
        if name in ("substr", "substring"):
            return call("substr", T.VARCHAR, *args)
        if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse"):
            if not T.is_string(args[0].type):
                raise SemanticError(f"{name} requires a string argument")
            return call(name, T.VARCHAR, args[0])
        if name == "replace":
            if len(args) == 2:
                args = args + [const("", T.VARCHAR)]
            return call("replace", T.VARCHAR, *args)
        if name == "concat":
            for a in args:
                if not T.is_string(a.type) and a.type != T.UNKNOWN:
                    raise SemanticError(
                        "concat requires varchar arguments (add a cast)"
                    )
            return call("concat", T.VARCHAR, *args)
        if name in ("lpad", "rpad"):
            return call(name, T.VARCHAR, *args)
        if name == "strpos":
            if isinstance(args[0], Constant) and isinstance(args[1], Constant):
                a, b = args[0].value, args[1].value
                v = None if a is None or b is None else str(a).find(str(b)) + 1
                return Constant(type=T.BIGINT, value=v)
            return call("strpos", T.BIGINT, *args)
        if name == "split_part":
            return call("split_part", T.VARCHAR, *args)
        if name == "starts_with":
            if isinstance(args[0], Constant) and isinstance(args[1], Constant):
                a, b = args[0].value, args[1].value
                v = None if a is None or b is None else str(a).startswith(str(b))
                return Constant(type=T.BOOLEAN, value=v)
            return call("starts_with", T.BOOLEAN, *args)
        if name == "date":
            return call("cast", T.DATE, args[0])
        if name == "date_add":
            unit_c, n_e, d_e = args
            if not isinstance(unit_c, Constant):
                raise SemanticError("date_add unit must be a literal")
            unit = str(unit_c.value).lower().rstrip("s")
            if isinstance(d_e.type, T.TimestampType):
                us = {"second": 10**6, "minute": 60 * 10**6, "hour": 3600 * 10**6,
                      "day": 86_400 * 10**6, "week": 7 * 86_400 * 10**6}
                if unit in us:
                    return call(
                        "add", T.TIMESTAMP, d_e,
                        call("multiply", T.BIGINT, _coerce_to(n_e, T.BIGINT),
                             const(us[unit], T.BIGINT)),
                    )
                raise SemanticError(f"date_add unit {unit} on timestamp unsupported")
            if unit == "day":
                return call("date_add_days", T.DATE, d_e, n_e)
            if unit == "week":
                return call(
                    "date_add_days", T.DATE, d_e,
                    call("multiply", T.BIGINT, _coerce_to(n_e, T.BIGINT), const(7, T.BIGINT)),
                )
            if unit == "month":
                return call("date_add_months", T.DATE, d_e, n_e)
            if unit == "quarter":
                return call(
                    "date_add_months", T.DATE, d_e,
                    call("multiply", T.BIGINT, _coerce_to(n_e, T.BIGINT), const(3, T.BIGINT)),
                )
            if unit == "year":
                return call(
                    "date_add_months", T.DATE, d_e,
                    call("multiply", T.BIGINT, _coerce_to(n_e, T.BIGINT), const(12, T.BIGINT)),
                )
            raise SemanticError(f"date_add unit {unit} unsupported")
        if name == "date_diff":
            unit_c, a_e, b_e = args
            if not isinstance(unit_c, Constant):
                raise SemanticError("date_diff unit must be a literal")
            unit = str(unit_c.value).lower().rstrip("s")
            if isinstance(a_e.type, T.TimestampType) or isinstance(b_e.type, T.TimestampType):
                us = {"second": 10**6, "minute": 60 * 10**6, "hour": 3600 * 10**6,
                      "day": 86_400 * 10**6, "week": 7 * 86_400 * 10**6,
                      "millisecond": 1000}
                if unit in us:
                    diff = call("subtract", T.BIGINT, _coerce_to(b_e, T.TIMESTAMP), _coerce_to(a_e, T.TIMESTAMP))
                    return call(
                        "divide", T.BIGINT, diff, const(us[unit], T.BIGINT)
                    )
                raise SemanticError(f"date_diff unit {unit} on timestamp unsupported")
            day_diff = call("date_diff_days", T.BIGINT, a_e, b_e)
            if unit == "day":
                return day_diff
            if unit == "week":
                return call("divide", T.BIGINT, day_diff, const(7, T.BIGINT))
            if unit in ("month", "quarter", "year"):
                cal = call(
                    "subtract", T.BIGINT,
                    call(
                        "add", T.BIGINT,
                        call("multiply", T.BIGINT, call("year", T.BIGINT, b_e), const(12, T.BIGINT)),
                        call("month", T.BIGINT, b_e),
                    ),
                    call(
                        "add", T.BIGINT,
                        call("multiply", T.BIGINT, call("year", T.BIGINT, a_e), const(12, T.BIGINT)),
                        call("month", T.BIGINT, a_e),
                    ),
                )
                da = call("day", T.BIGINT, a_e)
                db = call("day", T.BIGINT, b_e)
                # truncate toward zero to FULL months elapsed (reference
                # semantics): forward diffs lose 1 when day(b) < day(a),
                # backward diffs gain 1 when day(b) > day(a)
                months = call(
                    "add", T.BIGINT, cal,
                    special(
                        "if", T.BIGINT,
                        special(
                            "and", T.BOOLEAN,
                            call("gt", T.BOOLEAN, cal, const(0, T.BIGINT)),
                            call("lt", T.BOOLEAN, db, da),
                        ),
                        const(-1, T.BIGINT),
                        special(
                            "if", T.BIGINT,
                            special(
                                "and", T.BOOLEAN,
                                call("lt", T.BOOLEAN, cal, const(0, T.BIGINT)),
                                call("gt", T.BOOLEAN, db, da),
                            ),
                            const(1, T.BIGINT),
                            const(0, T.BIGINT),
                        ),
                    ),
                )
                if unit == "month":
                    return months
                if unit == "quarter":
                    return call("divide", T.BIGINT, months, const(3, T.BIGINT))
                return call("divide", T.BIGINT, months, const(12, T.BIGINT))
            raise SemanticError(f"date_diff unit {unit} unsupported")
        if name in ("day_of_week", "dow", "day_of_year", "doy", "week",
                    "week_of_year", "quarter", "last_day_of_month"):
            canon = {"dow": "day_of_week", "doy": "day_of_year",
                     "week_of_year": "week"}.get(name, name)
            rt = T.DATE if canon == "last_day_of_month" else T.BIGINT
            return call(canon, rt, args[0])
        if name == "from_unixtime":
            return call(
                "multiply", T.TIMESTAMP,
                _coerce_to(args[0], T.BIGINT), const(1_000_000, T.BIGINT),
            )
        if name == "concat_ws":
            sep = args[0]
            if not isinstance(sep, Constant):
                raise SemanticError("concat_ws separator must be a literal")
            # KNOWN DEVIATION: the reference SKIPS NULL arguments; this
            # desugar NULL-propagates like concat (see README deviations)
            parts: list[RowExpr] = []
            for i, a in enumerate(args[1:]):
                if i:
                    parts.append(sep)
                parts.append(a)
            return call("concat", T.VARCHAR, *parts)
        if name == "repeat":
            if isinstance(args[0], Constant) and isinstance(args[1], Constant):
                v, k = args[0].value, args[1].value
                return Constant(
                    type=T.VARCHAR,
                    value=None if v is None or k is None else str(v) * int(k),
                )
            return call("repeat", T.VARCHAR, *args)
        if name in _MATH_DOUBLE_FNS:
            return call(name, T.DOUBLE, _coerce_to(args[0], T.DOUBLE))
        if name == "log":
            # log(b, x) = ln(x)/ln(b)
            b = _coerce_to(args[0], T.DOUBLE)
            x = _coerce_to(args[1], T.DOUBLE)
            return call(
                "divide", T.DOUBLE, call("ln", T.DOUBLE, x), call("ln", T.DOUBLE, b)
            )
        if name == "atan2":
            return call(
                "atan2", T.DOUBLE,
                _coerce_to(args[0], T.DOUBLE), _coerce_to(args[1], T.DOUBLE),
            )
        if name == "pi":
            import math

            return Constant(type=T.DOUBLE, value=math.pi)
        if name == "e":
            import math

            return Constant(type=T.DOUBLE, value=math.e)
        if name == "sign":
            return call("sign", args[0].type, args[0])
        if name == "truncate":
            return call("truncate", args[0].type, _coerce_to(args[0], T.DOUBLE))
        if name in ("greatest", "least"):
            rt = args[0].type
            for a in args[1:]:
                rt = T.common_super_type(rt, a.type) or rt
            return call(name, rt, *[_coerce_to(a, rt) for a in args])
        if name == "chr":
            if isinstance(args[0], Constant):
                v = args[0].value
                return Constant(
                    type=T.VARCHAR, value=None if v is None else chr(int(v))
                )
            raise SemanticError("chr over non-constant values not supported")
        if name in ("codepoint", "ascii"):
            if isinstance(args[0], Constant):
                v = args[0].value
                return Constant(
                    type=T.BIGINT,
                    value=None if not v else ord(str(v)[0]),
                )
            return call("codepoint", T.BIGINT, args[0])
        if name == "regexp_like":
            if isinstance(args[0], Constant) and isinstance(args[1], Constant):
                import re as _re

                a, p = args[0].value, args[1].value
                v = (
                    None
                    if a is None or p is None
                    else _re.search(str(p), str(a)) is not None
                )
                return Constant(type=T.BOOLEAN, value=v)
            return call("regexp_like", T.BOOLEAN, *args)
        if name in ("regexp_replace", "regexp_extract"):
            # string->string: lowered host-side over the dictionary
            return call(name, T.VARCHAR, *args)
        if name == "date_trunc":
            if not isinstance(args[0], Constant):
                raise SemanticError("date_trunc unit must be a literal")
            return call("date_trunc", args[1].type, args[0], args[1])
        if name in ("current_date", "now", "current_timestamp", "localtimestamp"):
            import time as _time

            if name == "current_date":
                return Constant(
                    type=T.DATE, value=int(_time.time() // 86400)
                )
            return Constant(
                type=T.TIMESTAMP, value=int(_time.time() * 1_000_000)
            )
        if name == "format":
            # printf-style over constants only in v1
            if all(isinstance(a, Constant) for a in args):
                fmt = str(args[0].value)
                vals = [a.value for a in args[1:]]
                return Constant(type=T.VARCHAR, value=fmt % tuple(vals))
            raise SemanticError("format over non-constant values not supported")
        raise SemanticError(f"unknown function: {name}")

    def _binary(self, e: t.BinaryOp, rw) -> RowExpr:
        op = e.op
        if op in ("AND", "OR"):
            return special(op.lower(), T.BOOLEAN, rw(e.left), rw(e.right))
        left = rw(e.left)
        right = rw(e.right)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            name = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}[op]
            return _make_comparison(name, left, right)
        if op == "||":
            for a in (left, right):
                if not T.is_string(a.type) and a.type != T.UNKNOWN:
                    raise SemanticError(
                        "|| requires varchar operands (add a cast)"
                    )
            return call("concat", T.VARCHAR, left, right)
        # arithmetic, with date/interval special cases
        iv = None
        other = None
        if isinstance(left, Constant) and isinstance(left.value, t.IntervalLiteral):
            iv, other = left.value, right
        elif isinstance(right, Constant) and isinstance(right.value, t.IntervalLiteral):
            iv, other = right.value, left
        if iv is not None:
            sign = 1 if op == "+" else -1
            if isinstance(other.type, (T.DateType, T.TimestampType)):
                return _date_interval(other, iv, sign)
            raise SemanticError("interval arithmetic requires a date/timestamp")
        name = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide", "%": "modulus"}[op]
        rt = _arith_type(name, left.type, right.type)
        return call(name, rt, left, right)


# ==== helpers ==========================================================


def _literal(e: t.Literal) -> Constant:
    if e.kind == "null":
        return Constant(type=T.UNKNOWN, value=None)
    if e.kind == "boolean":
        return const(bool(e.value), T.BOOLEAN)
    if e.kind == "integer":
        # reference: integer literals are INTEGER when they fit 32 bits
        # (keeps decimal precision derivation narrow: INTEGER -> decimal(10,0))
        v = int(e.value)
        return const(v, T.INTEGER if -(2**31) <= v < 2**31 else T.BIGINT)
    if e.kind == "decimal":
        text = str(e.value)
        neg = text.startswith("-")
        digits = text.lstrip("-+")
        if "." in digits:
            whole, frac = digits.split(".")
        else:
            whole, frac = digits, ""
        scale = len(frac)
        precision = max(1, len(whole.lstrip("0")) + scale)
        unscaled = int((whole + frac) or "0") * (-1 if neg else 1)
        return const(unscaled, T.decimal(min(precision, 18), scale))
    if e.kind == "double":
        return const(float(e.value), T.DOUBLE)
    if e.kind == "string":
        return const(str(e.value), T.VARCHAR)
    if e.kind == "date":
        y, m, d = (int(x) for x in str(e.value).split("-"))
        return const(days_from_civil(y, m, d), T.DATE)
    if e.kind == "timestamp":
        import datetime

        s = str(e.value)
        dt = datetime.datetime.fromisoformat(s)
        epoch = datetime.datetime(1970, 1, 1)
        return const(int((dt - epoch).total_seconds() * 1_000_000), T.TIMESTAMP)
    raise SemanticError(f"unknown literal kind {e.kind}")


def _cast_string_constant(c: Constant, target: T.SqlType) -> Constant:
    s = str(c.value)
    if isinstance(target, T.DateType):
        y, m, d = (int(x) for x in s.split("-"))
        return const(days_from_civil(y, m, d), T.DATE)
    if isinstance(target, T.DecimalType):
        from decimal import Decimal

        return const(
            int(Decimal(s).scaleb(target.scale).to_integral_value()), target
        )
    if T.is_integer(target):
        return const(int(s), target)
    if isinstance(target, (T.DoubleType, T.RealType)):
        return const(float(s), target)
    if T.is_string(target):
        return const(s, target)
    raise SemanticError(f"cannot cast string literal to {target}")


def _arith_type(name: str, a: T.SqlType, b: T.SqlType) -> T.SqlType:
    if isinstance(a, (T.DoubleType,)) or isinstance(b, (T.DoubleType,)):
        return T.DOUBLE
    if isinstance(a, T.RealType) or isinstance(b, T.RealType):
        if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
            return T.DOUBLE
        return T.REAL
    da = a if isinstance(a, T.DecimalType) else None
    db = b if isinstance(b, T.DecimalType) else None
    if da or db:
        # integers join decimal arithmetic at their reference precision
        # (TypeCoercion: tinyint->3, smallint->5, integer->10, bigint->19)
        int_prec = {8: 3, 16: 5, 32: 10, 64: 19}
        if da is None:
            da = T.decimal(int_prec.get(getattr(a, "bits", 64), 19), 0)
        if db is None:
            db = T.decimal(int_prec.get(getattr(b, "bits", 64), 19), 0)
        # reference precision derivation (DecimalOperators), capped at 38
        if name in ("add", "subtract"):
            s = max(da.scale, db.scale)
            p = min(38, max(da.precision - da.scale, db.precision - db.scale) + s + 1)
            return T.decimal(p, s)
        if name == "multiply":
            s = da.scale + db.scale
            if s > 38:
                raise SemanticError("decimal multiply scale overflow (>38)")
            p = min(38, da.precision + db.precision)
            return T.decimal(p, s)
        if name in ("divide", "modulus"):
            s = max(da.scale, db.scale)
            p = min(38, da.precision + db.scale + max(0, db.scale - da.scale))
            return T.decimal(p, s)
    if T.is_integer(a) and T.is_integer(b):
        return T.common_super_type(a, b) or T.BIGINT
    if isinstance(a, T.DateType) and isinstance(b, T.DateType) and name == "subtract":
        return T.BIGINT  # date difference in days
    raise SemanticError(f"cannot apply {name} to {a}, {b}")


def _coerce_to(e: RowExpr, target: T.SqlType) -> RowExpr:
    if e.type == target:
        return e
    if isinstance(e, Constant) and e.type == T.UNKNOWN:
        return Constant(type=target, value=None)
    if isinstance(e, Constant) and T.is_string(e.type) and isinstance(target, T.DateType):
        return _cast_string_constant(e, target)
    if T.is_string(e.type) and T.is_string(target):
        return e  # varchar length variants share representation
    return call("cast", target, e)


def _coerce_pair(a: RowExpr, b: RowExpr) -> tuple[RowExpr, RowExpr]:
    if a.type == b.type:
        return a, b
    # date vs string literal: parse the literal
    if isinstance(a.type, T.DateType) and isinstance(b, Constant) and T.is_string(b.type):
        return a, _cast_string_constant(b, T.DATE)
    if isinstance(b.type, T.DateType) and isinstance(a, Constant) and T.is_string(a.type):
        return _cast_string_constant(a, T.DATE), b
    ct = T.common_super_type(a.type, b.type)
    if ct is None:
        raise SemanticError(f"cannot compare {a.type} and {b.type}")
    # decimals: comparisons rescale inside the kernel; avoid materializing casts
    if isinstance(ct, T.DecimalType):
        return a, b
    return _coerce_to(a, ct), _coerce_to(b, ct)


def _make_comparison(name: str, left: RowExpr, right: RowExpr) -> RowExpr:
    left, right = _coerce_pair(left, right)
    return call(name, T.BOOLEAN, left, right)


def _date_interval(operand: RowExpr, iv: t.IntervalLiteral, sign: int) -> RowExpr:
    amount = iv.value * iv.sign * sign
    if iv.unit == "day":
        delta = const(amount, T.BIGINT)
        return call("date_add_days", operand.type, operand, delta)
    if iv.unit in ("month", "year"):
        months = amount * (12 if iv.unit == "year" else 1)
        return call("date_add_months", operand.type, operand, const(months, T.BIGINT))
    raise SemanticError(f"interval unit {iv.unit} unsupported for dates")


def _split_conjuncts(e: t.Node) -> list[t.Node]:
    if isinstance(e, t.BinaryOp) and e.op == "AND":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _contains_aggregate(e: t.Node) -> bool:
    found = []
    _collect_aggregates(e, found)
    return bool(found)


_SUBQUERY_NODES = (t.ScalarSubquery, t.InSubquery, t.Exists, t.Query)


def _collect_windows(e: t.Node, out: list) -> None:
    if isinstance(e, _SUBQUERY_NODES):
        return  # subquery internals have their own scopes
    if isinstance(e, t.FunctionCall) and e.window is not None:
        out.append(e)
        return  # SQL forbids nested window functions
    for f in dataclasses.fields(e) if dataclasses.is_dataclass(e) else []:
        v = getattr(e, f.name)
        if isinstance(v, t.Node):
            _collect_windows(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node):
                    _collect_windows(item, out)
                elif isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node):
                            _collect_windows(sub, out)


# names treated as aggregate functions when not windowed
_DERIVED_AGGS = {
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "bool_and", "bool_or", "every",
    "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
    "checksum",
}
AGGREGATE_NAMES = {
    "sum", "count", "avg", "min", "max", "count_if", "approx_distinct",
    "arbitrary", "any_value", "array_agg",
} | _DERIVED_AGGS


def _collect_aggregates(e: t.Node, out: list) -> None:
    if isinstance(e, _SUBQUERY_NODES):
        return  # an aggregate inside a subquery aggregates the SUBQUERY
    if isinstance(e, t.FunctionCall):
        if e.name in AGGREGATE_NAMES and e.window is None:
            out.append(e)
            return
    for f in dataclasses.fields(e) if dataclasses.is_dataclass(e) else []:
        v = getattr(e, f.name)
        if isinstance(v, t.Node):
            _collect_aggregates(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node):
                    _collect_aggregates(item, out)
                elif isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node):
                            _collect_aggregates(sub, out)


def _coerce_constant_value(c: Constant, target: T.SqlType):
    if c.value is None:
        return None
    if isinstance(target, T.DecimalType) and isinstance(c.type, T.DecimalType):
        return c.value * 10 ** (target.scale - c.type.scale)
    if isinstance(target, T.DecimalType) and T.is_integer(c.type):
        return c.value * 10**target.scale
    if isinstance(target, (T.DoubleType, T.RealType)) and not isinstance(
        c.type, (T.DoubleType, T.RealType)
    ):
        if isinstance(c.type, T.DecimalType):
            return float(c.value) / c.type.unscale
        return float(c.value)
    return c.value


# ==== constant folding ==================================================


def _fold(e: RowExpr) -> RowExpr:
    """Host-side constant folding for date arithmetic and simple numeric ops
    (pushdown-friendly: `date '1994-01-01' + interval '1' year` becomes a
    plain date Constant)."""
    from trino_tpu.ir import transform

    def fn(node: RowExpr) -> RowExpr:
        if isinstance(node, Call) and all(
            isinstance(a, Constant) for a in node.args
        ):
            return _fold_call(node)
        return node

    return transform(e, fn)


def _fold_call(node: Call) -> RowExpr:
    args = node.args
    vals = [a.value for a in args]
    if any(v is None for v in vals):
        return Constant(type=node.type, value=None)
    try:
        if node.name == "cast":
            return _fold_cast(args[0], node.type) or node
        if node.name == "date_add_days":
            return const(int(vals[0]) + int(vals[1]), node.type)
        if node.name == "date_add_months":
            from trino_tpu.compiler import _civil_from_days
            import numpy as np

            y, m, d = _civil_from_days(np.asarray([int(vals[0])], dtype=np.int64))
            y, m, d = int(y[0]), int(m[0]), int(d[0])
            months_total = (y * 12 + (m - 1)) + int(vals[1])
            y2, m2 = divmod(months_total, 12)
            d2 = min(d, _days_in_month(y2, m2 + 1))
            return const(days_from_civil(y2, m2 + 1, d2), node.type)
        if node.name in ("add", "subtract", "multiply") and not isinstance(
            node.type, T.DecimalType
        ):
            if T.is_integer(node.type):
                a, b = int(vals[0]), int(vals[1])
                r = {"add": a + b, "subtract": a - b, "multiply": a * b}[node.name]
                import numpy as np

                info = np.iinfo(node.type.storage_dtype)
                if not (info.min <= r <= info.max):
                    # reference raises instead of wrapping
                    raise SemanticError(f"{node.type.name} overflow: {r}")
                return const(r, node.type)
            if isinstance(node.type, T.DoubleType):
                fa = _as_float(args[0])
                fb = _as_float(args[1])
                r = {"add": fa + fb, "subtract": fa - fb, "multiply": fa * fb}[node.name]
                return const(r, node.type)
        if node.name in ("add", "subtract", "multiply") and isinstance(
            node.type, T.DecimalType
        ):
            sa = args[0].type.scale if isinstance(args[0].type, T.DecimalType) else 0
            sb = args[1].type.scale if isinstance(args[1].type, T.DecimalType) else 0
            rs = node.type.scale
            a, b = int(vals[0]), int(vals[1])
            if node.name == "multiply":
                raw = a * b  # scale sa+sb
                return const(_rescale_int(raw, sa + sb, rs), node.type)
            av = _rescale_int(a, sa, rs)
            bv = _rescale_int(b, sb, rs)
            return const(av + bv if node.name == "add" else av - bv, node.type)
        if node.name == "negate":
            return const(-vals[0], node.type)
    except Exception:
        return node
    return node


def _fold_cast(src: Constant, target: T.SqlType) -> Optional[Constant]:
    """Fold CAST of a literal (storage-representation conversion)."""
    st = src.type
    v = src.value
    if st == target:
        return src
    if T.is_string(st):
        return _cast_string_constant(src, target)
    if isinstance(target, T.DecimalType):
        if isinstance(st, T.DecimalType):
            return const(_rescale_int(int(v), st.scale, target.scale), target)
        if T.is_integer(st):
            return const(int(v) * target.unscale, target)
        if isinstance(st, (T.DoubleType, T.RealType)):
            from decimal import Decimal

            return const(
                int(Decimal(str(float(v))).scaleb(target.scale).to_integral_value()),
                target,
            )
    if isinstance(target, (T.DoubleType, T.RealType)):
        if isinstance(st, T.DecimalType):
            return const(float(v) / st.unscale, target)
        return const(float(v), target)
    if T.is_integer(target):
        if isinstance(st, T.DecimalType):
            return const(_rescale_int(int(v), st.scale, 0), target)
        if isinstance(st, (T.DoubleType, T.RealType)):
            f = float(v)
            import math

            return const(int(math.floor(abs(f) + 0.5)) * (1 if f >= 0 else -1), target)
        return const(int(v), target)
    if isinstance(target, T.TimestampType) and isinstance(st, T.DateType):
        return const(int(v) * 86_400_000_000, target)
    if isinstance(target, T.DateType) and isinstance(st, T.TimestampType):
        return const(int(v) // 86_400_000_000, target)
    return None


def _as_float(c: Constant) -> float:
    if isinstance(c.type, T.DecimalType):
        return float(c.value) / c.type.unscale
    return float(c.value)


def _rescale_int(v: int, from_s: int, to_s: int) -> int:
    if to_s >= from_s:
        return v * 10 ** (to_s - from_s)
    f = 10 ** (from_s - to_s)
    half = f // 2
    return (v + half) // f if v >= 0 else -((-v + half) // f)


def _days_in_month(y: int, m: int) -> int:
    import calendar

    return calendar.monthrange(y, m)[1]


# shared AND-flattening helper (no OR factoring — decorrelation must see
# filters exactly as written)
from trino_tpu.planner.optimizer import _conjuncts_no_or as _conjuncts_of  # noqa: E402


_MATH_DOUBLE_FNS = {
    "ln", "log2", "log10", "exp", "sin", "cos", "tan", "asin", "acos",
    "atan", "sinh", "cosh", "tanh", "cbrt", "degrees", "radians",
}


def _unwrap_unnest(rel: t.Node):
    """(Unnest ast, alias, column_aliases) if rel is UNNEST (possibly
    aliased), else (None, None, ())."""
    if isinstance(rel, t.Unnest):
        return rel, None, ()
    if isinstance(rel, t.AliasedRelation) and isinstance(rel.relation, t.Unnest):
        return rel.relation, rel.alias.lower(), tuple(
            c.lower() for c in rel.column_aliases
        )
    return None, None, ()


def _expand_quantified(e: "t.QuantifiedComparison") -> t.Node:
    """Rewrite quantified comparisons (reference:
    QuantifiedComparisonExpression handling in SubqueryPlanner):
      = ANY  -> IN;    <> ALL -> NOT IN
      > ANY(S) -> > (SELECT min ...)   > ALL(S) -> > (SELECT max ...)
      < ANY(S) -> < (SELECT max ...)   < ALL(S) -> < (SELECT min ...)
    The min/max forms follow Trino's rewrite; with an empty subquery the
    comparison yields NULL (ANY: falsy — correct; ALL: should be TRUE —
    known deviation, documented)."""
    if e.op == "=" and e.quantifier == "ANY":
        return t.InSubquery(e.value, e.query, negated=False)
    if e.op == "<>" and e.quantifier == "ALL":
        return t.InSubquery(e.value, e.query, negated=True)
    if e.op in ("<", "<=", ">", ">="):
        descending = e.op in (">", ">=")
        agg = (
            ("min" if descending else "max")
            if e.quantifier == "ANY"
            else ("max" if descending else "min")
        )
        sub = t.Query(
            body=t.QuerySpec(
                select_items=(
                    t.SelectItem(
                        t.FunctionCall(agg, (t.Identifier(("__qc",)),))
                    ),
                ),
                distinct=False,
                from_=t.AliasedRelation(
                    t.SubqueryRelation(e.query), "__q", ("__qc",)
                ),
                where=None,
                group_by=(),
                having=None,
            ),
        )
        return t.BinaryOp(e.op, e.value, t.ScalarSubquery(sub))
    raise SemanticError(
        f"quantified comparison {e.op} {e.quantifier} is not supported"
    )
