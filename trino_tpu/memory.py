"""Hierarchical memory accounting + HBM-aware memory pool.

Reference: ``lib/trino-memory-context`` (``LocalMemoryContext.java:18``,
``AggregatedMemoryContext.java``) and ``core/trino-main/.../memory/``
(``MemoryPool.java``, ``LocalMemoryManager.java``,
``ClusterMemoryManager.java:89`` with ``LowMemoryKiller``).

TPU translation: the pool models device HBM (the scarce resource — v5e has
16 GiB/chip), not JVM heap. Contexts form node -> query -> pool (the
reference's operator->driver->pipeline->task chain collapses: our executor
materializes one plan node at a time). When a reservation cannot be
satisfied the engine first *revokes* (spills to host RAM via the
partitioned operators in :mod:`trino_tpu.spill`), then kills the largest
query (TotalReservationLowMemoryKiller policy).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional


class ExceededMemoryLimitError(Exception):
    """Reference: ``ExceededMemoryLimitException`` — kills the query, not
    the server."""


class MemoryPool:
    """Byte-accounted pool shared by queries (``memory/MemoryPool.java``)."""

    def __init__(self, capacity_bytes: int, name: str = "general"):
        self.name = name
        self.capacity = int(capacity_bytes)
        self._lock = threading.Lock()
        self._query_reserved: dict[str, int] = {}

    @property
    def reserved(self) -> int:
        with self._lock:
            return sum(self._query_reserved.values())

    @property
    def free_bytes(self) -> int:
        return self.capacity - self.reserved

    def query_reserved(self, query_id: str) -> int:
        with self._lock:
            return self._query_reserved.get(query_id, 0)

    def try_reserve(self, query_id: str, bytes_: int) -> bool:
        with self._lock:
            total = sum(self._query_reserved.values())
            if total + bytes_ > self.capacity:
                return False
            self._query_reserved[query_id] = (
                self._query_reserved.get(query_id, 0) + bytes_
            )
            return True

    def free(self, query_id: str, bytes_: int) -> None:
        with self._lock:
            cur = self._query_reserved.get(query_id, 0)
            nxt = max(0, cur - bytes_)
            if nxt:
                self._query_reserved[query_id] = nxt
            else:
                self._query_reserved.pop(query_id, None)

    def release_query(self, query_id: str) -> None:
        with self._lock:
            self._query_reserved.pop(query_id, None)

    def largest_query(self) -> Optional[str]:
        """TotalReservationLowMemoryKiller policy: pick the biggest."""
        with self._lock:
            if not self._query_reserved:
                return None
            return max(self._query_reserved, key=self._query_reserved.get)


@dataclasses.dataclass
class QueryMemoryContext:
    """Per-query context with a hard limit (``query_max_memory``).

    ``on_revoke`` is the spill hook: called once with the shortfall before
    failing (MemoryRevokingScheduler analog); it returns bytes it freed.
    """

    pool: MemoryPool
    query_id: str
    max_bytes: Optional[int] = None
    on_revoke: Optional[Callable[[int], int]] = None
    peak_bytes: int = 0

    def reserved(self) -> int:
        return self.pool.query_reserved(self.query_id)

    def reserve(self, bytes_: int, what: str = "") -> None:
        if bytes_ <= 0:
            return
        cur = self.reserved()
        if self.max_bytes is not None and cur + bytes_ > self.max_bytes:
            raise ExceededMemoryLimitError(
                f"Query exceeded memory limit of {self.max_bytes} bytes: "
                f"reserved={cur} request={bytes_}"
                + (f" at {what}" if what else "")
            )
        if not self.pool.try_reserve(self.query_id, bytes_):
            if self.on_revoke is not None:
                self.on_revoke(bytes_)
            if not self.pool.try_reserve(self.query_id, bytes_):
                raise ExceededMemoryLimitError(
                    f"Memory pool '{self.pool.name}' exhausted: "
                    f"capacity={self.pool.capacity} free={self.pool.free_bytes} "
                    f"request={bytes_}" + (f" at {what}" if what else "")
                )
        self.peak_bytes = max(self.peak_bytes, self.reserved())

    def free(self, bytes_: int) -> None:
        if bytes_ > 0:
            self.pool.free(self.query_id, bytes_)

    def close(self) -> None:
        self.pool.release_query(self.query_id)


def batch_nbytes(batch) -> int:
    """Device-resident footprint of a Batch (columns + validity + selection)."""
    import numpy as np

    total = 0
    for c in batch.columns:
        data = c.data
        itemsize = (
            data.dtype.itemsize if hasattr(data, "dtype") else 8
        )
        n = data.shape[0] if hasattr(data, "shape") and data.shape else 0
        total += n * itemsize
        if c.valid is not None:
            total += n  # bool mask
    if batch.sel is not None:
        total += batch.capacity
    return total


class ClusterMemoryManager:
    """Coordinator-side cluster-wide memory enforcement.

    Reference: ``memory/ClusterMemoryManager.java:89,104`` — workers report
    their pool state (here: piggybacked on the discovery announce), the
    coordinator aggregates reservations per query across every node, and
    when the cluster total exceeds the limit it kills the query with the
    largest total reservation (``TotalReservationLowMemoryKiller``).
    """

    def __init__(
        self,
        local_pool: MemoryPool,
        cluster_limit_bytes: int,
        kill_fn: Callable[[str, str], bool],
    ):
        self.local_pool = local_pool
        self.limit = int(cluster_limit_bytes)
        self.kill_fn = kill_fn  # (query_id, message) -> killed?
        self._lock = threading.Lock()
        self._nodes: dict[str, dict[str, int]] = {}  # node -> query -> bytes
        self.kills: list[str] = []  # query ids killed (observability)

    def update(self, node_id: str, memory_info: Optional[dict]) -> None:
        """Record one worker's per-query reservations and re-check."""
        if memory_info is None:
            return
        with self._lock:
            self._nodes[node_id] = {
                str(q): int(b)
                for q, b in (memory_info.get("queryReservations") or {}).items()
            }
        self.check()

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def cluster_reservations(self) -> dict[str, int]:
        """Per-query bytes summed over the coordinator + every worker."""
        totals: dict[str, int] = {}
        with self._lock:
            snapshots = list(self._nodes.values())
        for per_query in snapshots:
            for q, b in per_query.items():
                totals[q] = totals.get(q, 0) + b
        with self.local_pool._lock:
            for q, b in self.local_pool._query_reserved.items():
                totals[q] = totals.get(q, 0) + b
        return totals

    def check(self) -> Optional[str]:
        """Kill the largest query if the cluster total exceeds the limit.

        Returns the killed query id (None when under the limit)."""
        totals = self.cluster_reservations()
        used = sum(totals.values())
        if used <= self.limit or not totals:
            return None
        # walk candidates in descending reservation order until one kill
        # lands: the largest query may have already finished while its
        # reservations were still being reported by worker announces
        # (reference: TotalReservationLowMemoryKiller skips completed
        # queries and keeps looking for a live victim)
        for victim in sorted(totals, key=lambda q: totals[q], reverse=True):
            if victim in self.kills:
                continue
            message = (
                f"Query killed by the cluster memory manager: cluster "
                f"memory used {used} bytes exceeds the limit {self.limit} "
                f"bytes (this query reserved {totals[victim]} across the "
                f"cluster)"
            )
            if self.kill_fn(victim, message):
                self.kills.append(victim)
                return victim
        return None

    def info(self) -> dict:
        totals = self.cluster_reservations()
        return {
            "clusterMemoryLimitBytes": self.limit,
            "clusterReservedBytes": sum(totals.values()),
            "queryReservations": totals,
            "killedQueries": list(self.kills),
        }
