"""Global configuration and session properties.

Reference: Trino's session property system
(``core/trino-main/src/main/java/io/trino/SystemSessionProperties.java:50``)
and airlift ``@Config`` classes. Here: a plain dataclass of typed session
properties, overridable per query.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar

_X64_ENABLED = False


def enable_x64() -> None:
    """Enable 64-bit types in JAX.

    SQL semantics need int64 (BIGINT, scaled DECIMAL) and float64 (DOUBLE).
    TPUs emulate i64/f64; hot paths deliberately stay in i32/f32/bf16.
    """
    global _X64_ENABLED
    if not _X64_ENABLED:
        import jax

        jax.config.update("jax_enable_x64", True)
        _X64_ENABLED = True


@dataclasses.dataclass
class Session:
    """Per-query session (reference: ``io.trino.Session``).

    ``properties`` mirrors SET SESSION overrides
    (``SystemSessionProperties.java``); only properties our engine consults
    are defined, with typed defaults.
    """

    user: str = "user"
    catalog: str | None = "tpch"
    schema: str | None = "tiny"
    source: str = ""  # client-declared source (X-Trino-Source)
    properties: dict[str, Any] = dataclasses.field(default_factory=dict)
    # prepared statements (reference: Session.preparedStatements)
    prepared: dict[str, Any] = dataclasses.field(default_factory=dict)

    # --- defaults for recognised properties -------------------------------
    DEFAULTS: ClassVar[tuple[tuple[str, Any], ...]] = (
        ("join_distribution_type", "AUTOMATIC"),  # BROADCAST | PARTITIONED
        ("join_reordering_strategy", "AUTOMATIC"),
        ("task_concurrency", 1),
        ("batch_capacity", 1 << 16),  # padded kernel batch rows
        ("broadcast_join_threshold_rows", 1 << 22),
        # --- dense join tier (ops/dense_join.py) --------------------------
        # master switch for the open-addressing join engine: dense build
        # tables with graceful overflow (densejoin@ capacity sites), the
        # spill-cliff removal, and broadcast-link star-join fusion
        ("dense_join", True),
        # auto | sort | dense | matmul — auto picks dense for INNER/LEFT
        # equi-joins and escalates single-key dense-domain builds to the
        # binned (matmul) tier when PR-15 history proves the domain fits
        ("join_strategy", "auto"),
        # largest binned key domain the auto gate may promote to the
        # matmul tier (explicit join_strategy=matmul is not bounded)
        ("matmul_join_max_domain", 1 << 13),
        ("enable_dynamic_filtering", True),
        ("dynamic_filtering_max_build_rows", 1 << 20),
        ("query_max_memory_bytes", 8 << 30),
        ("spill_enabled", True),
        ("spill_partitions", 8),
        # rows above which join/group-by switch to partitioned host-spill
        ("spill_threshold_rows", 1 << 23),
        ("tpu_enabled", True),
        # plan sanity checkers after each optimizer stage, fragmentation,
        # and worker-side deserialization (reference PlanSanityChecker)
        ("plan_validation", True),
        ("execution_mode", "local"),  # local | distributed (mesh SPMD)
        # cluster worker tasks: 'fused' compiles the fragment onto the
        # worker's local devices; 'interpreter' forces the CPU fallback
        ("worker_execution", "fused"),
        # stage launch order: all-at-once | phased (build-before-probe;
        # reference AllAtOnceExecutionPolicy / PhasedExecutionPolicy)
        ("execution_policy", "all-at-once"),
        # distributed writer tasks over shared-storage connectors
        # (ScaledWriterScheduler analog; see Engine._scaled_insert ADR)
        ("scaled_writers", False),
        ("writer_target_bytes", 32 << 20),
        # streaming scans (Driver-loop analog): scan->agg fragments whose
        # table exceeds the threshold run as a chunk loop with carried
        # accumulators instead of materializing the table on device
        ("stream_scan_threshold_rows", 1 << 22),
        ("stream_chunk_rows", 1 << 20),
        # device-resident streaming: connectors that can stage a table
        # into HBM (memory connector) stream it via in-program
        # dynamic_slice chunks; cap on staged bytes per table
        ("stream_device_cache_bytes", 4 << 30),
        # 2M rows: the in-loop int64 cumsum's reduce-window must fit
        # scoped vmem (16MB on v5e; 4M-row chunks exceed it)
        ("stream_device_chunk_rows", 1 << 21),
        # initial per-shard group budget for streamed aggregation (grows
        # on overflow)
        ("stream_group_budget", 1 << 12),
        # distributed mode: compile each plan fragment into one SPMD
        # program (exec/fragments.py); off -> materialized interpreter
        ("fragment_execution", True),
        # --- whole-pipeline fusion (planner/fragmenter.py fuse_groups) ----
        # compile chains of fragments connected by eligible HASH (and
        # gather) exchanges into ONE jitted program with the repartition
        # collectives inside the jit, instead of one dispatch per
        # fragment; ineligible links fall back to the per-fragment path
        # bit-identically
        ("pipeline_fusion", True),
        # cap on fragments per fused program (bounds compile time and
        # scoped-vmem pressure of the merged XLA program)
        ("fusion_max_fragments", 8),
        # --- fault tolerance (trino_tpu/ft/) ------------------------------
        # NONE | TASK | QUERY (reference: io.trino.execution.RetryPolicy).
        # TASK re-dispatches a failed fragment attempt to another worker
        # over retained (materialized) exchange output; QUERY re-runs the
        # whole statement on a fresh attempt id.
        ("retry_policy", "NONE"),
        ("task_retry_attempts", 4),  # total attempts per task (incl. first)
        ("query_retry_attempts", 3),  # total attempts per query (incl. first)
        ("retry_initial_delay_ms", 100),
        ("retry_max_delay_ms", 2000),
        # spooled exchange (trino_tpu/exchange/spool.py): under TASK
        # retry, workers asynchronously copy finished output-buffer pages
        # to a coordinator-hosted spool store, so a producer's death
        # recovers by re-pointing consumers at the spool (level=task) or
        # re-executing only the lost producers (level=lineage) instead of
        # falling back to a QUERY retry
        ("exchange_spooling", False),
        ("spool_dir", ""),  # "" = host-RAM backend; path = local disk
        ("spool_max_bytes", 256 << 20),
        # deterministic fault injection (chaos testing; ft/injection.py):
        # all probabilities zero -> injection fully disabled
        ("fault_injection_seed", 0),
        ("fault_task_crash_p", 0.0),
        ("fault_http_drop_p", 0.0),
        ("fault_http_delay_ms", 0),
        # delay faults: deterministic per-node slowdowns at task-execute
        # sites so chaos tests can manufacture stragglers. fault_slow_workers
        # is a comma-separated node-id list ("" = every node once a delay
        # fault is configured); stall is a fixed pre-execute sleep, factor
        # scales the measured execution time (10.0 -> a 10x-slow worker)
        ("fault_slow_workers", ""),
        ("fault_task_stall_ms", 0),
        ("fault_task_slow_factor", 1.0),
        # worker-death faults: once a task at fault site
        # "task:{fragment}.{partition}" finishes on a matching node
        # (fault_worker_exit_node, "" = any), the worker process exits
        # hard (os._exit) after fault_worker_exit_delay_ms — simulating
        # SIGKILL for spool/lineage recovery tests. "" site = disabled.
        ("fault_worker_exit_node", ""),
        ("fault_worker_exit_site", ""),
        ("fault_worker_exit_delay_ms", 0),
        # --- speculative (hedged) task execution (server/cluster.py) ------
        # under retry_policy=TASK: when a running attempt's elapsed exceeds
        # max(floor, multiplier * p99 of completed siblings), dispatch one
        # duplicate on a different healthy node; first finisher wins, the
        # loser is cancelled (token-acked buffers dedupe delivery)
        ("speculation", False),
        ("speculation_floor_ms", 500),
        ("speculation_multiplier", 2.0),
        # cap on concurrent speculative attempts per query, as a fraction
        # of the query's planned task count (min 1 when speculation is on)
        ("speculation_max_fraction", 0.25),
        # --- internal HTTP tuning (chaos tests shrink these) --------------
        ("http_request_timeout_s", 30.0),  # task POST/GET/DELETE calls
        ("http_retry_attempts", 3),  # transient-error retries per request
        ("exchange_timeout_s", 300.0),  # total page-exchange read budget
        ("exchange_poll_s", 15.0),  # server-side long-poll hold per GET
        # per-task output buffer cap; TASK retry retains delivered pages
        # (materialized exchange), so give it headroom
        ("exchange_buffer_bytes", 64 << 20),
        # --- skew-aware exchange (ops/skew.py, parallel/exchange.py) ------
        # detect heavy-hitter join keys and route them on a salted path
        # (hot build keys replicated, hot probe rows kept local)
        ("skew_handling", True),
        # seed _Caps defaults from planner/stats.py estimates per
        # exchange/join/agg site (provenance recorded in /v1/query)
        ("stats_capacity_seeding", True),
        ("skew_hot_k", 16),  # top-k candidates per shard in the sketch
        # hot iff global count > frac * (total_rows / n_shards)
        ("skew_hot_threshold_frac", 0.5),
        # --- cross-query program cache (planner/canonicalize.py) ----------
        # share compiled fragment programs across statements under a
        # canonical-plan fingerprint (ExpressionCompiler CacheKey analog);
        # off -> every statement plans and traces from scratch
        ("program_cache", True),
        # hoist non-structural literals out of the plan into the jit
        # parameter vector so `x < 24` and `x < 25` share one traced
        # program; off -> literals bake into the trace (old behavior)
        ("constant_hoisting", True),
        # --- device-level profiling (obs/profiler.py) ---------------------
        # capture XLA cost_analysis/memory_analysis per compiled fragment
        # program (AOT lower+compile of the SAME jitted function, so query
        # results are bit-identical on or off); deliberately NOT part of
        # the canonical-plan fingerprint (planner/canonicalize.py) for the
        # same reason
        ("device_profiling", True),
        # --- columnar ingest tier (trino_tpu/ingest.py) --------------------
        # decode host columns with the native C hot loops when the shared
        # library built; off -> pure-Python/numpy fallback (bit-identical)
        ("native_decode", True),
        # two-slot double-buffered split decode: a background thread
        # decodes split k+1 while the device executes over split k
        ("ingest_prefetch", True),
        # pack every column of a shard into one contiguous uint32 staging
        # arena and issue a single H2D transfer per device (sliced back
        # into columns on-device), amortizing the per-transfer DMA floor;
        # off -> per-column device_put (bit-identical)
        ("coalesced_h2d", True),
        # below this many raw bytes a scan stays per-column even with
        # coalesced_h2d on: cold scans are unpack-program-cold too, and a
        # few DMA floors cost less than the first-touch XLA compile
        ("coalesce_min_bytes", 1 << 23),
        # device-resident table cache: keep scanned tables HBM-resident
        # across queries keyed by (catalog, table, version, projection,
        # splits); warm repeat scans issue zero H2D bytes
        ("table_cache", True),
        ("table_cache_max_bytes", 1 << 30),
        # --- semantic result cache (trino_tpu/cache/result_cache.py) -------
        # coordinator-level final-result reuse keyed by (canonical plan
        # fingerprint, hoisted-param vector, per-catalog data versions,
        # ACL generation): a warm repeat returns in microseconds with zero
        # device dispatches. Off by default — serving tiers opt in per
        # session (existing warm-repeat tests assert real executions).
        ("result_cache", False),
        ("result_cache_max_bytes", 64 << 20),
        # on an append-only data_versions() delta, re-execute the cached
        # aggregation plan over ONLY the new parts and merge partial
        # aggregates into the cached rows instead of invalidating;
        # non-maintainable shapes invalidate as before
        ("incremental_maintenance", True),
        # --- cross-query device batching (exec/batching.py) ----------------
        # hold compatible queries (same canonical-plan fingerprint,
        # differing only in hoisted literals) for a short window and
        # execute ONE stacked dispatch through the cached program,
        # demultiplexing K result sets — bit-identical to K sequential
        # runs. 0 disables collection entirely (today's behavior).
        ("batch_window_ms", 0),
        # flush a collecting batch early once this many members joined
        ("batch_max_size", 16),
        # --- query history (obs/history.py) --------------------------------
        # record per-fingerprint observed execution truth (final
        # capacities, overflow retries, peak HBM, elapsed, ...) and seed
        # warm repeats from it; bit-identical on/off
        ("query_history", True),
        # where the history JSON lives; "" keeps the store in-memory only
        # (per-process) — set a directory to survive restarts and share
        # across engines
        ("history_dir", ""),
        ("history_max_entries", 256),
        ("history_max_bytes", 1 << 20),
        # retained terminal queries in the coordinator QueryManager
        # (satellite of the same observability story: coordinator memory
        # under sustained traffic)
        ("query_manager_max_history", 100),
        # --- operator telemetry (exec/fragments.py tracer) ------------------
        # per-operator input/output row counters minted inside the traced
        # program (scan/filter/join/agg/exchange), riding the existing
        # deferred-counter pull: zero extra D2H round trips, bit-identical
        # results on/off. Unlike device_profiling this IS part of the
        # canonical-plan fingerprint — the extra reductions change the
        # compiled program.
        ("operator_stats", True),
        # --- flight recorder (obs/flight.py) --------------------------------
        # crash-safe on-disk journal of query lifecycle events; "" disables
        # journaling (tier-1 default: no cross-process state)
        ("flight_dir", ""),
        ("flight_max_bytes", 16 << 20),
        ("flight_segment_bytes", 1 << 20),
        # --- SLO regression sentinel (obs/slo.py) ---------------------------
        # absolute elapsed-time SLO per query in ms; 0 = no absolute SLO
        # (history-relative regressions still fire)
        ("slo_elapsed_ms", 0.0),
        # a completion regresses when elapsed > multiplier * the
        # fingerprint's history p50 baseline (severe at severe_multiplier),
        # once the baseline holds at least slo_min_samples samples
        ("slo_regression_multiplier", 2.0),
        ("slo_severe_multiplier", 4.0),
        ("slo_min_samples", 3),
    )

    def get(self, name: str) -> Any:
        if name in self.properties:
            return self.properties[name]
        for key, default in self.DEFAULTS:
            if key == name:
                return default
        raise KeyError(f"unknown session property: {name}")

    def set(self, name: str, value: Any) -> None:
        self.properties[name] = value


@dataclasses.dataclass
class ServerConfig:
    """Front-door (HTTP serving tier) knobs, analogous to airlift's
    ``HttpServerConfig`` + Trino's ``QueryManagerConfig`` client-timeout.

    These govern the serving edge — connection budgets, shedding, result
    paging — not query semantics, so they live apart from ``Session``.
    """

    # Global ceiling on requests concurrently occupying blocking-pool
    # workers; excess requests shed with 503 + Retry-After.
    max_inflight_requests: int = 256
    # Per-tenant (X-Trino-User) statement-submission rate limit; 0 = off.
    tenant_rate_limit_qps: float = 0.0
    tenant_rate_limit_burst: float = 16.0
    # A query whose nextUri goes unpolled this long is canceled and its
    # admission slot freed (reference: Trino query.client.timeout).
    client_timeout_s: float = 120.0
    # Byte budget per result page served off the streaming pager; <= 0
    # falls back to fixed row-count pages over the materialized result.
    result_page_max_bytes: int = 1 << 20
    # Outbound intra-cluster HTTP calls (announce, drain spool push).
    http_request_timeout_s: float = 10.0
    # Serving-edge socket hygiene.
    read_timeout_s: float = 30.0       # slowloris: max time to frame a request
    idle_timeout_s: float = 300.0      # keep-alive connections with no traffic
    write_timeout_s: float = 60.0      # peer stopped draining a response
    max_connections: int = 4096
    blocking_pool_size: int = 16
    # Graceful drain.
    drain_timeout_s: float = 120.0     # worker: max wait for running tasks
    drain_grace_s: float = 0.5         # coordinator: settle time before stop
    spool_finish_timeout_s: float = 30.0
    # Retry-After hint attached to shed responses.
    shed_retry_after_s: float = 1.0
