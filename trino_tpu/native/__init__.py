"""Native host tier: ctypes bindings for native/columnar.cpp.

Compiles the shared library on first import (g++ -O3 -shared -fPIC,
rebuilt when the source changes) and exposes numpy-friendly wrappers.
Every function has a pure-NumPy fallback so the engine works without a
toolchain (``NATIVE_AVAILABLE`` reports which path is active).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "..", "..", "native", "columnar.cpp")
_LIB: Optional[ctypes.CDLL] = None
NATIVE_AVAILABLE = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(tempfile.gettempdir(), "trino_tpu_native")
    os.makedirs(cache_dir, exist_ok=True)
    lib_path = os.path.join(cache_dir, f"columnar_{digest}.so")
    if not os.path.exists(lib_path):
        tmp = lib_path + f".tmp{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, src],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, lib_path)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    i64, u8p, i64p, i32p, u64p = (
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint64),
    )
    lib.tt_dict_encode.restype = i64
    lib.tt_dict_encode.argtypes = [ctypes.c_char_p, i64p, i64, i32p, i64p]
    lib.tt_varint_encode.restype = i64
    lib.tt_varint_encode.argtypes = [i64p, i64, u8p]
    lib.tt_varint_decode.restype = i64
    lib.tt_varint_decode.argtypes = [u8p, i64, i64, i64p]
    lib.tt_rle_encode.restype = i64
    lib.tt_rle_encode.argtypes = [i64p, i64, u8p]
    lib.tt_rle_decode.restype = i64
    lib.tt_rle_decode.argtypes = [u8p, i64, i64, i64p]
    lib.tt_bitpack_encode.restype = i64
    lib.tt_bitpack_encode.argtypes = [u64p, i64, ctypes.c_int32, u8p]
    lib.tt_bitpack_decode.restype = None
    lib.tt_bitpack_decode.argtypes = [u8p, i64, ctypes.c_int32, u64p]
    lib.tt_lz_compress.restype = i64
    lib.tt_lz_compress.argtypes = [u8p, i64, u8p]
    lib.tt_lz_decompress.restype = i64
    lib.tt_lz_decompress.argtypes = [u8p, i64, u8p, i64]
    lib.tt_snappy_decompress.restype = i64
    lib.tt_snappy_decompress.argtypes = [u8p, i64, u8p, i64]
    lib.tt_tpch_textpool.restype = i64
    lib.tt_tpch_textpool.argtypes = [u8p, i64, u8p, i64, i64]
    lib.tt_orc_rle2.restype = i64
    lib.tt_orc_rle2.argtypes = [u8p, i64, i64, ctypes.c_int32, i64p]
    lib.tt_orc_rle1.restype = i64
    lib.tt_orc_rle1.argtypes = [u8p, i64, i64, ctypes.c_int32, i64p]
    lib.tt_orc_byte_rle.restype = i64
    lib.tt_orc_byte_rle.argtypes = [u8p, i64, i64, u8p]
    lib.tt_orc_decimal64.restype = i64
    lib.tt_orc_decimal64.argtypes = [u8p, i64, i64, i64p]
    lib.tt_orc_rle2_encode.restype = i64
    lib.tt_orc_rle2_encode.argtypes = [i64p, i64, ctypes.c_int32, u8p]
    lib.tt_orc_byte_rle_encode.restype = i64
    lib.tt_orc_byte_rle_encode.argtypes = [u8p, i64, u8p]
    lib.tt_orc_varint_encode.restype = i64
    lib.tt_orc_varint_encode.argtypes = [u64p, i64, u8p]
    lib.tt_snappy_compress.restype = i64
    lib.tt_snappy_compress.argtypes = [u8p, i64, u8p]
    lib.tt_parquet_rle_decode.restype = i64
    lib.tt_parquet_rle_decode.argtypes = [u8p, i64, ctypes.c_int32, i64, i32p]
    lib.tt_parquet_rle_encode.restype = i64
    lib.tt_parquet_rle_encode.argtypes = [i32p, i64, ctypes.c_int32, u8p]
    lib.tt_pack_arena.restype = i64
    lib.tt_pack_arena.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), i64p, i64, u8p, i64,
    ]
    return lib


_LIB = _build_and_load()
NATIVE_AVAILABLE = _LIB is not None


import contextlib


@contextlib.contextmanager
def python_fallback():
    """Force every wrapper through its pure-Python path for the duration
    (session prop ``native_decode=false``; the decode parity tests).
    Flips the module-level handle, so native calls on OTHER threads also
    fall back while held — safe (fallbacks are bit-identical), just
    slower."""
    global _LIB
    saved, _LIB = _LIB, None
    try:
        yield
    finally:
        _LIB = saved


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# === dictionary encode ======================================================


def dict_encode(strings: Sequence[str]) -> tuple[np.ndarray, list[str]]:
    """codes (int32) + unique values in first-seen order."""
    n = len(strings)
    if n == 0:
        return np.zeros(0, dtype=np.int32), []
    if _LIB is not None:
        offsets = np.zeros(n + 1, dtype=np.int64)
        pos = 0
        enc = [s.encode("utf-8", "surrogatepass") for s in strings]
        blob = b"".join(enc)
        for i, e in enumerate(enc):
            offsets[i] = pos
            pos += len(e)
        offsets[n] = pos
        codes = np.empty(n, dtype=np.int32)
        first = np.empty(n, dtype=np.int64)
        n_unique = _LIB.tt_dict_encode(
            blob,
            _ptr(offsets, ctypes.c_int64),
            n,
            _ptr(codes, ctypes.c_int32),
            _ptr(first, ctypes.c_int64),
        )
        uniques = [strings[first[j]] for j in range(n_unique)]
        return codes, uniques
    # fallback
    index: dict[str, int] = {}
    codes = np.empty(n, dtype=np.int32)
    uniques: list[str] = []
    for i, s in enumerate(strings):
        c = index.get(s)
        if c is None:
            c = len(uniques)
            index[s] = c
            uniques.append(s)
        codes[i] = c
    return codes, uniques


# === integer codecs =========================================================


def varint_encode(values: np.ndarray) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.int64)
    n = len(values)
    if n == 0:
        return b""
    if _LIB is not None:
        out = np.empty(10 * n, dtype=np.uint8)
        ln = _LIB.tt_varint_encode(
            _ptr(values, ctypes.c_int64), n, _ptr(out, ctypes.c_uint8)
        )
        return out[:ln].tobytes()
    # fallback: delta + zigzag varint in python
    out = bytearray()
    prev = 0
    for v in values.tolist():
        u = ((v - prev) << 1) ^ ((v - prev) >> 63) if (v - prev) < 0 else (v - prev) << 1
        u &= (1 << 64) - 1
        prev = v
        while u >= 0x80:
            out.append((u & 0x7F) | 0x80)
            u >>= 7
        out.append(u)
    return bytes(out)


def varint_decode(data: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    if _LIB is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(n, dtype=np.int64)
        rc = _LIB.tt_varint_decode(
            _ptr(buf, ctypes.c_uint8), len(buf), n, _ptr(out, ctypes.c_int64)
        )
        if rc < 0:
            raise ValueError("corrupt varint page")
        return out
    out = np.empty(n, dtype=np.int64)
    pos = 0
    prev = 0
    for i in range(n):
        u = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            u |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        d = (u >> 1) ^ -(u & 1)
        prev += d
        out[i] = prev
    return out


def rle_encode(values: np.ndarray) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.int64)
    n = len(values)
    if n == 0:
        return b""
    if _LIB is not None:
        out = np.empty(20 * n + 16, dtype=np.uint8)
        ln = _LIB.tt_rle_encode(
            _ptr(values, ctypes.c_int64), n, _ptr(out, ctypes.c_uint8)
        )
        return out[:ln].tobytes()
    out = bytearray()
    i = 0
    vals = values.tolist()
    while i < n:
        run = 1
        while i + run < n and vals[i + run] == vals[i]:
            run += 1
        for u in (run, (vals[i] << 1) ^ (vals[i] >> 63) if vals[i] < 0 else vals[i] << 1):
            u &= (1 << 64) - 1
            while u >= 0x80:
                out.append((u & 0x7F) | 0x80)
                u >>= 7
            out.append(u)
        i += run
    return bytes(out)


def rle_decode(data: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    if _LIB is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(n, dtype=np.int64)
        rc = _LIB.tt_rle_decode(
            _ptr(buf, ctypes.c_uint8), len(buf), n, _ptr(out, ctypes.c_int64)
        )
        if rc < 0:
            raise ValueError("corrupt RLE page")
        return out
    out = np.empty(n, dtype=np.int64)
    pos = 0
    i = 0
    while i < n:
        parts = []
        for _ in range(2):
            u = 0
            shift = 0
            while True:
                b = data[pos]
                pos += 1
                u |= (b & 0x7F) << shift
                if not (b & 0x80):
                    break
                shift += 7
            parts.append(u)
        run, u = parts
        v = (u >> 1) ^ -(u & 1)
        for _ in range(run):
            if i < n:
                out[i] = v
                i += 1
    return out


def bitpack_encode(values: np.ndarray, width: int) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.uint64)
    n = len(values)
    if n == 0 or width == 0:
        return b""
    if _LIB is not None:
        out = np.zeros((n * width + 7) // 8, dtype=np.uint8)
        _LIB.tt_bitpack_encode(
            _ptr(values, ctypes.c_uint64), n, width, _ptr(out, ctypes.c_uint8)
        )
        return out.tobytes()
    bits = np.zeros(n * width, dtype=np.uint8)
    for b in range(width):
        bits[b::width] = (values >> np.uint64(b)) & np.uint64(1)
    return np.packbits(bits, bitorder="little").tobytes()


def bitpack_decode(data: bytes, n: int, width: int) -> np.ndarray:
    if n == 0 or width == 0:
        return np.zeros(n, dtype=np.uint64)
    if _LIB is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(n, dtype=np.uint64)
        _LIB.tt_bitpack_decode(
            _ptr(buf, ctypes.c_uint8), n, width, _ptr(out, ctypes.c_uint64)
        )
        return out
    bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8), bitorder="little")
    bits = bits[: n * width].reshape(n, width).astype(np.uint64)
    out = np.zeros(n, dtype=np.uint64)
    for b in range(width):
        out |= bits[:, b] << np.uint64(b)
    return out


def snappy_decompress(data: bytes, expected_len: int) -> bytes:
    """Snappy block format (Parquet's default codec). Python fallback
    implements the same tagged literal/copy stream."""
    if not data:
        return b""
    if _LIB is not None:
        inp = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(max(expected_len, 1), dtype=np.uint8)
        ln = _LIB.tt_snappy_decompress(
            _ptr(inp, ctypes.c_uint8), len(data), _ptr(out, ctypes.c_uint8),
            max(expected_len, 1),
        )
        if ln < 0:
            raise ValueError("corrupt snappy page")
        return out[:ln].tobytes()
    # pure-python fallback
    ip = 0
    ulen = 0
    shift = 0
    while True:
        b = data[ip]
        ip += 1
        ulen |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    out = bytearray()
    n = len(data)
    while ip < n:
        tag = data[ip]
        ip += 1
        kind = tag & 3
        if kind == 0:
            ln = (tag >> 2) + 1
            if (tag >> 2) >= 60:
                nb = (tag >> 2) - 59
                ln = int.from_bytes(data[ip : ip + nb], "little") + 1
                ip += nb
            out += data[ip : ip + ln]
            ip += ln
        else:
            if kind == 1:
                ln = ((tag >> 2) & 7) + 4
                off = ((tag >> 5) << 8) | data[ip]
                ip += 1
            elif kind == 2:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[ip : ip + 2], "little")
                ip += 2
            else:
                ln = (tag >> 2) + 1
                off = int.from_bytes(data[ip : ip + 4], "little")
                ip += 4
            for _ in range(ln):
                out.append(out[-off])
    return bytes(out)


def snappy_compress(data: bytes) -> bytes:
    """Literal-only snappy stream (valid for any decoder)."""
    if _LIB is not None and data:
        inp = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(len(data) + len(data) // 64 + 32, dtype=np.uint8)
        ln = _LIB.tt_snappy_compress(
            _ptr(inp, ctypes.c_uint8), len(data), _ptr(out, ctypes.c_uint8)
        )
        return out[:ln].tobytes()
    out = bytearray()
    ulen = len(data)
    while ulen >= 0x80:
        out.append((ulen & 0x7F) | 0x80)
        ulen >>= 7
    out.append(ulen)
    ip = 0
    while ip < len(data):
        chunk = min(len(data) - ip, 65536)
        ln = chunk - 1
        if ln < 60:
            out.append(ln << 2)
        else:
            out.append(61 << 2)  # 61 => two length bytes
            out += (ln).to_bytes(2, "little")
        out += data[ip : ip + chunk]
        ip += chunk
    return bytes(out)


def parquet_rle_decode(data: bytes, bit_width: int, n: int) -> np.ndarray:
    """Parquet RLE/bit-packed hybrid (def levels, dictionary indices)."""
    if n == 0:
        return np.zeros(0, dtype=np.int32)
    if bit_width == 0:
        return np.zeros(n, dtype=np.int32)
    if _LIB is not None:
        buf = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(n, dtype=np.int32)
        rc = _LIB.tt_parquet_rle_decode(
            _ptr(buf, ctypes.c_uint8), len(buf), bit_width, n,
            _ptr(out, ctypes.c_int32),
        )
        if rc < 0:
            raise ValueError("corrupt parquet RLE run")
        return out
    out = np.empty(n, dtype=np.int32)
    ip = 0
    op = 0
    byte_width = (bit_width + 7) // 8
    while op < n and ip < len(data):
        header = 0
        shift = 0
        while True:
            b = data[ip]
            ip += 1
            header |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if header & 1:
            count = (header >> 1) * 8
            acc = 0
            acc_bits = 0
            mask = (1 << bit_width) - 1
            for _ in range(count):
                while acc_bits < bit_width and ip < len(data):
                    acc |= data[ip] << acc_bits
                    ip += 1
                    acc_bits += 8
                if op < n:
                    out[op] = acc & mask
                    op += 1
                acc >>= bit_width
                acc_bits -= bit_width
        else:
            count = header >> 1
            v = int.from_bytes(data[ip : ip + byte_width], "little")
            ip += byte_width
            for _ in range(count):
                if op < n:
                    out[op] = v
                    op += 1
    return out


def parquet_rle_encode(values: np.ndarray, bit_width: int) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.int32)
    n = len(values)
    if n == 0:
        return b""
    if _LIB is not None:
        out = np.empty(n * 8 + 16, dtype=np.uint8)
        ln = _LIB.tt_parquet_rle_encode(
            _ptr(values, ctypes.c_int32), n, bit_width, _ptr(out, ctypes.c_uint8)
        )
        return out[:ln].tobytes()
    out = bytearray()
    byte_width = (bit_width + 7) // 8
    i = 0
    vals = values.tolist()
    while i < n:
        j = i
        while j < n and vals[j] == vals[i]:
            j += 1
        header = (j - i) << 1
        while header >= 0x80:
            out.append((header & 0x7F) | 0x80)
            header >>= 7
        out.append(header)
        out += int(vals[i] & 0xFFFFFFFF).to_bytes(4, "little")[:byte_width]
        i = j
    return bytes(out)


def lz_compress(data: bytes) -> bytes:
    if not data:
        return b""
    if _LIB is not None:
        inp = np.frombuffer(data, dtype=np.uint8)
        # worst case: all literals -> n + n/128 + 1 token bytes
        out = np.empty(len(data) + len(data) // 128 + 16, dtype=np.uint8)
        ln = _LIB.tt_lz_compress(
            _ptr(inp, ctypes.c_uint8), len(data), _ptr(out, ctypes.c_uint8)
        )
        return out[:ln].tobytes()
    import zlib

    return zlib.compress(data, 1)


def lz_decompress(data: bytes, expected_len: int) -> bytes:
    if not data:
        return b""
    if _LIB is not None:
        inp = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(expected_len, dtype=np.uint8)
        ln = _LIB.tt_lz_decompress(
            _ptr(inp, ctypes.c_uint8), len(data), _ptr(out, ctypes.c_uint8),
            expected_len,
        )
        if ln < 0:
            raise ValueError("corrupt compressed page")
        return out[:ln].tobytes()
    import zlib

    return zlib.decompress(data)


def tpch_textpool(size: int, dists_blob: bytes, seed: int) -> np.ndarray:
    """Generate the dbgen grammar text pool (uint8 array of `size`).

    Native path is ~1s for the spec's 300MB pool; the Python fallback is
    the same algorithm (slow — callers cache the pool on disk either way).
    """
    if _LIB is not None:
        out = np.empty(size, dtype=np.uint8)
        blob = np.frombuffer(dists_blob, dtype=np.uint8)
        ln = _LIB.tt_tpch_textpool(
            _ptr(out, ctypes.c_uint8), size,
            _ptr(blob, ctypes.c_uint8), len(dists_blob), seed,
        )
        if ln != size:
            raise ValueError("text pool generation failed")
        return out
    from trino_tpu.connectors.dbgen import textpool_python

    return textpool_python(size, dists_blob, seed)


def orc_rle2(data: bytes, count: int, signed: bool) -> Optional[np.ndarray]:
    """ORC RLEv2 integer decode (None -> caller uses the Python path)."""
    if _LIB is None or count == 0:
        return None if _LIB is None else np.zeros(0, dtype=np.int64)
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    rc = _LIB.tt_orc_rle2(
        _ptr(buf, ctypes.c_uint8), len(buf), count, int(signed),
        _ptr(out, ctypes.c_int64),
    )
    if rc < 0:
        raise ValueError("corrupt ORC RLEv2 stream")
    return out


def orc_rle1(data: bytes, count: int, signed: bool) -> Optional[np.ndarray]:
    if _LIB is None or count == 0:
        return None if _LIB is None else np.zeros(0, dtype=np.int64)
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    rc = _LIB.tt_orc_rle1(
        _ptr(buf, ctypes.c_uint8), len(buf), count, int(signed),
        _ptr(out, ctypes.c_int64),
    )
    if rc < 0:
        raise ValueError("corrupt ORC RLEv1 stream")
    return out


def orc_byte_rle(data: bytes, count: int) -> Optional[np.ndarray]:
    if _LIB is None or count == 0:
        return None if _LIB is None else np.zeros(0, dtype=np.uint8)
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.uint8)
    rc = _LIB.tt_orc_byte_rle(
        _ptr(buf, ctypes.c_uint8), len(buf), count, _ptr(out, ctypes.c_uint8)
    )
    if rc < 0:
        raise ValueError("corrupt ORC byte-RLE stream")
    return out


def orc_decimal64(data: bytes, count: int) -> Optional[np.ndarray]:
    if _LIB is None or count == 0:
        return None if _LIB is None else np.zeros(0, dtype=np.int64)
    buf = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    rc = _LIB.tt_orc_decimal64(
        _ptr(buf, ctypes.c_uint8), len(buf), count, _ptr(out, ctypes.c_int64)
    )
    if rc < 0:
        raise ValueError("corrupt ORC decimal stream")
    return out


def orc_rle2_encode(vals: np.ndarray, signed: bool) -> Optional[bytes]:
    """ORC RLEv2 integer encode (None -> caller uses the Python path)."""
    if _LIB is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n = len(vals)
    if n == 0:
        return b""
    out = np.empty(9 * n + 64, dtype=np.uint8)
    ln = _LIB.tt_orc_rle2_encode(
        _ptr(vals, ctypes.c_int64), n, int(signed), _ptr(out, ctypes.c_uint8)
    )
    return out[:ln].tobytes()


def orc_byte_rle_encode(b: np.ndarray) -> Optional[bytes]:
    if _LIB is None:
        return None
    b = np.ascontiguousarray(b, dtype=np.uint8)
    n = len(b)
    if n == 0:
        return b""
    out = np.empty(2 * n + 64, dtype=np.uint8)
    ln = _LIB.tt_orc_byte_rle_encode(
        _ptr(b, ctypes.c_uint8), n, _ptr(out, ctypes.c_uint8)
    )
    return out[:ln].tobytes()


def arena_words(nbytes_list: Sequence[int]) -> int:
    """uint32 words a staging arena needs for these source byte sizes
    (each source lands word-aligned with zeroed tail padding)."""
    return sum((nb + 3) // 4 for nb in nbytes_list)


def pack_arena(
    arrays: Sequence[np.ndarray], use_native: bool = True
) -> np.ndarray:
    """Copy column buffers into ONE contiguous uint32 staging arena.

    The coalesced-H2D hot loop: every buffer of a split (data, validity,
    selection) is packed word-aligned so the engine issues a single
    host->device transfer per shard. Native and numpy paths are
    bit-identical (tail padding is zeroed in both).
    """
    srcs = [np.ascontiguousarray(a) for a in arrays]
    sizes = [s.nbytes for s in srcs]
    total = arena_words(sizes)
    out = np.empty(total, dtype=np.uint32)
    if total == 0:
        return out
    if _LIB is not None and use_native:
        n = len(srcs)
        ptrs = (ctypes.c_void_p * n)(
            *[s.ctypes.data_as(ctypes.c_void_p).value for s in srcs]
        )
        nbytes = np.asarray(sizes, dtype=np.int64)
        rc = _LIB.tt_pack_arena(
            ptrs,
            _ptr(nbytes, ctypes.c_int64),
            n,
            out.view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)
            ),
            total,
        )
        if rc != total:
            raise ValueError("arena pack overrun")
        return out
    dst = out.view(np.uint8)
    pos = 0
    for s, nb in zip(srcs, sizes):
        dst[pos : pos + nb] = s.reshape(-1).view(np.uint8)
        padded = (nb + 3) & ~3
        if padded != nb:
            dst[pos + nb : pos + padded] = 0
        pos += padded
    return out


def orc_varint_encode(u: np.ndarray) -> Optional[bytes]:
    """Plain LEB128 of a uint64 array (no delta, unlike varint_encode)."""
    if _LIB is None:
        return None
    u = np.ascontiguousarray(u, dtype=np.uint64)
    n = len(u)
    if n == 0:
        return b""
    out = np.empty(10 * n + 16, dtype=np.uint8)
    ln = _LIB.tt_orc_varint_encode(
        _ptr(u, ctypes.c_uint64), n, _ptr(out, ctypes.c_uint8)
    )
    return out[:ln].tobytes()
