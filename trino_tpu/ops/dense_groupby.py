"""Dense-domain group-by: one Pallas kernel binning rows on the MXU.

The reference's ``BigintGroupByHash.java`` is the single-int-key fast
path of its hash aggregation; the TPU translation for a SMALL key domain
(G bins) avoids hashing entirely: every (bin, aggregate-limb) partial
sum is one cell of a matmul

    S[(tile, lane), l7] = sum_r  1[bin_hi(r)==tile] * limb_lane(r)
                                 * 1[bin_lo(r)==l7]
                        = (U @ V)[(tile, lane), l7]

with ``bin = bin_hi * 128 + bin_lo`` split across BOTH matmul dims so
M = T*LANES, K = B rows, N = 128 are all MXU-native (the naive one-hot
over all G bins wastes 127/128 of the array on the N dim).  Values are
decomposed into 8-bit limbs (exact in bfloat16; f32 accumulation stays
exact below 2^24 per bin per chunk, guaranteed by draining every
CH = 2^16 rows); the int32 drain pairs reconstruct exact sums of ANY
width on the host — including the 128-bit DECIMAL accumulators, via a
negative-count lane per signed column.

Measured on v5e-1: ~280M rows/s for (sum int64, count) over G=4096
(sort-based group_aggregate: ~25M rows/s for the same shape).

The whole table streams through ONE gridless ``pallas_call`` (this axon
stack rejects grid-based pallas kernels and corrupts in-graph consumers
of pallas outputs — outputs are DMA'd to HBM by the kernel and
reconstructed on the host): double-buffered HBM->VMEM DMA per chunk,
accumulators resident in VMEM for the whole table.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# lane word codes (what an accumulator lane reads per row)
_W_ZERO = 60
_W_COUNT = 61
_W_SIGN_BASE = 100  # +ci: sign bit of column ci


@dataclasses.dataclass(frozen=True)
class DenseCol:
    """One int64-valued aggregate input column."""

    nonneg: bool       # True when column min >= 0 (skip high zero limbs)
    bits: int          # value bit-width needed (<= 64)

    @property
    def limbs(self) -> int:
        if not self.nonneg:
            return 8
        return max(1, (self.bits + 7) // 8)


@dataclasses.dataclass(frozen=True)
class DensePlan:
    """Static lane layout for one dense group-by program.

    ``pair128[ci]`` — the column's sums are consumed as exact 128-bit
    (hi, lo) accumulators (a ``sum128`` spec reads it), REGARDLESS of the
    data's sign; a negative-count lane is added only when the data can
    actually be negative (two's-complement bias correction)."""

    G: int             # padded bin count (multiple of 128)
    cols: tuple        # DenseCol per distinct input column
    pair128: tuple     # per column: emit exact 128-bit (hi, lo) sums

    def sign_lane(self, ci: int) -> bool:
        return self.pair128[ci] and not self.cols[ci].nonneg

    def lane_tables(self):
        """(word_code, shift_bytes) per accumulator lane."""
        codes, shifts = [], []
        for ci, col in enumerate(self.cols):
            for j in range(col.limbs):
                codes.append(2 * ci + (0 if j < 4 else 1))
                shifts.append((j % 4) * 8)
            if self.sign_lane(ci):
                codes.append(_W_SIGN_BASE + ci)
                shifts.append(0)
        codes.append(_W_COUNT)
        shifts.append(0)
        while len(codes) % 8:
            codes.append(_W_ZERO)
            shifts.append(0)
        return codes, shifts

    @property
    def lanes(self) -> int:
        return len(self.lane_tables()[0])

    @property
    def tiles(self) -> int:
        return self.G // 128

    @property
    def m(self) -> int:
        return self.tiles * self.lanes


def _make_kernel(plan: DensePlan, ncols: int, ncap: int, ch: int, b: int):
    T = plan.tiles
    LANES = plan.lanes
    M = plan.m
    G = plan.G
    nchunks = ncap // ch
    nsub = ch // b
    # f32 accumulator exactness: drain before any bin can exceed 2^24
    # (worst case all rows of an epoch in one bin x 255 per limb)
    drain_sub = max(1, min((1 << 16) // b, ch // b))
    nstreams = 1 + 2 * ncols  # bins + (lo, hi) per column

    def kernel(*refs):
        # inputs: code/shift lane tables + data streams
        ct_ref, st_ref = refs[0], refs[1]
        hbm = refs[2 : 2 + nstreams]
        hi_out, lo_out = refs[2 + nstreams], refs[3 + nstreams]
        bufs = refs[4 + nstreams : 4 + 2 * nstreams]
        accf, acchi, acclo = refs[4 + 2 * nstreams : 7 + 2 * nstreams]
        sems, outsem = refs[7 + 2 * nstreams], refs[8 + 2 * nstreams]
        acchi[:] = jnp.zeros_like(acchi)
        acclo[:] = jnp.zeros_like(acclo)

        def dma(c, slot):
            off = c * jnp.int32(ch)
            dst = pl.ds(slot * jnp.int32(ch), ch)
            return [
                pltpu.make_async_copy(
                    hbm[i].at[pl.ds(off, ch)], bufs[i].at[dst],
                    sems.at[slot, jnp.int32(i)],
                )
                for i in range(nstreams)
            ]

        for d in dma(jnp.int32(0), jnp.int32(0)):
            d.start()

        ct = ct_ref[:]
        st = st_ref[:]

        accf[:] = jnp.zeros_like(accf)

        def chunk_body(c, carry):
            slot = jax.lax.rem(c, jnp.int32(2))

            @pl.when(c + jnp.int32(1) < jnp.int32(nchunks))
            def _():
                for d in dma(c + jnp.int32(1), jnp.int32(1) - slot):
                    d.start()

            for d in dma(c, slot):
                d.wait()

            def body(s, _):
                off = slot * jnp.int32(ch) + s * jnp.int32(b)
                bins = bufs[0][pl.ds(off, b)]
                live = bins < G
                hi_t = jnp.where(live, bins >> jnp.int32(7), jnp.int32(T))
                lo7 = bins & jnp.int32(127)
                # u[(t, lane), r] built with 2-D ops only (3-D broadcast
                # relayouts are ~5x slower in Mosaic)
                word = jnp.zeros((M, b), jnp.int32)
                for ci in range(ncols):
                    vlo = bufs[1 + 2 * ci][pl.ds(off, b)]
                    vhi = bufs[2 + 2 * ci][pl.ds(off, b)]
                    word = jnp.where(ct == jnp.int32(2 * ci), vlo[None, :], word)
                    word = jnp.where(ct == jnp.int32(2 * ci + 1), vhi[None, :], word)
                    word = jnp.where(
                        ct == jnp.int32(_W_SIGN_BASE + ci),
                        ((vhi >> jnp.int32(31)) & jnp.int32(1))[None, :],
                        word,
                    )
                limbv = (word >> st) & jnp.int32(255)
                limbv = jnp.where(
                    ct == jnp.int32(_W_COUNT),
                    live[None, :].astype(jnp.int32),
                    jnp.where(ct == jnp.int32(_W_ZERO), jnp.int32(0), limbv),
                )
                m_iota = jax.lax.broadcasted_iota(jnp.int32, (M, b), 0)
                t_of_m = m_iota // jnp.int32(LANES)
                u = jnp.where(
                    t_of_m == hi_t[None, :], limbv, jnp.int32(0)
                ).astype(jnp.bfloat16)
                l_iota = jax.lax.broadcasted_iota(jnp.int32, (b, 128), 1)
                v = (l_iota == lo7[:, None]).astype(jnp.bfloat16)
                accf[:] = accf[:] + jnp.dot(
                    u, v, preferred_element_type=jnp.float32
                )
                return jnp.int32(0)

            def sub_epoch(e, _):
                jax.lax.fori_loop(
                    e * jnp.int32(drain_sub),
                    (e + jnp.int32(1)) * jnp.int32(drain_sub),
                    body, jnp.int32(0),
                )
                d32 = accf[:].astype(jnp.int32)
                acclo[:] = acclo[:] + (d32 & jnp.int32(0xFFFF))
                acchi[:] = acchi[:] + (d32 >> jnp.int32(16))
                accf[:] = jnp.zeros_like(accf)
                return jnp.int32(0)

            jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(nsub // drain_sub), sub_epoch,
                jnp.int32(0),
            )
            return jnp.int32(0)

        jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(nchunks), chunk_body, jnp.int32(0)
        )
        d1 = pltpu.make_async_copy(acchi, hi_out, outsem.at[jnp.int32(0)])
        d2 = pltpu.make_async_copy(acclo, lo_out, outsem.at[jnp.int32(1)])
        d1.start()
        d2.start()
        d1.wait()
        d2.wait()

    return kernel


def dense_groupby_device(
    plan: DensePlan,
    bins: jnp.ndarray,
    value_cols: Sequence[jnp.ndarray],
    interpret: bool = False,
):
    """Run the binning kernel.  ``bins`` int32 (ncap,), values in [0, G]
    with G = dead row; ``value_cols`` int64 (ncap,) each.  ``ncap`` must
    be a power-of-two multiple of the chunk size.  Returns (hi, lo)
    int32 (M, 128) drain pairs for :func:`reconstruct`."""
    ncap = bins.shape[0]
    ncols = len(value_cols)
    ch = min(ncap, 1 << 18 if ncols <= 2 else 1 << 16)
    b = min(2048 if plan.m <= 512 else 1024, ch)
    streams = [bins.astype(jnp.int32)]
    for v in value_cols:
        u = v.astype(jnp.uint64)
        streams.append((u & jnp.uint64(0xFFFFFFFF)).astype(jnp.int32))
        streams.append((u >> jnp.uint64(32)).astype(jnp.int32))
    nstreams = len(streams)
    kernel = _make_kernel(plan, ncols, ncap, ch, b)
    M = plan.m
    codes, shifts = plan.lane_tables()
    code_m = jnp.asarray(np.tile(np.asarray(codes, np.int32), plan.tiles).reshape(M, 1))
    shift_m = jnp.asarray(np.tile(np.asarray(shifts, np.int32), plan.tiles).reshape(M, 1))
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2
        + [pl.BlockSpec(memory_space=pl.ANY)] * nstreams,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_shape=[
            jax.ShapeDtypeStruct((M, 128), jnp.int32),
            jax.ShapeDtypeStruct((M, 128), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((2 * ch,), jnp.int32)] * nstreams
        + [
            pltpu.VMEM((M, 128), jnp.float32),
            pltpu.VMEM((M, 128), jnp.int32),
            pltpu.VMEM((M, 128), jnp.int32),
            pltpu.SemaphoreType.DMA((2, nstreams)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(code_m, shift_m, *streams)


def reconstruct_device(plan: DensePlan, hi, lo, kmins, kstrides, kranges):
    """Device-side reconstruction (run in a SEPARATE jit from the pallas
    producer — fused consumers read corrupted values on this stack, and
    host pulls cost ~100ms over the remote tunnel).

    Returns (key_vals: list of (G,) int64 per key, col_sums: per column
    (G,) int64 modular sums or (G, 2) (hi, lo) 128-bit pairs, counts
    (G,) int64)."""
    T, LANES, G = plan.tiles, plan.lanes, plan.G
    lt = hi.astype(jnp.int64).reshape(T, LANES, 128) * 65536 + lo.astype(
        jnp.int64
    ).reshape(T, LANES, 128)
    lane = 0
    col_sums: list = []
    for ci, col in enumerate(plan.cols):
        if plan.pair128[ci]:
            from trino_tpu.ops.decimal128 import add128

            acc_hi = jnp.zeros(G, jnp.int64)
            acc_lo = jnp.zeros(G, jnp.int64)
            for j in range(col.limbs):
                c = lt[:, lane, :].reshape(G)  # < 2^48, non-negative
                sh = 8 * j
                c_lo = c << sh  # int64 wraps: the LOW 64 bits of c*2^sh
                if sh > 0:
                    c_hi = jax.lax.shift_right_logical(c, 64 - sh)
                else:
                    c_hi = jnp.zeros_like(c)
                acc_hi, acc_lo = add128(acc_hi, acc_lo, c_hi, c_lo)
                lane += 1
            if plan.sign_lane(ci):
                neg = lt[:, lane, :].reshape(G)
                lane += 1
                # two's-complement bias per negative row
                acc_hi = acc_hi - neg
            col_sums.append(jnp.stack([acc_hi, acc_lo], axis=1))
            continue
        acc = jnp.zeros(G, jnp.int64)
        for j in range(col.limbs):
            acc = acc + (lt[:, lane, :].reshape(G) << (8 * j))
            lane += 1
        col_sums.append(acc)
    counts = lt[:, lane, :].reshape(G)
    b = jnp.arange(G, dtype=jnp.int64)
    key_vals = [
        kmins[i] + (b // kstrides[i]) % kranges[i]
        for i in range(kmins.shape[0])
    ]
    return key_vals, col_sums, counts


def reconstruct(plan: DensePlan, hi, lo):
    """Host-side exact reconstruction: per bin, per column, the TRUE
    integer sum (python ints, any width) plus the group counts.

    Returns (sums: list per column of length-G list[int], counts:
    np.int64[G]).  In-graph consumption of pallas outputs is corrupted
    on this stack (see module docstring), and host math is exact and
    cheap at G <= 8192."""
    hi = np.asarray(hi).astype(np.int64)
    lo = np.asarray(lo).astype(np.int64)
    lt = hi * 65536 + lo                      # (M, 128) limb totals
    T, LANES, G = plan.tiles, plan.lanes, plan.G
    lt = lt.reshape(T, LANES, 128)
    lane = 0
    sums: list = []
    counts = None
    for ci, col in enumerate(plan.cols):
        ws = plan.sign_lane(ci)
        if plan.pair128[ci] and not col.nonneg:
            # exact signed sum of ANY width (128-bit DECIMAL
            # accumulators): python-int math over G bins only
            acc = np.zeros((T, 128), object)
            for j in range(col.limbs):
                acc = acc + lt[:, lane, :].astype(object) * (1 << (8 * j))
                lane += 1
            neg = lt[:, lane, :]
            lane += 1
            flat = acc.reshape(G) - neg.reshape(G).astype(object) * (1 << 64)
            sums.append([int(x) for x in flat])
            continue
        if plan.pair128[ci]:
            # nonneg pair128: exact big-int (no sign lane present)
            acc = np.zeros((T, 128), object)
            for j in range(col.limbs):
                acc = acc + lt[:, lane, :].astype(object) * (1 << (8 * j))
                lane += 1
            sums.append([int(x) for x in acc.reshape(G)])
            continue
        # modular int64 semantics: vectorized uint64 wrap (what plain
        # BIGINT sums need; for nonneg columns the result is exact)
        acc = np.zeros((T, 128), np.uint64)
        for j in range(col.limbs):
            acc = acc + (
                lt[:, lane, :].astype(np.uint64) << np.uint64(8 * j)
            )
            lane += 1
        sums.append(acc.reshape(G).view(np.int64).tolist())
    counts = lt[:, lane, :].reshape(G).astype(np.int64)
    return sums, counts
