"""Int128 decimal arithmetic on int64-limb pairs.

Reference semantics: ``core/trino-spi/src/main/java/io/trino/spi/type/
UnscaledDecimal128Arithmetic.java`` — DECIMAL(p>18) unscaled values as
128-bit integers. TPU-first representation:

- A *wide* value is two int64 lanes ``(hi, lo)`` holding the two's
  complement 128-bit integer (``lo`` interpreted unsigned). A wide COLUMN
  is an ``(n, 2)`` int64 array — two fixed-width lanes, no dynamic width.
- Multiplication uses the classic four-product 32-bit-limb schoolbook in
  uint64 lanes (every partial product of 32-bit limbs fits 64 bits).
- SUM accumulation decomposes values into four unsigned 32-bit limbs and
  ``segment_sum``s each limb independently (a limb column sums 2^31 rows
  without overflowing int64); carry propagation happens once per *group*
  on the host with exact Python integers. This keeps the per-row work
  MXU/VPU-friendly and the exactness cost O(groups), not O(rows).

All two's-complement modular identities make the limb sums exact mod
2^128; true sums of DECIMAL(38) values fit 127 bits, so reconstruction is
exact.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

_MASK32 = np.int64(0xFFFFFFFF)
_TWO127 = 1 << 127
_TWO128 = 1 << 128
_SIGNBIT = np.int64(np.uint64(1 << 63))  # int64 min as bit pattern


# --- scalar conversions (host) ----------------------------------------------


def int_to_pair(v: int) -> tuple[int, int]:
    """Python int -> (hi, lo) two's-complement int64 scalars."""
    u = v & (_TWO128 - 1)
    lo = u & 0xFFFFFFFFFFFFFFFF
    hi = u >> 64
    if lo >= 1 << 63:
        lo -= 1 << 64
    if hi >= 1 << 63:
        hi -= 1 << 64
    return hi, lo


def pair_to_int(hi: int, lo: int) -> int:
    """(hi, lo) int64 scalars -> Python int (signed 128-bit)."""
    u = ((int(hi) & 0xFFFFFFFFFFFFFFFF) << 64) | (int(lo) & 0xFFFFFFFFFFFFFFFF)
    return u - _TWO128 if u >= _TWO127 else u


def wide_from_ints(values: Sequence[int]) -> np.ndarray:
    """Python ints -> (n, 2) int64 wide column data."""
    out = np.empty((len(values), 2), dtype=np.int64)
    for i, v in enumerate(values):
        hi, lo = int_to_pair(int(v))
        out[i, 0] = hi
        out[i, 1] = lo
    return out


def wide_to_ints(arr: np.ndarray) -> list[int]:
    arr = np.asarray(arr)
    return [pair_to_int(arr[i, 0], arr[i, 1]) for i in range(arr.shape[0])]


def is_wide_data(data) -> bool:
    return getattr(data, "ndim", 1) == 2


# --- device kernels ---------------------------------------------------------


def _u(x):
    return x.astype(jnp.uint64)


def mulhi_u64(a, b):
    """High 64 bits of the unsigned 64x64 product (32-bit limb schoolbook)."""
    a, b = _u(a), _u(b)
    a_lo = a & jnp.uint64(0xFFFFFFFF)
    a_hi = a >> jnp.uint64(32)
    b_lo = b & jnp.uint64(0xFFFFFFFF)
    b_hi = b >> jnp.uint64(32)
    p0 = a_lo * b_lo
    p1 = a_lo * b_hi
    p2 = a_hi * b_lo
    p3 = a_hi * b_hi
    cy = ((p0 >> jnp.uint64(32)) + (p1 & jnp.uint64(0xFFFFFFFF)) + (p2 & jnp.uint64(0xFFFFFFFF))) >> jnp.uint64(32)
    return (p3 + (p1 >> jnp.uint64(32)) + (p2 >> jnp.uint64(32)) + cy).astype(
        jnp.int64
    )


def mul_i64_to_i128(a, b):
    """Signed 64x64 -> exact 128-bit product as (hi, lo) int64 lanes."""
    lo = (_u(a) * _u(b)).astype(jnp.int64)  # wrapping low 64
    hi = mulhi_u64(a, b)
    # signed correction: for two's complement, hi_signed =
    # hi_unsigned - (a<0 ? b : 0) - (b<0 ? a : 0)
    hi = hi - jnp.where(a < 0, b, jnp.zeros_like(b)) - jnp.where(
        b < 0, a, jnp.zeros_like(a)
    )
    return hi, lo


def mul_i64_overflows(a, b):
    """True where the signed 64x64 product does not fit int64."""
    hi, lo = mul_i64_to_i128(a, b)
    return hi != (lo >> jnp.int64(63))


def add128(hi1, lo1, hi2, lo2):
    """(hi,lo) + (hi,lo) two's complement with carry."""
    lo = (_u(lo1) + _u(lo2)).astype(jnp.int64)
    carry = (_u(lo) < _u(lo1)).astype(jnp.int64)
    hi = hi1 + hi2 + carry
    return hi, lo


def mul128_by_i64(hi, lo, m):
    """Low 128 bits of (hi,lo) * m (signed). Exact when the true product
    fits 128 bits (the caller's precision cap guarantees it)."""
    p_hi, p_lo = mul_i64_to_i128(lo, m)
    # correction: lo was treated signed by mul_i64_to_i128 but represents an
    # unsigned limb; add back m << 64 where lo's sign bit was set
    p_hi = p_hi + jnp.where(lo < 0, m, jnp.zeros_like(m))
    hi_lo = (_u(hi) * _u(m)).astype(jnp.int64)  # wrapping: low 64 of hi*m
    return p_hi + hi_lo, p_lo


def widen_i64(v):
    """int64 -> (hi, lo) sign-extended."""
    return v >> jnp.int64(63), v


def neg128(hi, lo):
    nlo = (~_u(lo) + jnp.uint64(1)).astype(jnp.int64)
    carry = (nlo == 0).astype(jnp.int64)
    nhi = (~_u(hi)).astype(jnp.int64) + carry
    return nhi, nlo


def compare128(hi1, lo1, hi2, lo2):
    """-1 / 0 / +1 sign array for signed 128-bit comparison."""
    hi_lt = hi1 < hi2
    hi_gt = hi1 > hi2
    lo_lt = _u(lo1) < _u(lo2)
    lo_gt = _u(lo1) > _u(lo2)
    lt = hi_lt | (~hi_gt & lo_lt)
    gt = hi_gt | (~hi_lt & lo_gt)
    return jnp.where(lt, -1, jnp.where(gt, 1, 0)).astype(jnp.int32)


def sort_operands_wide(hi, lo, ascending: bool = True):
    """Sort keys realizing signed-128 order under ascending lax.sort:
    (hi signed, lo as-unsigned-shifted-to-signed)."""
    lo_key = lo ^ _SIGNBIT  # unsigned order in signed lanes
    if not ascending:
        return [-1 - hi, jnp.int64(-1) - lo_key]
    return [hi, lo_key]


# --- accumulation -----------------------------------------------------------


def _limbs32_from_i64(v):
    """int64 values -> two unsigned 32-bit limbs in int64 lanes."""
    u = _u(v)
    return (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64), (
        u >> jnp.uint64(32)
    ).astype(jnp.int64)


def narrow_limb_sums(data, weights_valid, seg_sum):
    """Per-group exact sums of int64 values via 32-bit limb accumulation.

    ``seg_sum(x) -> (G,)`` performs the per-group reduction (the caller
    owns the grouping strategy — sorted-segment cumsum differences in
    :mod:`trino_tpu.ops.aggregation`, a plain ``jnp.sum`` for globals).

    Returns (G, 3) int64: [limb0_sum, limb1_sum, neg_count] where the true
    per-group sum = limb0 + limb1*2^32 - neg_count*2^64 (two's complement
    reconstruction of the sign-extended 64-bit values, exact in Python)."""
    l0, l1 = _limbs32_from_i64(data)
    z = jnp.zeros_like(data)
    if weights_valid is None:  # no nulls: skip the masking
        neg = jnp.where(data < 0, jnp.ones_like(data), z)
    else:
        l0 = jnp.where(weights_valid, l0, z)
        l1 = jnp.where(weights_valid, l1, z)
        neg = jnp.where(weights_valid & (data < 0), jnp.ones_like(data), z)
    return jnp.stack([seg_sum(l0), seg_sum(l1), seg_sum(neg)], axis=1)


def wide_limb_sums(hi, lo, weights_valid, seg_sum):
    """Per-group sums of (hi, lo) wide values as 5 limb columns:
    [lo0, lo1, hi0, hi1, hi_neg]; true sum = lo0 + lo1*2^32 +
    (hi0 + hi1*2^32 - hi_neg*2^64)*2^64 (exact in Python).
    ``seg_sum`` as in :func:`narrow_limb_sums`."""
    lo0, lo1 = _limbs32_from_i64(lo)
    hi0, hi1 = _limbs32_from_i64(hi)
    z = jnp.zeros_like(lo)
    if weights_valid is None:  # no nulls: skip the masking
        neg = jnp.where(hi < 0, jnp.ones_like(lo), z)
    else:
        lo0 = jnp.where(weights_valid, lo0, z)
        lo1 = jnp.where(weights_valid, lo1, z)
        hi0 = jnp.where(weights_valid, hi0, z)
        hi1 = jnp.where(weights_valid, hi1, z)
        neg = jnp.where(weights_valid & (hi < 0), jnp.ones_like(lo), z)
    return jnp.stack(
        [seg_sum(c) for c in (lo0, lo1, hi0, hi1, neg)], axis=1
    )


def _shl32_128(v):
    """int64 v << 32 as a 128-bit (hi, lo) pair."""
    hi = v >> jnp.int64(32)  # arithmetic shift keeps the sign
    lo = (_u(v) << jnp.uint64(32)).astype(jnp.int64)
    return hi, lo


def limb_sums_to_pair(limbs):
    """Device-side reconstruction of limb sums into (hi, lo) lanes.

    Accepts the (G, 3) output of :func:`narrow_limb_sums`
    (``total = s0 + s1*2^32 - neg*2^64``) or the (G, 5) output of
    :func:`wide_limb_sums`
    (``total = lo0 + lo1*2^32 + (hi0 + hi1*2^32 - neg*2^64) * 2^64``).
    Exact mod 2^128; true DECIMAL(38) sums fit 127 bits."""
    k = limbs.shape[1]
    if k == 3:
        s0, s1, sn = limbs[:, 0], limbs[:, 1], limbs[:, 2]
        hi, lo = widen_i64(s0)
        h2, l2 = _shl32_128(s1)
        hi, lo = add128(hi, lo, h2, l2)
        return hi - sn, lo
    lo0, lo1, hi0, hi1, neg = (limbs[:, i] for i in range(5))
    lp_hi, lp_lo = widen_i64(lo0)
    h2, l2 = _shl32_128(lo1)
    lp_hi, lp_lo = add128(lp_hi, lp_lo, h2, l2)
    # hi_part as 128-bit: hi0 + hi1<<32 - neg<<64; only its LOW 64 bits
    # contribute (they land in the hi lane of the final value)
    hp_hi, hp_lo = widen_i64(hi0)
    h3, l3 = _shl32_128(hi1)
    hp_hi, hp_lo = add128(hp_hi, hp_lo, h3, l3)
    hp_lo = hp_lo  # - neg<<64 only affects bits >= 64 of hi_part: drop
    return (lp_hi + hp_lo), lp_lo


_NLIMB = 9  # 288 bits: |dividend| < 2^127 times 10^shift (shift <= 39)


def _limbs9_from_pair(hi, lo):
    """|value| (non-negative (hi, lo)) -> (n, 9) array of 32-bit limbs in
    int64 lanes, little-endian."""
    u_lo = _u(lo)
    u_hi = _u(hi)
    mask = jnp.uint64(0xFFFFFFFF)
    limbs = [
        (u_lo & mask).astype(jnp.int64),
        ((u_lo >> jnp.uint64(32)) & mask).astype(jnp.int64),
        (u_hi & mask).astype(jnp.int64),
        ((u_hi >> jnp.uint64(32)) & mask).astype(jnp.int64),
    ]
    z = jnp.zeros_like(limbs[0])
    limbs += [z] * (_NLIMB - 4)
    return jnp.stack(limbs, axis=1)


def _limbs_mul_small(limbs, m: int):
    """(n, k) limb array times a scalar < 2^31, with carry propagation.
    Returns (limbs, lost) — ``lost`` marks rows whose product overflowed
    the limb width."""
    k = limbs.shape[1]
    out = []
    carry = jnp.zeros(limbs.shape[0], dtype=jnp.int64)
    for j in range(k):
        prod = limbs[:, j] * jnp.int64(m) + carry
        out.append(prod & jnp.int64(0xFFFFFFFF))
        carry = prod >> jnp.int64(32)
    return jnp.stack(out, axis=1), carry != 0


def _limbs_scale10(limbs, digits: int):
    """Multiply a limb array by 10**digits (digits >= 0) in <2^31 chunks.
    Returns (limbs, lost)."""
    lost = jnp.zeros(limbs.shape[0], dtype=jnp.bool_)
    while digits > 0:
        step = min(digits, 9)
        limbs, l = _limbs_mul_small(limbs, 10**step)
        lost = lost | l
        digits -= step
    return limbs, lost


def div128_round(ahi, alo, bhi, blo, shift: int):
    """Exact DECIMAL division with HALF_UP rounding:
    ``round(a * 10**shift / b)`` over signed 128-bit (hi, lo) pairs.

    Reference semantics: ``spi/type/UnscaledDecimal128Arithmetic.java``
    divideRoundUp — scale the dividend, divide magnitudes, round half
    away from zero, apply the sign. The magnitude division is a
    bit-serial restoring long division over 288-bit limbs inside a
    ``fori_loop`` (shift-in quotient bits; no scatters), fully
    vectorized across rows. Division by zero, a scaled dividend past 288
    bits, or a quotient past 128 bits all yield 0 with ``ok=False``
    (callers turn that into NULL; the eager reference raises instead —
    such inputs are errors either way).

    Returns (qhi, qlo, ok)."""
    sign_neg = (ahi < 0) ^ (bhi < 0)
    na_hi, na_lo = neg128(ahi, alo)
    abs_a_hi = jnp.where(ahi < 0, na_hi, ahi)
    abs_a_lo = jnp.where(ahi < 0, na_lo, alo)
    nb_hi, nb_lo = neg128(bhi, blo)
    abs_b_hi = jnp.where(bhi < 0, nb_hi, bhi)
    abs_b_lo = jnp.where(bhi < 0, nb_lo, blo)

    num = _limbs9_from_pair(abs_a_hi, abs_a_lo)
    ok = (abs_b_hi != 0) | (abs_b_lo != 0)
    if shift > 0:
        num, lost = _limbs_scale10(num, shift)
        ok = ok & ~lost
    den = _limbs9_from_pair(abs_b_hi, abs_b_lo)

    nbits = 32 * _NLIMB
    n = num.shape[0]

    def _ge(x, y):
        """Lexicographic >= over little-endian limb arrays."""
        res = jnp.zeros(n, dtype=jnp.bool_)
        decided = jnp.zeros(n, dtype=jnp.bool_)
        for j in range(_NLIMB - 1, -1, -1):
            gt = x[:, j] > y[:, j]
            lt = x[:, j] < y[:, j]
            res = jnp.where(~decided & gt, True, res)
            decided = decided | gt | lt
        return res | ~decided  # equal counts as >=

    def _sub(x, y):
        borrow = jnp.zeros(n, dtype=jnp.int64)
        out = []
        for j in range(_NLIMB):
            d = x[:, j] - y[:, j] - borrow
            borrow = (d < 0).astype(jnp.int64)
            out.append(d + borrow * jnp.int64(1 << 32))
        return jnp.stack(out, axis=1)

    def _shl1_or(x, bit):
        """(x << 1) | bit across limbs; bit is (n,) 0/1."""
        out = []
        carry = bit
        for j in range(_NLIMB):
            v = (x[:, j] << 1) | carry
            carry = v >> jnp.int64(32)
            out.append(v & jnp.int64(0xFFFFFFFF))
        return jnp.stack(out, axis=1)

    def body(i, carry):
        rem, quo = carry
        pos = nbits - 1 - i
        limb = pos // 32  # traced ints; dynamic_index over limb axis
        off = pos % 32
        bits = (
            jax.lax.dynamic_index_in_dim(num, limb, axis=1, keepdims=False)
            >> off
        ) & 1
        rem = _shl1_or(rem, bits)
        ge = _ge(rem, den)
        rem = jnp.where(ge[:, None], _sub(rem, den), rem)
        quo = _shl1_or(quo, ge.astype(jnp.int64))
        return rem, quo

    zeros = jnp.zeros_like(num)
    rem, quo = jax.lax.fori_loop(0, nbits, body, (zeros, zeros))
    # HALF_UP: round away from zero when 2*rem >= den
    twice = _shl1_or(rem, jnp.zeros(n, dtype=jnp.int64))
    roundup = _ge(twice, den) & ok
    # quo += roundup (carry-propagating add of 0/1)
    carry = roundup.astype(jnp.int64)
    limbs_out = []
    for j in range(_NLIMB):
        v = quo[:, j] + carry
        carry = v >> jnp.int64(32)
        limbs_out.append(v & jnp.int64(0xFFFFFFFF))
    quo = jnp.stack(limbs_out, axis=1)
    # quotient must fit 128 bits (magnitude < 2^127: the sign bit of the
    # hi lane must stay clear before sign application)
    over = (carry != 0) | (quo[:, 3] >> jnp.int64(31) != 0)
    for j in range(4, _NLIMB):
        over = over | (quo[:, j] != 0)
    ok = ok & ~over
    q_lo = _u(quo[:, 0]) | (_u(quo[:, 1]) << jnp.uint64(32))
    q_hi = _u(quo[:, 2]) | (_u(quo[:, 3]) << jnp.uint64(32))
    qhi = q_hi.astype(jnp.int64)
    qlo = q_lo.astype(jnp.int64)
    nqhi, nqlo = neg128(qhi, qlo)
    qhi = jnp.where(sign_neg, nqhi, qhi)
    qlo = jnp.where(sign_neg, nqlo, qlo)
    qhi = jnp.where(ok, qhi, jnp.zeros_like(qhi))
    qlo = jnp.where(ok, qlo, jnp.zeros_like(qlo))
    return qhi, qlo, ok


def rescale_up_wide(hi, lo, digits: int):
    """Multiply a wide value by 10**digits (digits >= 0), staying exact
    while the true result fits 128 bits."""
    while digits > 0:
        step = min(digits, 18)
        hi, lo = mul128_by_i64(hi, lo, jnp.int64(10**step))
        digits -= step
    return hi, lo


def global_minmax_wide(hi, lo, use, kind: str):
    """min/max of (hi, lo) wide values over selected rows: lexicographic
    two-pass — extreme of the signed hi lane, then extreme of the unsigned
    lo lane among rows tied on hi. Returns scalar-shaped (hi, lo)."""
    i64 = jnp.int64
    if kind == "max":
        ident = jnp.asarray(np.iinfo(np.int64).min, dtype=i64)
        red = jnp.max
    else:
        ident = jnp.asarray(np.iinfo(np.int64).max, dtype=i64)
        red = jnp.min
    best_hi = red(jnp.where(use, hi, ident))
    tied = use & (hi == best_hi)
    lo_key = lo ^ _SIGNBIT  # unsigned order in signed lanes
    best_lo_key = red(jnp.where(tied, lo_key, ident))
    return jnp.reshape(best_hi, (1,)), jnp.reshape(best_lo_key ^ _SIGNBIT, (1,))


def narrow_sums_to_ints(sums: np.ndarray) -> list[int]:
    """Host reconstruction for :func:`narrow_limb_sums` output."""
    sums = np.asarray(sums)
    out = []
    for i in range(sums.shape[0]):
        s0, s1, sn = (int(sums[i, 0]), int(sums[i, 1]), int(sums[i, 2]))
        out.append(s0 + (s1 << 32) - (sn << 64))
    return out


def wide_sums_to_ints(sums: np.ndarray) -> list[int]:
    """Host reconstruction for :func:`wide_limb_sums` output."""
    sums = np.asarray(sums)
    out = []
    for i in range(sums.shape[0]):
        lo0, lo1, hi0, hi1, neg = (int(x) for x in sums[i])
        lo_part = lo0 + (lo1 << 32)
        hi_part = hi0 + (hi1 << 32) - (neg << 64)
        out.append(lo_part + (hi_part << 64))
    return out
