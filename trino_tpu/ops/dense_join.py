"""Dense equi-join tier: device-resident open-addressing build table.

The sort tier (``ops/join.py``) pays an O(n log n) bitonic ``lax.sort``
on every build side.  This tier replaces it with a static-shape
open-addressing table — the TPU translation of Trino's ``PagesHash``
linear-probe table — built and probed with fully vectorized rounds:

1. Each build row proposes itself for the slots ``base+0 .. base+W-1``
   (``W = PROBE_WINDOW``), one displacement per round.  A round is one
   masked ``scatter-min`` of row ids: vacant slots keep the smallest
   proposing row id, occupied slots are untouched (any occupant id is
   smaller than the ``EMPTY`` sentinel).  Rows whose id appears in the
   table after a round stop proposing.
2. Probing gathers the same W slots per probe row and filters on build
   hash equality — two static W-round passes produce exactly the
   ``probe_join`` contract ``(probe_pos, build_pos, out_sel, total,
   overflow)``, so ``verify_equal`` and every downstream consumer are
   shared with the sort tier unchanged.
3. Rows that fail to place within W rounds raise the table-overflow
   flag; the executor's retry ladder re-hashes the whole build side at
   doubled capacity (``densejoin@…`` capacity sites) instead of
   dropping the fragment to the interpreter's partitioned spill — the
   graceful-overflow contract.  Duplicate-key chains longer than W can
   never place regardless of capacity (same key ⇒ same probe sequence);
   the executor demotes such a site back to the sort strategy after a
   few fruitless growths (see ``_Caps.demoted``).

Ordering guarantee (bit-identity with the sort tier after row sorting):
among build rows with equal hash, round r of the min-id scatter places
the r-th smallest unplaced row id, so matches of one probe row emit in
ascending build-row order — the same set the sorted tier emits, and the
exactness pass (``verify_equal``) ANDs out hash collisions identically.

The ``matmul`` tier is the join analog of ``dense_groupby``'s binning:
when the build key domain bins densely, ``slot_base_binned`` addresses
the table by ``key - kmin`` directly (identity binning == perfect
hashing — zero probe collisions when the domain fits the capacity).
The per-probe match-count contraction ``counts = onehot(bins) @ hist``
is MXU-shaped; ``matmul_join_counts`` computes it as a real chunked
``jnp.dot`` for the bench/join-project path, while the traced tier uses
the gather lowering of the same contraction (no n×C one-hot resident).

Pallas: ``build_table_device`` is the NOTES_r05 gridless single-core
kernel (in-kernel ``fori_loop`` insertion over double-buffered
HBM→VMEM chunks, table resident in VMEM).  Per the NOTES constraints
its outputs must be consumed from a SEPARATE jit (in-graph consumers of
pallas outputs read corrupted values on this stack), so traced fragment
programs use the jnp rounds above and the pallas kernel serves the
standalone/bench path; both produce the same join output (see module
tests for the equivalence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trino_tpu.ops.join import MISSING

# vacant-slot sentinel: int32 max, deliberately equal to join.MISSING —
# row ids are always < capacity < 2^31 so no live entry collides with it
EMPTY = jnp.iinfo(jnp.int32).max

# static displacement window: max open-addressing chain per slot base.
# Capacity growth thins hash clusters past it; duplicate-key chains
# longer than this demote the site to the sort tier (see module doc).
PROBE_WINDOW = 16


def slot_base_hash(key_hash: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Dense tier: table slot base from the mix64 key hash."""
    return (
        key_hash.astype(jnp.uint64) & jnp.uint64(capacity - 1)
    ).astype(jnp.int32)


def slot_base_binned(
    key: jnp.ndarray, kmin: jnp.ndarray, capacity: int
) -> jnp.ndarray:
    """Matmul tier: identity binning ``key - kmin`` onto the table —
    collision-free (perfect hashing) while the key domain fits the
    capacity; wider domains wrap and degrade to ordinary probing."""
    return (
        (key.astype(jnp.int64) - kmin).astype(jnp.uint64)
        & jnp.uint64(capacity - 1)
    ).astype(jnp.int32)


def build_table(
    slot_base: jnp.ndarray,
    valid: jnp.ndarray,
    sel: jnp.ndarray,
    capacity: int,
    window: int = PROBE_WINDOW,
):
    """Insert build rows into an open-addressing table of row ids.

    Returns ``(table int32[capacity], overflow bool)`` — ``overflow``
    set when any live row failed to place within ``window`` rounds (the
    executor re-hashes at doubled capacity).
    """
    n = slot_base.shape[0]
    window = min(window, capacity)
    use = valid & sel
    ids = jnp.arange(n, dtype=jnp.int32)
    mask = jnp.int32(capacity - 1)
    table0 = jnp.full((capacity,), EMPTY, dtype=jnp.int32)

    def round_body(d, st):
        table, placed = st
        prop = (slot_base + d) & mask
        vacant = table[prop] == EMPTY
        cand = jnp.where(~placed & vacant, ids, EMPTY)
        table = table.at[prop].min(cand)
        placed = placed | (table[prop] == ids)
        return table, placed

    table, placed = jax.lax.fori_loop(
        0, window, round_body, (table0, ~use)
    )
    return table, jnp.any(~placed)


def probe_table(
    table: jnp.ndarray,
    build_hash: jnp.ndarray,
    probe_base: jnp.ndarray,
    probe_hash: jnp.ndarray,
    probe_valid: jnp.ndarray,
    probe_sel: jnp.ndarray,
    out_capacity: int,
    join_type: str = "inner",
    window: int = PROBE_WINDOW,
):
    """Expand probe × table matches into fixed-capacity gather indices.

    Same contract as ``join.probe_join``: ``(probe_pos, build_pos,
    out_sel, total, overflow)`` with ``build_pos == MISSING`` for outer
    rows; the caller runs ``verify_equal`` for hash-collision exactness.
    """
    capacity = table.shape[0]
    window = min(window, capacity)
    use = probe_valid & probe_sel
    if probe_hash.shape[0] == 0 or build_hash.shape[0] == 0:
        # statically empty side: defer to the sort tier's guard logic,
        # which already covers LEFT-over-empty-build row emission
        from trino_tpu.ops.join import probe_join

        empty_keys = jnp.zeros((0,), dtype=jnp.int64)
        empty_idx = jnp.zeros((0,), dtype=jnp.int32)
        return probe_join(
            empty_keys, empty_idx, jnp.int32(0), probe_hash,
            probe_valid, probe_sel, out_capacity, join_type,
        )
    nb = build_hash.shape[0]
    mask = jnp.int32(capacity - 1)

    def count_body(d, counts):
        e = table[(probe_base + d) & mask]
        eh = build_hash[jnp.clip(e, 0, nb - 1)]
        m = (e != EMPTY) & (eh == probe_hash) & use
        return counts + m.astype(jnp.int32)

    counts = jax.lax.fori_loop(
        0, window, count_body,
        jnp.zeros(probe_hash.shape[0], dtype=jnp.int32),
    )
    if join_type == "left":
        emit = jnp.where(probe_sel, jnp.maximum(counts, 1), 0)
    elif join_type == "inner":
        emit = counts
    else:
        raise NotImplementedError(join_type)
    from trino_tpu.ops.aggregation import _prefix_sum

    offsets = _prefix_sum(emit) - emit  # exclusive prefix
    total = offsets[-1] + emit[-1]
    overflow = total > out_capacity

    t = jnp.arange(out_capacity, dtype=emit.dtype)
    ends = offsets + emit
    probe_pos = jnp.searchsorted(ends, t, side="right").astype(jnp.int32)
    probe_pos = jnp.minimum(probe_pos, emit.shape[0] - 1)
    j = t - offsets[probe_pos]

    # second W-round pass: per output slot, the j-th matching window
    # entry of its owning probe row ((out_capacity,)-sized arrays only —
    # the (n, W) match matrix is never materialized)
    o_base = probe_base[probe_pos]
    o_hash = probe_hash[probe_pos]
    o_use = use[probe_pos]

    def pick_body(d, st):
        bpos, r = st
        e = table[(o_base + d) & mask]
        eh = build_hash[jnp.clip(e, 0, nb - 1)]
        m = (e != EMPTY) & (eh == o_hash) & o_use
        bpos = jnp.where(m & (r == j), e, bpos)
        return bpos, r + m.astype(j.dtype)

    build_pos, _ = jax.lax.fori_loop(
        0, window, pick_body,
        (
            jnp.full(out_capacity, MISSING, dtype=jnp.int32),
            jnp.zeros(out_capacity, dtype=j.dtype),
        ),
    )
    out_sel = t < total
    return probe_pos, build_pos, out_sel, total, overflow


def matmul_join_counts(
    probe_bins: jnp.ndarray,
    build_bins: jnp.ndarray,
    probe_use: jnp.ndarray,
    build_use: jnp.ndarray,
    domain: int,
    chunk: int = 2048,
):
    """Per-probe match counts as a real MXU contraction.

    ``counts[i] = Σ_g 1[probe_bin_i = g] · hist_g`` — the join-as-matmul
    count kernel for join-project shapes, computed as chunked
    ``onehot @ hist`` dots exactly like ``dense_groupby``'s binning
    matmul.  Equal to the gather lowering ``hist[probe_bins]`` (asserted
    by the unit tests); the traced tier uses the gather form to avoid a
    resident n×domain one-hot.
    """
    hist = (
        jnp.zeros((domain,), jnp.float32)
        .at[jnp.where(build_use, build_bins, domain - 1)]
        .add(build_use.astype(jnp.float32))
    )
    n = probe_bins.shape[0]
    pad = (-n) % chunk
    bins_p = jnp.pad(probe_bins, (0, pad))
    use_p = jnp.pad(probe_use, (0, pad))
    nch = bins_p.shape[0] // chunk
    g = jnp.arange(domain, dtype=jnp.int32)

    def chunk_body(c, out):
        b = jax.lax.dynamic_slice(bins_p, (c * chunk,), (chunk,))
        u = jax.lax.dynamic_slice(use_p, (c * chunk,), (chunk,))
        onehot = ((b[:, None] == g[None, :]) & u[:, None]).astype(
            jnp.float32
        )
        cc = jnp.dot(onehot, hist, preferred_element_type=jnp.float32)
        return jax.lax.dynamic_update_slice(out, cc, (c * chunk,))

    out = jax.lax.fori_loop(
        0, nch, chunk_body, jnp.zeros(bins_p.shape[0], jnp.float32)
    )
    return out[:n].astype(jnp.int32)


# ── gridless pallas build kernel (bench/standalone path) ────────────────


def _make_build_kernel(ncap: int, capacity: int, ch: int, window: int):
    nchunks = ncap // ch

    def kernel(
        base_hbm, use_hbm, table_out, ovf_out, tbuf, obuf, bbuf, ubuf,
        sems, outsem,
    ):
        tbuf[:] = jnp.full((capacity,), EMPTY, jnp.int32)

        def dma(c, slot):
            off = c * jnp.int32(ch)
            dst = pl.ds(slot * jnp.int32(ch), ch)
            return [
                pltpu.make_async_copy(
                    base_hbm.at[pl.ds(off, ch)], bbuf.at[dst],
                    sems.at[slot, jnp.int32(0)],
                ),
                pltpu.make_async_copy(
                    use_hbm.at[pl.ds(off, ch)], ubuf.at[dst],
                    sems.at[slot, jnp.int32(1)],
                ),
            ]

        for d in dma(jnp.int32(0), jnp.int32(0)):
            d.start()

        def chunk_body(c, ovf):
            slot = jax.lax.rem(c, jnp.int32(2))

            @pl.when(c + jnp.int32(1) < jnp.int32(nchunks))
            def _():
                for d in dma(c + jnp.int32(1), jnp.int32(1) - slot):
                    d.start()

            for d in dma(c, slot):
                d.wait()
            off = slot * jnp.int32(ch)

            def row_body(rr, ovf):
                b = bbuf[off + rr]
                u = ubuf[off + rr]
                rid = c * jnp.int32(ch) + rr

                def win(d, found):
                    idx = (b + d) & jnp.int32(capacity - 1)
                    vac = tbuf[idx] == EMPTY
                    return jnp.where(
                        (found < jnp.int32(0)) & vac, idx, found
                    )

                found = jax.lax.fori_loop(
                    jnp.int32(0), jnp.int32(window), win, jnp.int32(-1)
                )
                # -2: dead row, no placement wanted (and no overflow)
                found = jnp.where(u > jnp.int32(0), found, jnp.int32(-2))

                @pl.when(found >= jnp.int32(0))
                def _():
                    tbuf[found] = rid

                return ovf + jnp.where(
                    found == jnp.int32(-1), jnp.int32(1), jnp.int32(0)
                )

            return jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(ch), row_body, ovf
            )

        ovf = jax.lax.fori_loop(
            jnp.int32(0), jnp.int32(nchunks), chunk_body, jnp.int32(0)
        )
        obuf[:] = jnp.zeros((8,), jnp.int32)
        obuf[0] = ovf
        d1 = pltpu.make_async_copy(tbuf, table_out, outsem.at[jnp.int32(0)])
        d2 = pltpu.make_async_copy(obuf, ovf_out, outsem.at[jnp.int32(1)])
        d1.start()
        d2.start()
        d1.wait()
        d2.wait()

    return kernel


def build_table_device(
    slot_base: jnp.ndarray,
    use: jnp.ndarray,
    capacity: int,
    window: int = PROBE_WINDOW,
    interpret: bool = False,
):
    """Pallas build: sequential in-kernel insertion (first-vacant-slot
    per row, rows in id order — the same per-key ascending placement the
    jnp rounds produce, so probing either table emits identical joins).

    Returns ``(table int32[capacity], unplaced int32)``; consume from a
    SEPARATE jit (module doc).
    """
    n = slot_base.shape[0]
    window = min(window, capacity)
    ch = min(1024, max(256, n))
    pad = (-n) % ch
    base_p = jnp.pad(slot_base.astype(jnp.int32), (0, pad))
    use_p = jnp.pad(use.astype(jnp.int32), (0, pad))
    ncap = n + pad
    kernel = _make_build_kernel(ncap, capacity, ch, window)
    table, ovf = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_shape=[
            jax.ShapeDtypeStruct((capacity,), jnp.int32),
            jax.ShapeDtypeStruct((8,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((capacity,), jnp.int32),
            pltpu.VMEM((8,), jnp.int32),
            pltpu.VMEM((2 * ch,), jnp.int32),
            pltpu.VMEM((2 * ch,), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(base_p, use_p)
    return table, ovf[0]
