"""Equi-join kernels: sort build side + vectorized binary search probe.

Reference: Trino's hash join — ``operator/HashBuilderOperator.java:51``,
``operator/PagesHash.java:34`` (linear-probe table over synthetic addresses),
``operator/LookupJoinOperator.java:71``.

TPU-first design: no pointer-chasing hash table. Instead:
1. Hash each side's key columns into one int64 key (mix64 per column,
   combined), with NULL keys mapped to a never-matching sentinel.
2. Sort the build side by hashed key (``lax.sort`` — fast bitonic on TPU).
3. Probe with two vectorized binary searches (searchsorted left/right) to
   get per-probe match ranges — fully parallel, no data-dependent loops.
4. Expand matches into a fixed output capacity via cumsum offsets +
   searchsorted "which probe row owns output slot t" — static shapes.
5. Exactness: hashing may collide, so after expansion the caller re-checks
   the real key columns and ANDs mismatches out of the selection. This makes
   the kernel exact without needing perfect packing (Trino's 8-bit raw-hash
   prefilter + full key compare, taken to its vectorized conclusion).

Overflow: if total matches exceed capacity, the kernel reports it; the
executor retries with a larger bucket (shape-bucketed recompile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MISSING = jnp.iinfo(jnp.int32).max  # build position marking "no match" (left join)


def mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — good avalanche, cheap on VPU."""
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> 31)
    return x


def hash_keys(keys, null_sentinel: bool = True) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Combine key columns [(data, valid), ...] into (hash int64, all_valid)."""
    acc = jnp.zeros(keys[0][0].shape[0], dtype=jnp.uint64)
    all_valid = None
    for data, valid in keys:
        h = mix64(data.astype(jnp.int64))
        acc = mix64(acc ^ h)
        all_valid = valid if all_valid is None else (all_valid & valid)
    return acc.astype(jnp.int64), all_valid


def build_side(key_hash: jnp.ndarray, valid: jnp.ndarray, sel: jnp.ndarray):
    """Sort build rows by hashed key; invalid/unselected rows pushed to +inf.

    Returns (sorted_keys, sorted_row_indices, build_count).
    """
    n = key_hash.shape[0]
    use = valid & sel
    maxv = jnp.iinfo(jnp.int64).max
    keyed = jnp.where(use, key_hash, maxv)
    idx = jnp.arange(n, dtype=jnp.int32)
    # idx as a second sort KEY (not payload): deterministic tie order
    # without is_stable, which doubles XLA:TPU sort compile time
    sorted_keys, sorted_idx = jax.lax.sort(
        (keyed, idx), num_keys=2, is_stable=False
    )
    count = jnp.sum(use.astype(jnp.int32))
    return sorted_keys, sorted_idx, count


def probe_join(
    sorted_build_keys: jnp.ndarray,
    sorted_build_idx: jnp.ndarray,
    build_count: jnp.ndarray,
    probe_hash: jnp.ndarray,
    probe_valid: jnp.ndarray,
    probe_sel: jnp.ndarray,
    out_capacity: int,
    join_type: str = "inner",
):
    """Expand probe x build matches into fixed-capacity gather indices.

    Returns (probe_pos, build_pos, out_sel, total, overflow):
      probe_pos/build_pos: (out_capacity,) int32 gather indices into the
        original (unsorted) batches; build_pos == MISSING for outer rows.
      out_sel: (out_capacity,) bool — which output slots are live.
      total: int32 scalar — true number of output rows.
      overflow: bool — total > out_capacity.
    """
    use = probe_valid & probe_sel
    if probe_hash.shape[0] == 0:
        # statically empty probe: nothing to emit
        return (
            jnp.zeros(out_capacity, dtype=jnp.int32),
            jnp.full(out_capacity, MISSING, dtype=jnp.int32),
            jnp.zeros(out_capacity, dtype=jnp.bool_),
            jnp.int32(0),
            jnp.asarray(False),
        )
    if sorted_build_idx.shape[0] == 0:
        # statically empty build: no matches; LEFT still emits probe rows
        n = probe_hash.shape[0]
        if join_type == "left":
            ends0 = jnp.cumsum(probe_sel.astype(jnp.int32))
            t0 = jnp.arange(out_capacity, dtype=jnp.int32)
            ppos = jnp.searchsorted(ends0, t0, side="right").astype(jnp.int32)
            ppos = jnp.minimum(ppos, n - 1)  # probe nonempty (guard above)
            total0 = ends0[-1]
            osel = t0 < total0
            bpos = jnp.full(out_capacity, MISSING, dtype=jnp.int32)
            return ppos, bpos, osel, total0, total0 > out_capacity
        return (
            jnp.zeros(out_capacity, dtype=jnp.int32),
            jnp.full(out_capacity, MISSING, dtype=jnp.int32),
            jnp.zeros(out_capacity, dtype=jnp.bool_),
            jnp.int32(0),
            jnp.asarray(False),
        )
    maxv = jnp.iinfo(jnp.int64).max
    keys = jnp.where(use, probe_hash, maxv - 1)  # never matches sentinel maxv
    lo = jnp.searchsorted(sorted_build_keys, keys, side="left")
    hi = jnp.searchsorted(sorted_build_keys, keys, side="right")
    hi = jnp.minimum(hi, build_count)
    lo = jnp.minimum(lo, hi)
    counts = jnp.where(use, hi - lo, 0)
    if join_type == "left":
        emit = jnp.where(probe_sel, jnp.maximum(counts, 1), 0)
    elif join_type == "inner":
        emit = counts
    else:
        raise NotImplementedError(join_type)
    from trino_tpu.ops.aggregation import _prefix_sum
    offsets = _prefix_sum(emit) - emit  # exclusive prefix
    total = offsets[-1] + emit[-1] if emit.shape[0] else jnp.int32(0)
    overflow = total > out_capacity

    # For each output slot t, find owning probe row: last p with offsets<=t.
    t = jnp.arange(out_capacity, dtype=emit.dtype)
    ends = offsets + emit  # inclusive end per probe row
    probe_pos = jnp.searchsorted(ends, t, side="right").astype(jnp.int32)
    probe_pos = jnp.minimum(probe_pos, emit.shape[0] - 1)
    j = t - offsets[probe_pos]
    matched = counts[probe_pos] > 0
    build_slot = lo[probe_pos] + j.astype(lo.dtype)
    build_pos = jnp.where(
        matched,
        sorted_build_idx[jnp.clip(build_slot, 0, sorted_build_idx.shape[0] - 1)],
        MISSING,
    ).astype(jnp.int32)
    out_sel = t < total
    return probe_pos, build_pos, out_sel, total, overflow


def verify_equal(probe_keys, build_keys, probe_pos, build_pos, out_sel):
    """Exactness pass: re-check real key equality after hash-based expansion.

    probe_keys/build_keys: [(data, valid), ...] original (unsorted) columns.
    Rows where build_pos == MISSING (left-outer padding) are kept.
    """
    ok = jnp.ones(probe_pos.shape[0], dtype=jnp.bool_)
    is_outer = build_pos == MISSING
    safe_build = jnp.where(is_outer, 0, build_pos)
    for (pd, pv), (bd, bv) in zip(probe_keys, build_keys):
        if pd.shape[0] == 0 or bd.shape[0] == 0:
            # statically empty side: no equality can hold
            return out_sel & is_outer
        p_d = pd[probe_pos]
        p_v = pv[probe_pos]
        b_d = bd[safe_build]
        b_v = bv[safe_build]
        ok = ok & (p_d == b_d) & p_v & b_v
    return out_sel & (ok | is_outer)


def semi_join_mask(
    sorted_build_keys, build_count, probe_hash, probe_valid,
):
    """EXISTS-style membership: does probe key appear in build? (hash-level;
    caller verifies via a small second pass or accepts for dynamic filters).
    """
    maxv = jnp.iinfo(jnp.int64).max
    keys = jnp.where(probe_valid, probe_hash, maxv - 1)
    lo = jnp.searchsorted(sorted_build_keys, keys, side="left")
    hi = jnp.searchsorted(sorted_build_keys, keys, side="right")
    hi = jnp.minimum(hi, build_count)
    return (hi > lo) & probe_valid
