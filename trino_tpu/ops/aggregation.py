"""Group-by aggregation via sort + sorted-segment reductions.

Reference semantics: ``operator/HashAggregationOperator.java:49`` +
``operator/MultiChannelGroupByHash.java:55`` (open-addressing hash group-by)
and the aggregation function triple input/combine/output
(``operator/aggregation/LongSumAggregation.java:29-55``).

TPU-first design: instead of a linear-probing hash table (scatter-heavy,
serial), we lexicographically sort rows by the group keys with ``lax.sort``
(TPU has a fast bitonic sort), mark group boundaries, assign dense group ids
with a cumulative sum, and reduce over the *sorted* segments — all
MXU/VPU-friendly, fully static shapes.

Scatter-free: XLA scatter (``segment_sum`` / ``.at[].set``) lowers to a
serialized update loop on TPU (~80ms per 1M rows measured vs ~1ms for a
cumsum). Because rows are already sorted by group, every reduction is
expressible without scatter:
- segment boundary positions compact to the front of one cheap
  ``(bool, int32)`` sort (see :class:`_SortedSegments`);
- integer sums are exclusive-cumsum differences at the boundaries (exact:
  int64 wraparound is modular, so boundary differences recover any
  segment sum that itself fits in 64 bits);
- min/max re-sort ``(group_id, masked value)`` — bitonic sort is ~40x
  cheaper than scatter here — and gather the first/last row per segment;
- group keys gather the first row of each segment.
Float sums keep ``segment_sum`` (a global cumsum would change rounding).

Partial/final split: the same kernel serves both; COUNT partials re-aggregate
with SUM, AVG decomposes into SUM+COUNT (exactly Trino's
input/combine/output contract for distributed aggregation).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from trino_tpu import types as T

# Supported aggregate kinds and their (partial, final-combine) decomposition.
# sum128 / sum128w are the exact 128-bit accumulation variants for wide
# DECIMAL results (narrow int64 input / wide (n,2) input respectively) —
# see trino_tpu.ops.decimal128 (UnscaledDecimal128Arithmetic semantics).
AGG_KINDS = ("sum", "count", "count_star", "min", "max", "avg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind + input channel (None for count(*))."""

    kind: str
    input_dtype: object | None = None  # storage dtype of the input


def group_aggregate(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
    max_groups: int,
):
    """Sort-based grouped aggregation.

    Args:
      keys: per key column (data, valid), each shape (n,).
      sel: bool (n,) — rows participating.
      agg_inputs: per agg (data, valid) or None for count(*).
      agg_specs: kinds aligned with agg_inputs.
      max_groups: static output capacity (groups beyond are dropped —
        caller must size from stats; overflow is reported).

    Returns:
      (group_key_data, group_key_valid): lists of (max_groups,) arrays
      agg_results: list of result arrays (max_groups,) —
        for 'avg' returns (sum, count) pair folded by caller
      num_groups: int32 scalar
      overflow: bool scalar (true if groups were dropped)
    """
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # ONE narrow sort: all key columns (plus selection/validity bits) are
    # bit-packed into 1-3 integer lanes (ops/keypack.py), sorted unstably
    # — XLA:TPU sort compile time is ~linear in operand count AND doubles
    # under is_stable, so the old per-column operand list compiled ~20x
    # slower. Aggregate inputs RIDE the sort as payload lanes: a post-sort
    # random gather costs ~35ms per column at 2^21 rows on v5e, ~10x the
    # whole sort; payload moves inside the sort are near-free by
    # comparison. Group-key outputs are recovered by G-sized bit
    # extraction from the packed lanes (KeyPlan), not payload lanes.
    from trino_tpu.ops import keypack as KP

    plan = KP.KeyPlan(keys, sel_present=True)
    fields, native = plan.build_fields(keys, sel)
    packed = KP.pack(fields)
    n_packed = len(packed)
    key_ops = packed + list(native)
    nkey_ops = len(key_ops)
    payload: list = []
    payload_pos: dict[tuple, tuple] = {}
    for pair in agg_inputs:
        if pair is None:
            continue
        pid = (id(pair[0]), id(pair[1]))
        if pid in payload_pos:
            continue
        data, valid = pair
        base = nkey_ops + len(payload)
        wide = getattr(data, "ndim", 1) == 2
        lanes = [data[:, 0], data[:, 1]] if wide else [data]
        if valid is not None:
            lanes.append(valid)
        payload.extend(lanes)
        payload_pos[pid] = (wide, tuple(range(base, base + len(lanes))), valid is not None)
    sorted_ops = jax.lax.sort(
        tuple(key_ops) + tuple(payload), num_keys=nkey_ops, is_stable=False
    )
    s_lanes = list(sorted_ops[:nkey_ops])
    s_sel = plan.sel_bit(s_lanes[0])

    def _sorted_pair(pair):
        wide, pos, has_valid = payload_pos[(id(pair[0]), id(pair[1]))]
        sv = sorted_ops[pos[-1]] if has_valid else None
        if wide:
            return (
                jnp.stack([sorted_ops[pos[0]], sorted_ops[pos[1]]], axis=1),
                sv,
            )
        return sorted_ops[pos[0]], sv

    # boundary: first row, or any sorted key lane changed vs previous row
    changed = idx == 0
    for k in s_lanes:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    changed = changed & s_sel
    group_id = jnp.cumsum(changed.astype(jnp.int32)) - 1
    # unselected rows sort past selected ones -> monotonic out-of-range id
    group_id = jnp.where(s_sel, group_id, max_groups)
    num_groups = jnp.sum(changed.astype(jnp.int32))
    overflow = num_groups > max_groups

    seg = _SortedSegments(changed, s_sel, group_id, num_groups, max_groups, n)

    # group key output: gather the packed lanes at each segment's first
    # sorted row (G-sized gathers) and bit-extract the key fields back
    lanes_at = [seg.first(ln) for ln in s_lanes[:n_packed]]
    native_at = [seg.first(ln) for ln in s_lanes[n_packed:]]
    out_key_data, out_key_valid = [], []
    for ki, (data, valid) in enumerate(keys):
        g, kv = plan.key_output(keys, lanes_at, native_at, ki)
        kv = seg.nonempty if kv is None else (kv & seg.nonempty)
        zero = jnp.zeros((), data.dtype)
        if getattr(data, "ndim", 1) == 2:
            out_key_data.append(jnp.where(kv[:, None], g, zero).astype(data.dtype))
        else:
            out_key_data.append(jnp.where(kv, g, zero).astype(data.dtype))
        out_key_valid.append(kv)

    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            results.append(seg.sizes.astype(jnp.int64))
            continue
        s_data, s_valid = _sorted_pair(pair)

        def vcount():
            if s_valid is None:
                return seg.sizes.astype(jnp.int64)
            return seg.sum(s_valid.astype(jnp.int64))

        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            cnt = vcount()
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(s_data, s_valid, seg.sum)
            else:
                limbs = D.wide_limb_sums(
                    s_data[:, 0], s_data[:, 1], s_valid, seg.sum
                )
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(vcount())
        elif spec.kind in ("sum", "avg"):
            contrib = (
                s_data if s_valid is None
                else jnp.where(s_valid, s_data, jnp.zeros_like(s_data))
            )
            ssum = seg.sum(contrib)
            # SQL: sum over empty/all-null group is NULL — caller uses cnt
            results.append((ssum, vcount()))
        elif spec.kind in ("min", "max"):
            cnt = vcount()
            if getattr(s_data, "ndim", 1) == 2:
                from trino_tpu.ops.decimal128 import sort_operands_wide

                hi, lo = s_data[:, 0], s_data[:, 1]
                ident = _max_ident(hi.dtype) if spec.kind == "min" else _min_ident(hi.dtype)
                hk, lk = sort_operands_wide(hi, lo)
                if s_valid is not None:
                    hk = jnp.where(s_valid, hk, ident)
                    lk = jnp.where(s_valid, lk, ident)
                bh, blk = seg.extreme2(hk, lk, spec.kind)
                from trino_tpu.ops.decimal128 import _SIGNBIT

                results.append((jnp.stack([bh, blk ^ _SIGNBIT], axis=1), cnt))
            else:
                ident = (
                    _max_ident(s_data.dtype)
                    if spec.kind == "min"
                    else _min_ident(s_data.dtype)
                )
                masked = (
                    s_data if s_valid is None
                    else jnp.where(s_valid, s_data, ident)
                )
                results.append((seg.extreme(masked, spec.kind), cnt))
        else:
            raise NotImplementedError(spec.kind)
    return (out_key_data, out_key_valid), results, num_groups, overflow


def _prefix_sum(x):
    """Inclusive prefix sum via a blocked two-level scan.

    ``jnp.cumsum`` lowers to one big reduce-window: its scoped-vmem
    allocation blows up inside TPU while-loops (the streaming chunk
    loop), and XLA:TPU takes ~1min to COMPILE an int64 reduce-window at
    odd (non-power-of-two) sizes. Odd sizes are padded to a block
    multiple so every window stays small and power-of-two shaped."""
    n = x.shape[0]
    blk = 512
    if n <= blk:
        return jnp.cumsum(x)
    pad = (-n) % blk
    xp = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)]) if pad else x
    xb = jnp.reshape(xp, ((n + pad) // blk, blk))
    within = jnp.cumsum(xb, axis=1)
    offsets = jnp.cumsum(within[:, -1])
    offsets = jnp.concatenate([jnp.zeros((1,), x.dtype), offsets[:-1]])
    out = jnp.reshape(within + offsets[:, None], (n + pad,))
    return out[:n] if pad else out


def _segmented_scan(flags, x, kind: str):
    """Running within-segment reduction (sum/min/max) via one
    ``associative_scan`` over (segment-start flag, value) pairs — the
    standard segmented-scan operator, O(log n) passes, no sort and no
    scatter. ``run[last_row_of_segment]`` is the segment reduction."""
    if kind == "sum":
        op = jnp.add
    elif kind == "min":
        op = jnp.minimum
    else:
        op = jnp.maximum

    def comb(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, op(av, bv))

    _, run = jax.lax.associative_scan(comb, (flags, x))
    return run


class _SortedSegments:
    """Scatter-free reductions over rows sorted by a monotonic group id.

    ``starts[g]`` is the first sorted-row index of group ``g``; every
    reduction is then a cumsum difference, a boundary gather, or a
    segmented associative scan. Boundary positions come from one cheap
    single-lane sort of ``(is-not-boundary, row-index)`` packed into one
    integer (a ``searchsorted`` over the 1M-row group-id array costs ~5x
    more here: its binary-search rounds serialize, while one more narrow
    bitonic sort rides the same fast path the main sort uses)."""

    def __init__(self, changed, s_sel, group_id_sorted, num_groups,
                 max_groups: int, n: int):
        from trino_tpu.ops import keypack as KP

        g = min(max_groups + 1, n)
        pos = KP.compact_front_positions(changed, n)
        pos = pos[:g]
        if g < max_groups + 1:  # tiny batch: fewer rows than groups
            pos = jnp.concatenate(
                [pos, jnp.zeros(max_groups + 1 - g, dtype=jnp.int32)]
            )
        n_sel = jnp.sum(s_sel.astype(jnp.int32))
        live = jnp.arange(max_groups + 1, dtype=jnp.int32) < num_groups
        self.starts = jnp.where(live, pos, n_sel)
        self.sizes = self.starts[1:] - self.starts[:-1]
        self.nonempty = self.sizes > 0
        self._changed = changed
        self._gid = group_id_sorted
        self._max_groups = max_groups
        hi = max(n - 1, 0)
        self._first_idx = jnp.clip(self.starts[:-1], 0, hi)
        self._last_idx = jnp.clip(self.starts[1:] - 1, 0, hi)

    def first(self, x):
        """x gathered at each segment's first row (junk for empty segs)."""
        return x[self._first_idx]

    def sum(self, x):
        """Per-segment sum via exclusive-cumsum boundary differences.

        Exact for integers (modular wraparound cancels); floats use a
        segmented scan (the running within-segment sum read at each
        segment's last row) — a global float cumsum would accumulate
        cross-segment rounding, and a scatter ``segment_sum`` serializes
        on TPU."""
        import numpy as np

        if not np.issubdtype(np.dtype(x.dtype), np.integer):
            run = _segmented_scan(self._changed, x, "sum")
            return jnp.where(self.nonempty, run[self._last_idx], 0)
        cs = _prefix_sum(x)
        csz = jnp.concatenate([jnp.zeros((1,), x.dtype), cs])
        return csz[self.starts[1:]] - csz[self.starts[:-1]]

    def extreme(self, masked, kind: str):
        """Per-segment min/max of pre-masked values via one segmented
        associative scan (sort-free, scatter-free): the running extreme
        read at each segment's last row."""
        run = _segmented_scan(self._changed, masked, kind)
        return run[self._last_idx]

    def extreme2(self, k1, k2, kind: str):
        """Lexicographic two-lane min/max (wide DECIMAL) via one
        segmented scan over (hi, lo) pairs."""
        flags = self._changed

        def comb(a, b):
            af, ah, al = a
            bf, bh, bl = b
            a_less = (ah < bh) | ((ah == bh) & (al < bl))
            take_a = a_less if kind == "min" else ~a_less
            take_a = take_a & ~bf  # segment restart: keep b
            return (
                af | bf,
                jnp.where(take_a, ah, bh),
                jnp.where(take_a, al, bl),
            )

        _, rh, rl = jax.lax.associative_scan(comb, (flags, k1, k2))
        i = self._last_idx
        return rh[i], rl[i]


def distinct_first_mask(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    value: tuple[jnp.ndarray, jnp.ndarray],
    sel: jnp.ndarray,
) -> jnp.ndarray:
    """Mask of first occurrences of each (group keys..., value) combination
    among selected rows — the dedup pass behind DISTINCT aggregates
    (reference: ``MarkDistinctOperator.java`` / distinct accumulators).

    Sort-based: one narrow bit-packed sort of (sel, keys..., value), mark
    rows where any packed lane differs from the previous row, and restore
    original row order with a scatter-free inverse-permutation sort.
    """
    from trino_tpu.ops import keypack as KP

    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    s_lanes, perm, s_sel = KP.grouping_sort(list(keys) + [value], sel, n)
    changed = idx == 0
    for k in s_lanes:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    first_sorted = changed & s_sel
    return KP.inverse_permute_mask(perm, first_sorted)


def global_aggregate(
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
):
    """Aggregation without GROUP BY: single group, plain reductions."""
    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            results.append(jnp.sum(sel.astype(jnp.int64)))
            continue
        data, valid = pair
        use = sel if valid is None else (valid & sel)
        cnt = jnp.sum(use.astype(jnp.int64))
        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            total = lambda x: jnp.reshape(jnp.sum(x), (1,))  # noqa: E731
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(data, use, total)
            else:
                limbs = D.wide_limb_sums(data[:, 0], data[:, 1], use, total)
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(cnt)
        elif spec.kind in ("sum", "avg"):
            s = jnp.sum(jnp.where(use, data, jnp.zeros_like(data)))
            results.append((s, cnt))
        elif spec.kind in ("min", "max") and getattr(data, "ndim", 1) == 2:
            from trino_tpu.ops.decimal128 import global_minmax_wide

            bh, bl = global_minmax_wide(data[:, 0], data[:, 1], use, spec.kind)
            results.append((jnp.stack([bh, bl], axis=1), cnt))
        elif spec.kind == "min":
            results.append((jnp.min(jnp.where(use, data, _max_ident(data.dtype))), cnt))
        elif spec.kind == "max":
            results.append((jnp.max(jnp.where(use, data, _min_ident(data.dtype))), cnt))
        else:
            raise NotImplementedError(spec.kind)
    return results


def _max_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).max, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(True)
    return jnp.asarray(np.inf, dtype=dtype)


def _min_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).min, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False)
    return jnp.asarray(-np.inf, dtype=dtype)
